#!/usr/bin/env bash
# Build distributable artifacts (reference make-dist.sh role): wheel +
# sdist into dist/. Uses `python -m build` when available, falling back to
# a pip-built wheel (sdist skipped) on minimal images.
set -euo pipefail
cd "$(dirname "$0")/.."

rm -rf build dist *.egg-info
if python -c "import build" 2>/dev/null; then
    python -m build
else
    echo "python-build not installed; building wheel via pip"
    pip wheel . --no-deps -w dist
fi
echo "== dist artifacts =="
ls -l dist/
