#!/usr/bin/env bash
# Build distributable artifacts (reference make-dist.sh role): wheel +
# sdist into dist/. Uses `python -m build` when available, falling back to
# a pip-built wheel (sdist skipped) on minimal images.
set -euo pipefail
cd "$(dirname "$0")/.."

rm -rf build dist *.egg-info
# Prefer non-isolated builds when the ambient env already has setuptools —
# the isolated build env needs network access to bootstrap, which
# egress-free build hosts (like this CI) don't have. Fresh venvs without
# setuptools keep the isolated (networked) path.
ISOLATION_FLAGS=""
PIP_ISOLATION=""
if python -c "import setuptools, wheel" 2>/dev/null; then
    ISOLATION_FLAGS="--no-isolation"
    PIP_ISOLATION="--no-build-isolation"
fi
if python -c "import build" 2>/dev/null; then
    python -m build $ISOLATION_FLAGS
else
    echo "python-build not installed; building wheel via pip"
    pip wheel . --no-deps $PIP_ISOLATION -w dist
fi
echo "== dist artifacts =="
ls -l dist/
