#!/usr/bin/env python
"""Thin shim: the hot-path sync checker now lives in
``analytics_zoo_tpu.lint.passes.hot_path`` (zoolint pass
``hot-path-sync``). Kept so existing invocations and tests keep working;
prefer ``python -m analytics_zoo_tpu.lint --pass hot-path-sync``.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from analytics_zoo_tpu.lint.passes.hot_path import (  # noqa: E402,F401
    DECODE_PY, DEVICE_FEED_PY, EMBED_BODIES, EMBED_KERNEL_BODIES,
    EMBED_KERNEL_WRAPPERS, EMBED_KERNELS_PY, EMBEDDING_PY, ENGINE_PY,
    ESTIMATOR_PY, ETL_KERNELS, ETL_TASKS, FEATURESET_PY, FLEET_PY,
    HOT_FUNCS, LM_PY, MOE_BODIES, MOE_PY, PAGED_OPS, PIPELINE_BODIES,
    PIPELINE_PY, RING_BODIES, RING_PY, SERVER_PY, SLOT_OPS, _CHECKS,
    _banned_call, _check_file, _iter_functions, _scan_stmts, check, main,
    policed_functions)

if __name__ == "__main__":
    raise SystemExit(main())
