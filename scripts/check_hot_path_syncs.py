#!/usr/bin/env python
"""Tier-1 lint: no blocking host↔device sync inside the per-batch loop
bodies of Estimator's evaluate*/predict hot paths.

The async eval/predict redesign moved every per-batch ``float(...)`` /
``np.asarray(...)`` sync out of ``estimator.py``'s dispatch loops: batches
stream through the DeviceFeed, accumulation stays on device, and the pass
drains with one ``jax.device_get`` AFTER the loop (module-level ``_drain*``
helpers / ``metrics.compute_all``). A regression that reintroduces a
per-batch sync re-serializes host decode with device compute — the exact
stall this PR removed — and nothing functional breaks, so only a BENCH
round would notice. This check fails the test run at collection time
instead (``tests/test_hot_path_lint.py``).

Scope: the loop bodies of ``Estimator.evaluate``, ``_evaluate_direct``,
``_evaluate_direct_exact`` and ``predict`` in
``analytics_zoo_tpu/estimator/estimator.py``. The synchronous fallbacks in
``estimator/sync_eval.py`` are deliberately NOT policed — they exist to be
the per-batch-sync parity reference.

Banned inside those loop bodies: ``float(...)``, ``np.asarray(...)`` /
``numpy.asarray(...)``, ``jax.device_get(...)``, ``.block_until_ready()``.
Post-loop drains and helpers called FROM the loop (``fetch`` behind the
predict window) are fine — the lint looks at the literal loop body, which
is also the honest boundary: a helper fetching K dispatches behind the
frontier is pipelining, an inline sync is a stall.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

HOT_FUNCS = ("evaluate", "_evaluate_direct", "_evaluate_direct_exact",
             "predict")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ESTIMATOR_PY = os.path.join(_REPO, "analytics_zoo_tpu", "estimator",
                            "estimator.py")


def _banned_call(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name) and f.id == "float":
        return "float()"
    if isinstance(f, ast.Attribute):
        base = f.value
        if (f.attr == "asarray" and isinstance(base, ast.Name)
                and base.id in ("np", "numpy")):
            return f"{base.id}.asarray()"
        if (f.attr == "device_get" and isinstance(base, ast.Name)
                and base.id == "jax"):
            return "jax.device_get()"
        if f.attr == "block_until_ready":
            return ".block_until_ready()"
    return ""


def check(path: str = ESTIMATOR_PY) -> List[Tuple[str, int, str]]:
    """Return (function, line, what) violations; empty means clean."""
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    violations: List[Tuple[str, int, str]] = []
    for cls in ast.walk(tree):
        if not (isinstance(cls, ast.ClassDef) and cls.name == "Estimator"):
            continue
        for fn in cls.body:
            if not (isinstance(fn, ast.FunctionDef)
                    and fn.name in HOT_FUNCS):
                continue
            for loop in ast.walk(fn):
                if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
                    continue
                for stmt in loop.body + loop.orelse:
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.Call):
                            what = _banned_call(sub)
                            if what:
                                violations.append(
                                    (fn.name, sub.lineno, what))
    return violations


def main() -> int:
    violations = check()
    if not violations:
        print("hot-path sync lint: clean")
        return 0
    for fn, line, what in violations:
        print(f"{ESTIMATOR_PY}:{line}: blocking {what} inside the per-batch "
              f"loop body of Estimator.{fn} — route the sync behind the "
              f"dispatch frontier or drain after the loop", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
