#!/usr/bin/env python
"""Tier-1 lint: the data-plane and eval/predict hot paths must stay free of
per-batch host↔device syncs and per-batch/per-record Python regressions.

Three families of policed regressions, each of which re-serializes work the
async redesigns deliberately overlapped — nothing functional breaks when
they creep back in, so only a BENCH round would notice. This check fails
the test run at collection time instead (``tests/test_hot_path_lint.py``).

1. **Estimator dispatch loops** (``analytics_zoo_tpu/estimator/
   estimator.py``: ``evaluate``/``_evaluate_direct``/
   ``_evaluate_direct_exact``/``predict`` loop bodies): no blocking
   ``float(...)``, ``np.asarray(...)``, ``jax.device_get(...)``,
   ``.block_until_ready()`` — batches stream through the DeviceFeed,
   accumulation stays on device, the pass drains once after the loop.
   The synchronous fallbacks in ``estimator/sync_eval.py`` are
   deliberately NOT policed — they exist to be the parity reference.

2. **FeatureSet batch staging** (``feature/featureset.py``):
   ``FeatureSet._gather`` is the innermost per-batch hot function — no
   device syncs, no per-record Python loops (it must stay a pure tree-map
   of vectorized ``np.take`` gathers), and no ``np.asarray`` copies (the
   zero-alloc redesign routes copies through ``np.take(..., out=...)``).
   The lazy data plane's iterator cores are policed for device syncs too.

3. **DeviceFeed eval adaptation** (``feature/device_feed.py``):
   ``masked_eval_batches`` must not rebuild its ``np.arange`` mask per
   batch (cached-mask fix), and the ``_produce`` producer loop must never
   sync.

4. **Sharded-embedding exchange bodies** (``parallel/embedding.py``:
   ``_routing``/``_lookup_body``/``_lookup_bwd_body``/``_update_body``,
   the shard_map-traced lookup/grad/update path): no host syncs, no
   per-row Python loops (everything stays a vectorized
   unique/all-to-all/segment-sum pipeline), and no ``one_hot`` calls —
   a one-hot matmul densifies the [vocab, dim] gradient the segment-sum
   backward exists to avoid. The ``one_hot`` ban applies to every
   policed function above, not just the embedding bodies.

5. **Generative decode step loop** (continuous-batching scheduler): the
   slot-cache ops (``ops/decode.py``: ``init_slot_cache``/``slot_join``/
   ``slot_evict``/``slot_insert``/``slot_attention``) and the
   scheduler's device hot path (``serving/server.py GenerativeServing``:
   ``_dispatch_step``/``_insert_request_device``/``_evict_slots``) must
   stay pure vectorized jitted dispatches — no host syncs, no per-slot
   Python loops, no per-token shape changes (a recompile per token is
   the regression the fixed-shape slot cache exists to prevent). The
   ``TransformerLM`` step fns (``capture/lm.py``: ``slot_step``/
   ``prefill_kv``) are policed for syncs only — their per-BLOCK loop is
   constant-trip tracing, not per-record work. The scheduler's single
   host fetch per step lives in the deliberately-unpoliced
   ``_fetch_tokens``.

6. **Paged KV + speculative decode bodies**: the page gather/scatter ops
   (``ops/decode.py``: ``init_paged_pool``/``page_table_set``/
   ``page_table_clear``/``page_copy``/``_page_positions``/
   ``_paged_write``/``paged_gather``/``paged_insert``/``paged_attention``/
   ``paged_verify_attention`` and the speculative accept rules
   ``spec_accept_greedy``/``_spec_accept_sampled``) must stay pure
   vectorized advanced-indexing scatters/gathers — no host syncs, no
   per-PAGE Python loops (a loop over table columns re-serializes the
   gather the pool exists to batch), no ``one_hot`` densification of
   page ids. The ``TransformerLM`` draft/verify step fns
   (``capture/lm.py``: ``paged_slot_step``/``verify_step``/
   ``prefill_kv_suffix``) and the scheduler's paged device methods
   (``serving/server.py``: ``_insert_request_paged``/
   ``_insert_request_spec``/``_insert_suffix_paged``/
   ``_copy_page_device``) are policed like their contiguous twins —
   syncs banned everywhere, with the constant-trip per-BLOCK loop
   exemption for the lm step fns only.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ESTIMATOR_PY = os.path.join(_REPO, "analytics_zoo_tpu", "estimator",
                            "estimator.py")
FEATURESET_PY = os.path.join(_REPO, "analytics_zoo_tpu", "feature",
                             "featureset.py")
DEVICE_FEED_PY = os.path.join(_REPO, "analytics_zoo_tpu", "feature",
                              "device_feed.py")
EMBEDDING_PY = os.path.join(_REPO, "analytics_zoo_tpu", "parallel",
                            "embedding.py")
DECODE_PY = os.path.join(_REPO, "analytics_zoo_tpu", "ops", "decode.py")
LM_PY = os.path.join(_REPO, "analytics_zoo_tpu", "capture", "lm.py")
SERVER_PY = os.path.join(_REPO, "analytics_zoo_tpu", "serving", "server.py")

EMBED_BODIES = ("_routing", "_lookup_body", "_lookup_bwd_body",
                "_update_body")

SLOT_OPS = ("init_slot_cache", "slot_join", "slot_evict", "slot_insert",
            "slot_attention")

PAGED_OPS = ("init_paged_pool", "page_table_set", "page_table_clear",
             "page_copy", "_page_positions", "_paged_write", "paged_gather",
             "paged_insert", "paged_attention", "paged_verify_attention",
             "spec_accept_greedy", "_spec_accept_sampled")

HOT_FUNCS = ("evaluate", "_evaluate_direct", "_evaluate_direct_exact",
             "predict")

#: policy rows: (path, class name or None for module level, function names,
#: extra banned np.<attr> calls, ban per-record loops?, scope)
#: scope "loops" = only loop bodies inside the function are policed;
#: scope "body"  = the whole function body is policed (innermost hot funcs)
_CHECKS: List[Tuple[str, Optional[str], Sequence[str], Sequence[str],
                    bool, str]] = [
    (ESTIMATOR_PY, "Estimator", HOT_FUNCS, (), False, "loops"),
    (FEATURESET_PY, "FeatureSet", ("_gather",), ("asarray",), True, "body"),
    (FEATURESET_PY, "LazyTransformFeatureSet",
     ("train_iterator", "eval_iterator", "_transformed_batches",
      "_cached_batches"), (), False, "loops"),
    (DEVICE_FEED_PY, None, ("masked_eval_batches",), ("arange",), False,
     "loops"),
    (DEVICE_FEED_PY, None, ("_produce",), (), False, "loops"),
    (EMBEDDING_PY, None, EMBED_BODIES, (), True, "body"),
    (DECODE_PY, None, SLOT_OPS, (), True, "body"),
    (DECODE_PY, None, PAGED_OPS, (), True, "body"),
    (LM_PY, "TransformerLM",
     ("slot_step", "prefill_kv", "paged_slot_step", "verify_step",
      "prefill_kv_suffix"), (), False, "body"),
    (SERVER_PY, "GenerativeServing",
     ("_dispatch_step", "_insert_request_device", "_insert_request_paged",
      "_insert_request_spec", "_insert_suffix_paged", "_copy_page_device",
      "_evict_slots"), (), True, "body"),
]


def _banned_call(node: ast.Call, np_attrs: Sequence[str] = ("asarray",)
                 ) -> str:
    f = node.func
    if isinstance(f, ast.Name) and f.id == "float":
        return "float()"
    if isinstance(f, ast.Name) and f.id == "one_hot":
        return "one_hot()"
    if isinstance(f, ast.Attribute):
        if f.attr == "one_hot":
            return "one_hot()"
        base = f.value
        if (f.attr in np_attrs and isinstance(base, ast.Name)
                and base.id in ("np", "numpy")):
            return f"{base.id}.{f.attr}()"
        if (f.attr == "device_get" and isinstance(base, ast.Name)
                and base.id == "jax"):
            return "jax.device_get()"
        if f.attr == "block_until_ready":
            return ".block_until_ready()"
    return ""


def _iter_functions(tree: ast.Module, cls: Optional[str],
                    names: Sequence[str]):
    if cls is None:
        for node in tree.body:
            if isinstance(node, ast.FunctionDef) and node.name in names:
                yield node
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls:
            for fn in node.body:
                if isinstance(fn, ast.FunctionDef) and fn.name in names:
                    yield fn


def _scan_stmts(stmts, np_attrs, out, fn_name):
    for stmt in stmts:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call):
                what = _banned_call(sub, np_attrs)
                if what:
                    out.append((fn_name, sub.lineno, what))


def _check_file(path: str, cls: Optional[str], names: Sequence[str],
                extra_np: Sequence[str], ban_loops: bool, scope: str
                ) -> List[Tuple[str, int, str]]:
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    np_attrs = ("asarray",) + tuple(extra_np)
    violations: List[Tuple[str, int, str]] = []
    for fn in _iter_functions(tree, cls, names):
        if scope == "body":
            _scan_stmts(fn.body, np_attrs, violations, fn.name)
            if ban_loops:
                for sub in ast.walk(fn):
                    if isinstance(sub, (ast.For, ast.While, ast.AsyncFor,
                                        ast.ListComp, ast.SetComp,
                                        ast.DictComp, ast.GeneratorExp)):
                        violations.append(
                            (fn.name, sub.lineno, "per-record Python loop"))
            continue
        for loop in ast.walk(fn):
            if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
                continue
            _scan_stmts(loop.body + loop.orelse, np_attrs, violations,
                        fn.name)
    return violations


def check(path: Optional[str] = None
          ) -> List[Tuple[str, str, int, str]]:
    """Return ``(file, function, line, what)`` violations; empty = clean.
    With an explicit ``path`` only the Estimator dispatch-loop policy runs
    against that file (self-test hook)."""
    if path is not None:
        return [(path, fn, line, what) for fn, line, what in
                _check_file(path, "Estimator", HOT_FUNCS, (), False,
                            "loops")]
    out: List[Tuple[str, str, int, str]] = []
    for (p, cls, names, extra_np, ban_loops, scope) in _CHECKS:
        out.extend((p, fn, line, what) for fn, line, what in
                   _check_file(p, cls, names, extra_np, ban_loops, scope))
    return out


def main() -> int:
    violations = check()
    if not violations:
        print("hot-path sync lint: clean")
        return 0
    for path, fn, line, what in violations:
        print(f"{path}:{line}: {what} inside the hot path of {fn} — "
              f"route syncs behind the dispatch frontier / drain after "
              f"the loop, and keep per-batch staging vectorized",
              file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
