#!/usr/bin/env python
"""Tier-1 lint: the metrics registry's names must stay canonical.

The telemetry plane (``analytics_zoo_tpu/common/metrics.py``) only stays
queryable if names don't rot: a metric registered twice makes dashboards
ambiguous, an off-convention name breaks every ``subsystem.*`` query, and
an undocumented metric is invisible to whoever writes the alerts. Mirrors
``check_fault_sites.py``: fails the test run at collection time
(``tests/test_metric_names_lint.py``) when any of the following drifts:

1. every registration call (``metrics.counter(...)`` / ``.gauge(...)`` /
   ``.histogram(...)`` on a metrics-module alias) passes a string LITERAL
   name (a computed name defeats both this lint and grep);
2. every metric name is registered exactly ONCE across the codebase — one
   name, one owning module (re-registration elsewhere would silently
   alias series);
3. names follow the ``subsystem.noun_unit`` convention
   (lower_snake, one dot), counters end in ``_total``, histograms in
   ``_seconds`` (all our histograms observe durations), and gauges carry
   a unit suffix (``_seconds``/``_bytes``/``_ratio``/``_depth``) unless
   allow-listed as genuinely unitless (``serving.in_flight`` counts,
   ``build.info`` is an info-style constant-1 gauge);
4. every registered metric is documented in ``docs/observability.md``
   (the metric table is the operator's scrape vocabulary).
"""
from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_REPO, "analytics_zoo_tpu")
_DOCS = os.path.join(_REPO, "docs", "observability.md")

#: files scanned for registration calls: the whole package + the bench
#: driver; common/metrics.py itself is excluded (its internal plumbing
#: calls the same method names on ``self``/fresh registries)
_SCAN_ROOTS = (_PKG, os.path.join(_REPO, "bench.py"))
_EXCLUDE = (os.path.join("common", "metrics.py"),)

_KINDS = ("counter", "gauge", "histogram")
_NAME_RE = re.compile(r"^[a-z][a-z0-9]*\.[a-z][a-z0-9_]*$")
_UNIT_SUFFIX = {"counter": "_total", "histogram": "_seconds"}

#: gauges must say what they measure; any of these suffixes qualifies
_GAUGE_UNIT_SUFFIXES = ("_seconds", "_bytes", "_ratio", "_depth")
#: gauges that are genuinely unitless: live request/slot counts and the
#: info-style constant-1 build gauge (labels carry the payload)
_GAUGE_UNITLESS_OK = {"serving.in_flight", "serving.slots_occupied",
                      "serving.kv_pages_free", "build.info"}


def _is_registration(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr in _KINDS
            and isinstance(f.value, ast.Name)
            and (f.value.id == "metrics" or f.value.id.endswith("_metrics")))


def registrations() -> Tuple[Dict[str, List[Tuple[str, str]]],
                             List[Tuple[str, int, str]]]:
    """``{name: [(file:line, kind), ...]}`` over all scanned files, plus
    violations for non-literal name arguments."""
    regs: Dict[str, List[Tuple[str, str]]] = {}
    bad: List[Tuple[str, int, str]] = []
    files: List[str] = []
    for root in _SCAN_ROOTS:
        if os.path.isfile(root):
            files.append(root)
            continue
        for dirpath, _dirs, names in os.walk(root):
            if "__pycache__" in dirpath:
                continue
            files.extend(os.path.join(dirpath, n) for n in names
                         if n.endswith(".py"))
    for path in sorted(files):
        rel = os.path.relpath(path, _REPO)
        if any(rel.endswith(e) for e in _EXCLUDE):
            continue
        with open(path) as fh:
            tree = ast.parse(fh.read(), filename=path)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and _is_registration(node)):
                continue
            where = f"{rel}:{node.lineno}"
            if (not node.args
                    or not isinstance(node.args[0], ast.Constant)
                    or not isinstance(node.args[0].value, str)):
                bad.append((path, node.lineno,
                            "metric name must be one string literal"))
                continue
            regs.setdefault(node.args[0].value, []).append(
                (where, node.func.attr))
    return regs, bad


def undocumented(names) -> List[str]:
    """Registered names with no `` `name` `` mention in the metric docs."""
    try:
        with open(_DOCS) as fh:
            text = fh.read()
    except OSError:
        return sorted(names)
    return sorted(n for n in names if f"`{n}`" not in text)


def check() -> List[str]:
    """Human-readable violations; empty = clean."""
    regs, bad = registrations()
    problems = [f"{os.path.relpath(p, _REPO)}:{line}: {what}"
                for p, line, what in bad]
    for name, places in sorted(regs.items()):
        if len(places) > 1:
            problems.append(
                f"metric {name!r} registered at {len(places)} sites "
                f"({', '.join(w for w, _ in places)}); each name must be "
                f"registered exactly once")
        kind = places[0][1]
        if not _NAME_RE.match(name):
            problems.append(
                f"metric {name!r} ({places[0][0]}) breaks the "
                f"'subsystem.noun_unit' convention (lower_snake, one dot)")
        suffix = _UNIT_SUFFIX.get(kind)
        if suffix and not name.endswith(suffix):
            problems.append(
                f"{kind} {name!r} ({places[0][0]}) must end in "
                f"'{suffix}'")
        if (kind == "gauge" and name not in _GAUGE_UNITLESS_OK
                and not name.endswith(_GAUGE_UNIT_SUFFIXES)):
            problems.append(
                f"gauge {name!r} ({places[0][0]}) must end in one of "
                f"{'/'.join(_GAUGE_UNIT_SUFFIXES)} or be allow-listed in "
                f"_GAUGE_UNITLESS_OK")
    for name in undocumented(regs):
        problems.append(
            f"metric {name!r} is registered but undocumented — add a row "
            f"to the metric table in docs/observability.md")
    return problems


def main() -> int:
    problems = check()
    if not problems:
        print(f"metric-name lint: clean ({len(registrations()[0])} metrics,"
              f" all literal, unique, canonical and documented)")
        return 0
    for p in problems:
        print(p, file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
