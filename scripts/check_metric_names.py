#!/usr/bin/env python
"""Thin shim: the metric-name checker now lives in
``analytics_zoo_tpu.lint.passes.metric_names`` (zoolint pass
``metric-names``). Kept so existing invocations and tests keep working;
prefer ``python -m analytics_zoo_tpu.lint --pass metric-names``.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from analytics_zoo_tpu.lint.passes.metric_names import (  # noqa: E402,F401
    _EXCLUDE, _GAUGE_UNIT_SUFFIXES, _GAUGE_UNITLESS_OK, _KINDS, _NAME_RE,
    _UNIT_SUFFIX, _is_registration, check, findings, main, registrations,
    undocumented)

if __name__ == "__main__":
    raise SystemExit(main())
