#!/usr/bin/env python
"""Tier-1 lint: the fault-injection site registry and the ``faults.inject``
call sites must stay in bijection, and every site must be exercised.

Chaos coverage rots silently: an injection site that no test arms is dead
code wearing a safety vest, and a registry row whose call site was
refactored away advertises protection that no longer exists. This check
fails the test run at collection time (``tests/test_fault_sites_lint.py``)
when any of the following drifts:

1. every ``faults.inject(...)`` call passes a string LITERAL (a computed
   site name defeats both this lint and grep);
2. every injected site name is registered in
   ``analytics_zoo_tpu/common/faults.py``'s ``REGISTRY``;
3. site names are UNIQUE across call sites — one site, one place (a name
   shared by two call sites makes budgets/schedules ambiguous);
4. every REGISTRY row has a live call site (no stale advertising);
5. every site name appears in at least one file under ``tests/`` — i.e.
   some test arms or asserts on it;
6. every registered site is documented in ``docs/faults.md`` (the site
   table is the operator's chaos-plan vocabulary — an undocumented site
   is invisible to whoever writes ``faults.plan`` schedules).
"""
from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Set, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_REPO, "analytics_zoo_tpu")
_FAULTS_PY = os.path.join(_PKG, "common", "faults.py")
_TESTS_DIR = os.path.join(_REPO, "tests")

#: files scanned for inject() calls: the whole package + the bench driver
_SCAN_ROOTS = (_PKG, os.path.join(_REPO, "bench.py"))


def registry_sites(path: str = _FAULTS_PY) -> Set[str]:
    """Site names from the REGISTRY dict literal (AST parse — no package
    import, so the lint runs without jax in a bare interpreter)."""
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    for node in tree.body:
        target = None
        if isinstance(node, ast.AnnAssign):
            target, value = node.target, node.value
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        if (isinstance(target, ast.Name) and target.id == "REGISTRY"
                and isinstance(value, ast.Dict)):
            keys = set()
            for k in value.keys:
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    raise AssertionError(
                        f"{path}: REGISTRY keys must be string literals")
            return {k.value for k in value.keys}
    raise AssertionError(f"{path}: no REGISTRY dict literal found")


def _is_inject_call(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "inject"
            and isinstance(f.value, ast.Name) and f.value.id == "faults")


def inject_sites() -> Tuple[Dict[str, List[str]], List[Tuple[str, int, str]]]:
    """``{site: [file:line, ...]}`` over all scanned files, plus
    violations for non-literal site arguments."""
    calls: Dict[str, List[str]] = {}
    bad: List[Tuple[str, int, str]] = []
    files: List[str] = []
    for root in _SCAN_ROOTS:
        if os.path.isfile(root):
            files.append(root)
            continue
        for dirpath, _dirs, names in os.walk(root):
            if "__pycache__" in dirpath:
                continue
            files.extend(os.path.join(dirpath, n) for n in names
                         if n.endswith(".py"))
    for path in sorted(files):
        with open(path) as fh:
            tree = ast.parse(fh.read(), filename=path)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and _is_inject_call(node)):
                continue
            where = f"{os.path.relpath(path, _REPO)}:{node.lineno}"
            if (len(node.args) != 1
                    or not isinstance(node.args[0], ast.Constant)
                    or not isinstance(node.args[0].value, str)):
                bad.append((path, node.lineno,
                            "faults.inject() site must be one string "
                            "literal"))
                continue
            calls.setdefault(node.args[0].value, []).append(where)
    return calls, bad


def tests_mentioning(site: str) -> List[str]:
    out = []
    for name in sorted(os.listdir(_TESTS_DIR)):
        if not name.endswith(".py"):
            continue
        with open(os.path.join(_TESTS_DIR, name)) as fh:
            if site in fh.read():
                out.append(name)
    return out


_DOCS_FAULTS = os.path.join(_REPO, "docs", "faults.md")


def undocumented_sites(registered: Set[str]) -> List[str]:
    """Registered sites with no `` `site` `` mention in docs/faults.md."""
    try:
        with open(_DOCS_FAULTS) as fh:
            text = fh.read()
    except OSError:
        return sorted(registered)
    return sorted(s for s in registered if f"`{s}`" not in text)


def check() -> List[str]:
    """Human-readable violations; empty = clean."""
    registered = registry_sites()
    calls, bad = inject_sites()
    problems = [f"{os.path.relpath(p, _REPO)}:{line}: {what}"
                for p, line, what in bad]
    for site, places in sorted(calls.items()):
        if site not in registered:
            problems.append(
                f"site {site!r} injected at {places[0]} but not registered "
                f"in common/faults.py REGISTRY")
        if len(places) > 1:
            problems.append(
                f"site {site!r} injected from {len(places)} call sites "
                f"({', '.join(places)}); site names must be unique")
        if not tests_mentioning(site):
            problems.append(
                f"site {site!r} is not exercised by any test under tests/ "
                f"(arm it in a chaos test or drop the site)")
    for site in sorted(registered - set(calls)):
        problems.append(
            f"REGISTRY advertises site {site!r} but no faults.inject("
            f"{site!r}) call exists in the codebase")
    for site in undocumented_sites(registered):
        problems.append(
            f"site {site!r} is registered but undocumented — add a row to "
            f"the site table in docs/faults.md")
    return problems


def main() -> int:
    problems = check()
    if not problems:
        print(f"fault-site lint: clean "
              f"({len(registry_sites())} sites, all registered, unique, "
              f"test-exercised and documented)")
        return 0
    for p in problems:
        print(p, file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
