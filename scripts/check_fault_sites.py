#!/usr/bin/env python
"""Thin shim: the fault-site checker now lives in
``analytics_zoo_tpu.lint.passes.fault_sites`` (zoolint pass
``fault-sites``). Kept so existing invocations and tests keep working;
prefer ``python -m analytics_zoo_tpu.lint --pass fault-sites``.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from analytics_zoo_tpu.lint.passes.fault_sites import (  # noqa: E402,F401
    _is_inject_call, check, findings, inject_sites, main, registry_sites,
    tests_mentioning, undocumented_sites)

if __name__ == "__main__":
    raise SystemExit(main())
