"""8→64-chip scaling projection from the COMPILED 8-way programs.

Method (the honest substitute for pod hardware this environment lacks):

1. jit the real data-parallel train step over an 8-device mesh (CPU
   simulation — the HLO collectives are identical to the TPU lowering for
   the same shardings) and read every ``all-reduce`` instruction's tensor
   bytes out of the optimized module: that is the per-step collective
   payload B.
2. Per-chip compute time T_c comes from the measured single-chip bench
   (BENCH_r04: differenced device step times).
3. α-β ring model on v5e ICI: a bidirectional ring all-reduce of B bytes
   over n chips moves 2·B·(n−1)/n per chip; with the 2D torus both axes
   carry traffic, so the effective per-chip ICI bandwidth is
   W = links_used · per-link bandwidth. Published v5e figures used:
   45 GB/s unidirectional per link, 2 links usable per all-reduce
   direction (2D torus axes), α = 1 µs per hop.
4. Efficiency bounds: XLA overlaps the grad all-reduce with backward
   compute where dependencies allow —
     no-overlap (pessimistic):  eff = T_c / (T_c + T_ar(n))
     full-overlap (optimistic): eff = T_c / max(T_c, T_ar(n))
   Real systems land between; DP grad reduction overlaps well in
   practice (the reduce of layer i's grads runs during layer i−1's
   backward), so the truth sits near the optimistic bound.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     JAX_PLATFORMS=cpu python scripts/scaling_projection.py
"""
import re
import sys

sys.path.insert(0, ".")

import jax

jax.config.update("jax_platforms", "cpu")  # sitecustomize pre-sets axon

import numpy as np

DT_BYTES = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "pred": 1,
            "f64": 8, "s64": 8}

# v5e ICI assumptions (public "How to Scale Your Model" figures)
W_LINK = 4.5e10          # bytes/s unidirectional per ICI link
LINKS_PER_AR = 2         # 2D torus: both axes carry ring traffic
ALPHA = 1e-6             # per-hop latency seconds
W_EFF = W_LINK * LINKS_PER_AR


def collective_bytes(compiled) -> int:
    """Sum payload bytes over every all-reduce/reduce-scatter/all-gather
    in the optimized HLO."""
    txt = compiled.as_text()
    total = 0
    ops = ("all-reduce(", "all-reduce-start(", "reduce-scatter(",
           "all-gather(")
    for line in txt.splitlines():
        if " = " not in line:
            continue
        seg = line.split(" = ", 1)[1]
        hit = next((op for op in ops if op in seg), None)
        if hit is None:
            continue
        shape_part = seg.split(hit)[0]  # tuple or single shape before opcode
        for m in re.finditer(r"(\w+)\[([0-9,]*)\]", shape_part):
            dt, dims = m.groups()
            if dt not in DT_BYTES:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            total += n * DT_BYTES[dt]
    return total


def ar_time(bytes_, n):
    """Bidirectional ring all-reduce over n chips."""
    return 2.0 * bytes_ * (n - 1) / n / W_EFF + ALPHA * (n - 1)


def project(name, bytes_, step_s, chips=(8, 16, 32, 64)):
    print(f"\n## {name}: collective payload {bytes_/1e6:.1f} MB/step, "
          f"per-chip step {step_s*1e3:.1f} ms")
    print("| chips | all-reduce ms | eff (no overlap) | eff (overlapped) |")
    print("|---|---|---|---|")
    rows = []
    for n in chips:
        t = ar_time(bytes_, n)
        e_no = step_s / (step_s + t)
        e_ov = step_s / max(step_s, t)
        rows.append((n, t, e_no, e_ov))
        print(f"| {n} | {t*1e3:.2f} | {e_no*100:.1f}% | {e_ov*100:.1f}% |")
    return rows


def build_resnet_step():
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.keras import objectives, optimizers
    from analytics_zoo_tpu.models.image.imageclassification import resnet
    from analytics_zoo_tpu.parallel.mesh import shard_batch

    init_tpu_context()
    model = resnet(50, num_classes=2, input_shape=(224, 224, 3))
    est = Estimator(model=model,
                    loss_fn=objectives.get("sparse_categorical_crossentropy"),
                    optimizer=optimizers.SGD(0.1, momentum=0.9),
                    compute_dtype=jnp.bfloat16)
    rs = np.random.RandomState(0)
    x = rs.rand(8, 224, 224, 3).astype(np.float32)  # batch size is
    y = rs.randint(0, 2, 8).astype(np.float32)      # irrelevant to grads
    bx, by = shard_batch(est.mesh, (x, y))
    est._ensure_initialized(bx)
    step = est._build_train_step()
    return step.lower(est.params, est.opt_state, est.model_state,
                      __import__("jax").random.PRNGKey(0), bx, by).compile()


def build_bert_step():
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.capture.text import BERTClassifier, bert_input_pack
    from analytics_zoo_tpu.parallel.mesh import shard_batch

    cfg = dict(vocab=30522, hidden_size=768, n_block=12, n_head=12,
               max_position_len=512, intermediate_size=3072,
               compute_dtype=jnp.bfloat16)
    clf = BERTClassifier(2, bert_config=cfg)
    est = clf.model.get_estimator()
    rs = np.random.RandomState(0)
    x = bert_input_pack(rs.randint(1, 30000, (8, 128)))
    y = rs.randint(0, 2, 8).astype(np.float32)
    bx, by = shard_batch(est.mesh, (x, y))
    est._ensure_initialized(bx)
    step = est._build_train_step()
    return step.lower(est.params, est.opt_state, est.model_state,
                      jax.random.PRNGKey(0), bx, by).compile()


def main():
    import jax
    assert jax.device_count() >= 8, "run with 8 simulated devices"
    print("devices:", jax.device_count(), jax.devices()[0].platform)

    resnet_c = build_resnet_step()
    b = collective_bytes(resnet_c)
    # measured single-chip step (BENCH_r04 differenced): 95.4 ms @ b256
    project("ResNet-50 b256/chip DP", b, 0.0954)

    bert_c = build_bert_step()
    b2 = collective_bytes(bert_c)
    # measured: 105.4 ms @ b128 s128
    project("BERT-base b128/chip DP", b2, 0.1054)


if __name__ == "__main__":
    main()
