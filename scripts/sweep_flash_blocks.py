"""Tuning harness: measure flash-attention fwd+bwd step time on the real
chip across block sizes (run manually; results inform DEFAULT_*_BLOCK)."""
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")
from analytics_zoo_tpu.ops.attention import flash_attention  # noqa: E402

B, H, S, D = 4, 8, 4096, 64
STEPS = 20


def timed_once(fn, *args):
    t0 = time.perf_counter()
    float(fn(*args))
    return time.perf_counter() - t0


def measure(q_block, kv_block, causal=True):
    rs = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rs.randn(B, H, S, D).astype(np.float32),
                           jnp.bfloat16) for _ in range(3))

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       q_block=q_block, kv_block=kv_block
                                       ).astype(jnp.float32))

    grad_fn = jax.grad(loss, argnums=(0, 1, 2))

    def chained(q, k, v, eps, n):
        def body(carry, _):
            cq, ck, cv = carry
            dq, dk, dv = grad_fn(cq, ck, cv)
            return (cq + eps * dq, ck + eps * dk, cv + eps * dv), ()
        (q, k, v), _ = jax.lax.scan(body, (q, k, v), None, length=n)
        return jnp.sum(q.astype(jnp.float32))

    eps = jnp.bfloat16(0.0)
    # difference two scan lengths: t(2N) - t(N) = N steps of pure device
    # time, with the (noisy, 0.1-2s) tunnel dispatch latency cancelled
    c1 = jax.jit(lambda q, k, v, e: chained(q, k, v, e, STEPS)
                 ).lower(q, k, v, eps).compile()
    c2 = jax.jit(lambda q, k, v, e: chained(q, k, v, e, 2 * STEPS)
                 ).lower(q, k, v, eps).compile()
    float(c1(q, k, v, eps)); float(c2(q, k, v, eps))  # warm
    t1 = min(timed_once(c1, q, k, v, eps) for _ in range(3))
    t2 = min(timed_once(c2, q, k, v, eps) for _ in range(3))
    elapsed = max(t2 - t1, 1e-9)
    flops = 9 * B * H * S * S * D  # 9 causal-halved matmuls/step (bench.py)
    mfu = flops * STEPS / elapsed / 197e12
    per_step_ms = elapsed / STEPS * 1e3
    print(f"bq={q_block:5d} bk={kv_block:5d} step={per_step_ms:7.3f} ms "
          f"mfu={mfu:.3f}", flush=True)
    return mfu


if __name__ == "__main__":
    combos = [(512, 512), (256, 512), (512, 1024), (1024, 512),
              (1024, 1024), (256, 1024), (2048, 512), (512, 2048),
              (128, 1024), (1024, 128)]
    if len(sys.argv) > 1:
        combos = [tuple(map(int, a.split("x"))) for a in sys.argv[1:]]
    for bq, bk in combos:
        try:
            measure(bq, bk)
        except Exception as e:
            print(f"bq={bq} bk={bk} FAILED: {repr(e)[:200]}", flush=True)
