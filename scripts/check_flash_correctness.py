"""Numerical check of the pallas flash kernels against reference attention
on the attached TPU (CI covers the CPU fallback; this exercises the real
kernels). Run manually after kernel changes."""
import sys

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, ".")
from analytics_zoo_tpu.ops.attention import (  # noqa: E402
    dot_product_attention, flash_attention, flash_attention_lse)


def check(name, got, want, tol):
    got = np.asarray(got, np.float32)
    want = np.asarray(want, np.float32)
    err = np.max(np.abs(got - want)) / max(np.max(np.abs(want)), 1e-6)
    status = "OK " if err < tol else "FAIL"
    print(f"{status} {name}: rel_err={err:.2e} (tol {tol})")
    return err < tol


def main():
    rs = np.random.RandomState(0)
    ok = True
    for causal in (False, True):
        for (b, h, s, d) in [(2, 4, 512, 64), (1, 2, 1024, 128)]:
            q, k, v = (jnp.asarray(rs.randn(b, h, s, d) * 0.5, jnp.bfloat16)
                       for _ in range(3))

            ref_out = dot_product_attention(
                q.astype(jnp.float32), k.astype(jnp.float32),
                v.astype(jnp.float32), causal=causal)
            got = flash_attention(q, k, v, causal=causal, q_block=256,
                                  kv_block=256)
            ok &= check(f"fwd c={causal} s={s} d={d}", got, ref_out, 2e-2)

            def loss_flash(q, k, v):
                return jnp.sum(flash_attention(
                    q, k, v, causal=causal, q_block=256, kv_block=256
                ).astype(jnp.float32) * 0.01)

            def loss_ref(q, k, v):
                return jnp.sum(dot_product_attention(
                    q.astype(jnp.float32), k.astype(jnp.float32),
                    v.astype(jnp.float32), causal=causal) * 0.01)

            g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
            g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
            for nm, gf, gr in zip("dq dk dv".split(), g_flash, g_ref):
                ok &= check(f"{nm}  c={causal} s={s} d={d}", gf, gr, 4e-2)

    # key-bias path
    b, h, s, d = 2, 2, 512, 64
    q, k, v = (jnp.asarray(rs.randn(b, h, s, d) * 0.5, jnp.bfloat16)
               for _ in range(3))
    kb = jnp.asarray(np.where(rs.rand(b, s) > 0.2, 0.0, -1e9), jnp.float32)
    ref = dot_product_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                                v.astype(jnp.float32),
                                bias=kb[:, None, None, :])
    got = flash_attention(q, k, v, bias=kb[:, None, None, :])
    ok &= check("fwd key_bias", got, ref, 2e-2)

    # lse path + merge identity
    out, lse = flash_attention_lse(q, k, v, causal=True)
    ref = dot_product_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                                v.astype(jnp.float32), causal=True)
    ok &= check("lse fwd", out, ref, 2e-2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) / np.sqrt(d)
    qi = jnp.arange(s)[:, None]
    kj = jnp.arange(s)[None, :]
    scores = jnp.where(qi >= kj, scores, -1e30)
    ref_lse = jax.scipy.special.logsumexp(scores, axis=-1)
    ok &= check("lse values", lse, ref_lse, 2e-2)

    # lse cotangent flows through the bwd kernels
    def loss_lse(q, k, v):
        out, lse = flash_attention_lse(q, k, v, causal=True)
        return jnp.sum(out.astype(jnp.float32)) * 0.01 + jnp.sum(lse) * 0.001

    def loss_lse_ref(q, k, v):
        qf, kf, vf = (t.astype(jnp.float32) for t in (q, k, v))
        out = dot_product_attention(qf, kf, vf, causal=True)
        scores = jnp.einsum("bhqd,bhkd->bhqk", qf, kf) / np.sqrt(d)
        m = jnp.where(jnp.arange(s)[:, None] >= jnp.arange(s)[None, :],
                      scores, -1e30)
        lse = jax.scipy.special.logsumexp(m, axis=-1)
        return jnp.sum(out) * 0.01 + jnp.sum(lse) * 0.001

    gl = jax.grad(loss_lse, argnums=(0, 1, 2))(q, k, v)
    glr = jax.grad(loss_lse_ref, argnums=(0, 1, 2))(q, k, v)
    for nm, gf, gr in zip("dq dk dv".split(), gl, glr):
        ok &= check(f"lse-cotangent {nm}", gf, gr, 4e-2)

    ok &= check_fused_short()
    print("ALL OK" if ok else "FAILURES PRESENT")
    return 0 if ok else 1


def check_fused_short():
    """Fused short-seq kernel (non-causal, bias, dropout determinism)."""
    import jax.numpy as jnp

    from analytics_zoo_tpu.ops.attention import fused_short_attention
    rs = np.random.RandomState(1)
    ok = True
    for (b, h, s, d) in [(8, 4, 128, 64), (2, 12, 256, 64), (4, 2, 384, 32)]:
        q, k, v = (jnp.asarray(rs.randn(b, h, s, d) * 0.4, jnp.bfloat16)
                   for _ in range(3))
        kb = jnp.asarray(np.where(rs.rand(b, s) > 0.2, 0.0, -30.0),
                         np.float32)
        ref = dot_product_attention(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), bias=kb[:, None, None, :])
        got = fused_short_attention(q, k, v, key_bias=kb)
        ok &= check(f"fused fwd b{b} s{s}", got, ref, 2e-2)
        gf = jax.grad(lambda q, k, v: jnp.sum(
            fused_short_attention(q, k, v, key_bias=kb).astype(jnp.float32)
            * 0.01), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(dot_product_attention(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), bias=kb[:, None, None, :]) * 0.01),
            argnums=(0, 1, 2))(q, k, v)
        for nm, a, bb in zip("dq dk dv".split(), gf, gr):
            ok &= check(f"fused {nm} b{b} s{s}", a, bb, 4e-2)
    # dropout: deterministic per rng, different across rngs, grads finite
    b, h, s, d = 4, 4, 128, 64
    q, k, v = (jnp.asarray(rs.randn(b, h, s, d) * 0.4, jnp.bfloat16)
               for _ in range(3))
    rng = jax.random.PRNGKey(7)
    o1 = fused_short_attention(q, k, v, dropout_rate=0.1, dropout_rng=rng)
    o2 = fused_short_attention(q, k, v, dropout_rate=0.1, dropout_rng=rng)
    det = bool(jnp.all(o1 == o2))
    dif = not bool(jnp.all(o1 == fused_short_attention(
        q, k, v, dropout_rate=0.1, dropout_rng=jax.random.PRNGKey(8))))
    g = jax.grad(lambda q: jnp.sum(fused_short_attention(
        q, k, v, dropout_rate=0.1, dropout_rng=rng).astype(jnp.float32)))(q)
    fin = bool(jnp.all(jnp.isfinite(g.astype(jnp.float32))))
    print(("OK " if det and dif and fin else "FAIL")
          + f" fused dropout det={det} dif={dif} finite={fin}")
    return ok and det and dif and fin

if __name__ == "__main__":
    sys.exit(main())
