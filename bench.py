"""Benchmark driver: prints ONE JSON line with the headline metric.

Round-2 coverage of the north-star set (BASELINE.json):
  1. ResNet-50 training images/sec (headline; config #2)
  2. NCF samples/sec (config #1)
  3. Wide&Deep samples/sec, sparse-embedding allreduce stress (config #3)
  4. BERT-base fine-tune step, capture-style (config #4)

Every workload reports MFU (achieved matmul FLOP/s divided by chip peak) from
XLA's compiled cost analysis. The reference publishes no absolute numbers
(`published: {}`), so ``vs_baseline`` is null until an operator records a
floor with ``--write-baseline``.

Usage: ``python bench.py [all|resnet50|ncf|widedeep|bert|...]`` (default
all; the full workload list is ``_WORKLOADS`` below, incl. the ``eval``
async-vs-sync eval/predict pipeline A/B). Outage-proofing flags —
``--shard i/n`` / ``--resume`` (multi-invocation rounds via
BENCH_STATE.json), ``--ratio`` / ``--full`` (force or suppress the
CPU-parity ratio mode the sweep auto-selects when the accelerator
preflight fails), ``--budget S`` (child-side per-workload budget) — are
documented in docs/benchmarking.md.
"""
import json
import os
import sys
import time
from functools import partial

import numpy as np

# The chip-peak table and XLA cost-analysis extraction moved to
# common/profiler.py (the step-phase profiler uses the same numbers for its
# live MFU/roofline gauges); bench delegates LAZILY so plain
# `python bench.py` still defers every jax import to the workloads.


def _peak_flops():
    from analytics_zoo_tpu.common import profiler as _profiler
    return _profiler.device_peak_flops()


class _BenchResult(dict):
    pass


def _transient(e: Exception) -> bool:
    msg = repr(e)
    return any(s in msg for s in ("remote_compile", "response body closed",
                                  "DEADLINE_EXCEEDED", "UNAVAILABLE"))


def _cost_flops(compiled):
    from analytics_zoo_tpu.common import profiler as _profiler
    return _profiler.cost_flops(compiled)


def _cost_bytes(compiled):
    from analytics_zoo_tpu.common import profiler as _profiler
    return _profiler.cost_bytes(compiled)


# best-so-far record for the CURRENT workload (child process). Workloads
# stash intermediate numbers here as each phase lands; the budget guard
# (SIGALRM/SIGTERM in --one mode) emits them as a partial record instead of
# dying with nothing on stdout — the round-4/5 failure mode (rc=124, no
# JSON for the whole round) cannot recur.
_PARTIAL = {"detail": {}}


def _note_partial(metric=None, value=None, unit=None, **detail):
    if metric is not None:
        _PARTIAL["metric"] = metric
        _PARTIAL["value"] = value
        _PARTIAL["unit"] = unit
    _PARTIAL["detail"].update(detail)


# v5e HBM bandwidth (per chip); the denominator for roofline fractions
_HBM_GBPS = 820.0


def _roofline_fields(flops, bytes_per_step, elapsed, steps):
    """Bytes/step from XLA cost analysis + achieved HBM GB/s — every
    compute row carries the same accounting the round-3 resnet note had,
    so 'X-bound' claims are arithmetic, not assertion."""
    if bytes_per_step is None or elapsed <= 0:
        return {}
    step_t = elapsed / steps
    gbs = bytes_per_step / step_t / 1e9
    try:
        import jax
        kind = jax.devices()[0].device_kind
    except Exception:
        kind = "unknown"
    is_v5e = "v5 lite" in kind.lower() or "v5e" in kind.lower()
    out = {"bytes_per_step": round(bytes_per_step / 1e9, 2),
           "achieved_gb_per_sec": round(gbs, 1),
           "hbm_roofline_fraction": round(gbs / _HBM_GBPS, 3),
           # the denominator always assumes v5e HBM (kept numeric for
           # downstream parsers); the tag flags when the detected device
           # kind is NOT a v5e so the fraction is not silently misread
           "hbm_gbps_assumed": _HBM_GBPS,
           "hbm_assumption": "v5e" if is_v5e
           else f"assumed_v5e_on_{kind}"}
    peak = _peak_flops()
    if flops is not None and peak is not None:
        # time the step would take if ONLY matmuls or ONLY bytes mattered
        out["ideal_matmul_ms"] = round(flops / peak * 1e3, 2)
        out["hbm_floor_ms"] = round(bytes_per_step / (_HBM_GBPS * 1e9) * 1e3,
                                    2)
        out["measured_step_ms"] = round(step_t * 1e3, 2)
    return out


def _roofline_utilization(mfu, roofline):
    """Headline utilization for gather-dominated steps: embedding gathers
    do almost no FLOPs, so MFU reads ~0 even when the step sits at the
    HBM roofline — the honest single number is max(mfu,
    hbm_roofline_fraction), the same max() the live profiler's
    ``roofline_utilization_ratio`` gauge publishes. ``roofline_bound``
    names which bound won so the number can't be misread as MFU."""
    frac = roofline.get("hbm_roofline_fraction")
    cands = [(v, s) for v, s in ((mfu, "mfu"), (frac, "hbm"))
             if isinstance(v, (int, float))]
    if not cands:
        return {}
    v, bound = max(cands)
    return {"roofline_utilization": v, "roofline_bound": bound}


def _run_steps_differenced(est, bx, by, steps, flops_override=None):
    """Differenced device timing with ONE compiled executable.

    Compile a single N-step chained scan that returns its carry, dispatch
    it once vs twice CHAINED (the second call consumes the first call's
    output carry), and take t(two) − t(one) as N steps of pure device
    time: JAX's async dispatch enqueues the second call while the first
    executes, so the per-dispatch tunnel RPC latency (0.1–2s, varying run
    to run) cancels exactly as it did in the earlier two-executable
    t(2N)−t(N) scheme — but at HALF the remote-compile cost, which
    dominates bench wall time on slow-tunnel days. A scalar loss readback
    is the completion fence.

    Returns (elapsed_for_N_steps, flops_per_step, bytes_per_step).
    ``flops_override``: XLA's cost analysis cannot see inside pallas
    custom calls, so workloads with hand-written kernels pass an analytic
    count. flops/bytes come from the scan executable's cost analysis —
    XLA counts a loop body ONCE regardless of trip count (verified), so
    they are per-step numbers already.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    est._ensure_initialized(bx)
    step_fn = est._build_train_step()
    rng = jax.random.PRNGKey(0)

    def many(params, opt_state, mstate):
        def body(carry, _):
            p, o, m = carry
            p, o, m, loss = step_fn(p, o, m, rng, bx, by)
            return (p, o, m), loss
        carry, losses = lax.scan(body, (params, opt_state, mstate),
                                 None, length=steps)
        # the steps chain through params, so the scan measures SERIAL step
        # latency; the scalar is the device-fetch fence
        return carry, jnp.sum(losses.astype(jnp.float32))

    c1 = jax.jit(many).lower(est.params, est.opt_state,
                             est.model_state).compile()
    flops = flops_override if flops_override is not None \
        else _cost_flops(c1)
    bytes_per_step = _cost_bytes(c1)
    args = (est.params, est.opt_state, est.model_state)
    carry, loss = c1(*args)
    float(loss)  # warm + fence
    float(c1(*carry)[1])  # second warm from a device-resident carry

    def once():
        _, l = c1(*args)
        return float(l)

    def twice():
        mid, _ = c1(*args)
        _, l = c1(*mid)
        return float(l)

    for _attempt in range(3):
        t1 = min(_timed(once) for _ in range(3))
        t2 = min(_timed(twice) for _ in range(3))
        if t2 - t1 > 1e-4:
            return t2 - t1, flops, bytes_per_step
    raise RuntimeError(
        f"differenced timing collapsed (t1={t1:.4f} t2={t2:.4f})")


def _embedding_fused_ab(make_est, bx, by, steps, parity_steps=3):
    """Fused-vs-unfused embedding kernel A/B: time the same workload with
    ``kernels.fused_embedding`` on and off (same differenced N-step scan
    as the headline number), and train ``parity_steps`` real steps each
    way. The params must come out bit-identical — the bench refuses to
    publish a speedup whose numerics changed (same contract as the flash
    numerics gate). Off-TPU both settings trace the identical jaxpr, so
    the ratio there reads ~1.0 by construction; on the TPU it is the
    pallas-fusion win."""
    import jax
    from analytics_zoo_tpu.common.config import global_config

    cfg = global_config()
    had_override = "kernels.fused_embedding" in cfg._overrides
    saved = cfg.get("kernels.fused_embedding")
    times, params = {}, {}
    try:
        for mode, enabled in (("fused", True), ("unfused", False)):
            cfg.set("kernels.fused_embedding", enabled)
            est = make_est()
            t, _f, _b = _run_steps_differenced(est, bx, by, steps)
            times[mode] = t
            step_fn = est._build_train_step()
            p, o, m = est.params, est.opt_state, est.model_state
            rng = jax.random.PRNGKey(0)
            for _ in range(parity_steps):
                p, o, m, _loss = step_fn(p, o, m, rng, bx, by)
            params[mode] = jax.device_get(p)
    finally:
        if had_override:
            cfg.set("kernels.fused_embedding", saved)
        else:
            cfg.unset("kernels.fused_embedding")
    flat_f, tree_f = jax.tree_util.tree_flatten(params["fused"])
    flat_u, tree_u = jax.tree_util.tree_flatten(params["unfused"])
    if tree_f != tree_u or any(
            not np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(flat_f, flat_u)):
        raise RuntimeError(
            "embedding fused A/B parity FAILED: trained params diverge "
            "between kernels.fused_embedding on/off — refusing to publish "
            "embedding_fused_speedup")
    return {"embedding_fused_speedup":
                round(times["unfused"] / max(times["fused"], 1e-9), 3),
            "embedding_fused_step_ms":
                round(times["fused"] / steps * 1e3, 3),
            "embedding_unfused_step_ms":
                round(times["unfused"] / steps * 1e3, 3),
            "embedding_fused_parity_ok": True}


def _fed_rate(est, train_set, batch_size: int, iters: int = 24,
              warm_iters: int = 8, steps_per_dispatch: int = 8):
    """End-to-end ``Estimator.train`` throughput from HOST data: FeatureSet
    shuffle/gather → DeviceFeed (double-buffered device_put) → multi-step
    dispatch, i.e. the path a real user runs (the reference's FeatureSet
    cached-iterator contract, ``FeatureSet.scala:655``). Returns
    samples/sec over ``iters`` post-warmup iterations — wall clock, nothing
    subtracted: this number deliberately includes host+transfer costs.
    ``steps_per_dispatch`` amortizes the tunnel's per-dispatch RPC latency
    exactly as a production remote-attached deployment would. For the
    measurement the DeviceFeed depth is pinned to 1 via the config
    registry ("data.prefetch") — the tunnel rate-limits sustained
    transfers (measured: 52 → 9 img/s raw device_put within minutes of
    heavy traffic), so speculative prefetch beyond the measured
    iterations actively corrupts the number."""
    from analytics_zoo_tpu.common.config import global_config
    from analytics_zoo_tpu.common.triggers import MaxIteration

    cfg = global_config()
    had_override = "data.prefetch" in cfg._overrides
    saved = cfg.get("data.prefetch")
    cfg.set("data.prefetch", 1)
    try:
        est.train(train_set, batch_size,
                  end_trigger=MaxIteration(est.global_step + warm_iters),
                  steps_per_dispatch=steps_per_dispatch)
        start = time.perf_counter()
        est.train(train_set, batch_size,
                  end_trigger=MaxIteration(est.global_step + iters),
                  steps_per_dispatch=steps_per_dispatch)
        elapsed = time.perf_counter() - start
    finally:
        # don't pin a permanent override where none existed (it would
        # shadow later env/file config changes)
        if had_override:
            cfg.set("data.prefetch", saved)
        else:
            cfg.unset("data.prefetch")
    return batch_size * iters / elapsed


def _flash_numerics_gate(head_dim: int, causal: bool = True):
    """Pallas flash fwd+bwd vs the XLA blockwise path on a small multi-block
    shape; the bench refuses to publish a kernel number whose kernels don't
    agree with the reference math in the same process."""
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.ops.attention import (blockwise_attention,
                                                 flash_attention)

    rs = np.random.RandomState(7)
    b, h, s = 2, 2, 1024  # 2 q-blocks / kv-blocks: exercises the grids
    q, k, v = (jnp.asarray(rs.randn(b, h, s, head_dim) * 0.5, jnp.bfloat16)
               for _ in range(3))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal)
                       .astype(jnp.float32) * 0.01)

    def loss_ref(q, k, v):
        return jnp.sum(blockwise_attention(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32), causal=causal).astype(jnp.float32) * 0.01)

    out_f = flash_attention(q, k, v, causal=causal)
    out_r = blockwise_attention(q.astype(jnp.float32),
                                k.astype(jnp.float32),
                                v.astype(jnp.float32), causal=causal)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    worst = 0.0
    for got, want in [(out_f, out_r), *zip(gf, gr)]:
        got = np.asarray(got, np.float32)
        want = np.asarray(want, np.float32)
        err = float(np.max(np.abs(got - want))
                    / max(float(np.max(np.abs(want))), 1e-6))
        worst = max(worst, err)
    if worst > 4e-2:
        raise RuntimeError(
            f"flash kernel numerics gate FAILED: rel_err={worst:.3e}")
    return round(worst, 6)


def _fused_short_numerics_gate(seq_len: int = 128):
    """The BERT-path fused short-sequence kernel vs plain XLA attention
    (fwd + all three grads, with a padding-mask bias)."""
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.ops.attention import (dot_product_attention,
                                                 fused_short_applicable,
                                                 fused_short_attention)

    if not fused_short_applicable(seq_len, seq_len, causal=False):
        return None  # CPU run: the kernel is not in the measured path
    rs = np.random.RandomState(11)
    b, h, d = 4, 12, 64
    q, k, v = (jnp.asarray(rs.randn(b, h, seq_len, d) * 0.5, jnp.bfloat16)
               for _ in range(3))
    kb = jnp.asarray(np.where(rs.rand(b, seq_len) > 0.15, 0.0, -1e9),
                     jnp.float32)

    def loss_fused(q, k, v):
        return jnp.sum(fused_short_attention(q, k, v, key_bias=kb)
                       .astype(jnp.float32) * 0.01)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(
            q.astype(jnp.float32), k.astype(jnp.float32),
            v.astype(jnp.float32),
            bias=kb[:, None, None, :]).astype(jnp.float32) * 0.01)

    out_f = fused_short_attention(q, k, v, key_bias=kb)
    out_r = dot_product_attention(q.astype(jnp.float32),
                                  k.astype(jnp.float32),
                                  v.astype(jnp.float32),
                                  bias=kb[:, None, None, :])
    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    worst = 0.0
    for got, want in [(out_f, out_r), *zip(gf, gr)]:
        got = np.asarray(got, np.float32)
        want = np.asarray(want, np.float32)
        err = float(np.max(np.abs(got - want))
                    / max(float(np.max(np.abs(want))), 1e-6))
        worst = max(worst, err)
    if worst > 4e-2:
        raise RuntimeError(
            f"fused-short kernel numerics gate FAILED: rel_err={worst:.3e}")
    return round(worst, 6)


def _timed(fn):
    start = time.perf_counter()
    fn()
    return time.perf_counter() - start


def _mfu(flops_per_step, steps, elapsed):
    peak = _peak_flops()
    if flops_per_step is None or peak is None:
        return None
    return round(flops_per_step * steps / elapsed / peak, 4)


def bench_resnet50(batch_size: int = 256, steps: int = 20, warmup: int = 3):
    """ResNet-50 dogs-vs-cats-shape training throughput (north-star #2)."""
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.keras import objectives, optimizers
    from analytics_zoo_tpu.models.image.imageclassification import resnet
    from analytics_zoo_tpu.parallel.mesh import shard_batch

    ctx = init_tpu_context()
    batch_size = max(ctx.num_devices, (batch_size // ctx.num_devices)
                     * ctx.num_devices)
    import jax.numpy as jnp
    model = resnet(50, num_classes=2, input_shape=(224, 224, 3))
    est = Estimator(model=model,
                    loss_fn=objectives.get("sparse_categorical_crossentropy"),
                    optimizer=optimizers.SGD(0.1, momentum=0.9),
                    compute_dtype=jnp.bfloat16)
    rs = np.random.RandomState(0)
    x = rs.rand(batch_size, 224, 224, 3).astype(np.float32)
    y = rs.randint(0, 2, batch_size).astype(np.float32)
    bx, by = shard_batch(est.mesh, (x, y))
    del warmup
    elapsed, flops, bytes_step = _run_steps_differenced(est, bx, by, steps)
    dev_rate = round(batch_size * steps / elapsed, 1)
    # headline banked: if the fed add-on below outlives the budget, the
    # guard still emits this device rate as a partial record
    _note_partial(metric="resnet50_train_images_per_sec", value=dev_rate,
                  unit="images/s", device_images_per_sec=dev_rate,
                  mfu=_mfu(flops, steps, elapsed))

    # end-to-end FED rate: same model family trained from HOST data through
    # FeatureSet→DeviceFeed→Estimator.train (uint8 wire + on-device
    # normalize — the TPU-first input contract). Wall clock, nothing
    # subtracted: on the tunneled bench chip this is transfer-bound, and
    # reporting it next to the device rate is the honest gap.
    from analytics_zoo_tpu.feature import FeatureSet
    fed_model = resnet(50, num_classes=2, input_shape=(224, 224, 3),
                       preprocess="imagenet_uint8")
    fed_est = Estimator(
        model=fed_model,
        loss_fn=objectives.get("sparse_categorical_crossentropy"),
        optimizer=optimizers.SGD(0.1, momentum=0.9),
        compute_dtype=jnp.bfloat16)
    raw = rs.randint(0, 255, (batch_size * 8, 224, 224, 3), dtype=np.uint8)
    labels = rs.randint(0, 2, batch_size * 8).astype(np.float32)
    fed_set = FeatureSet.from_ndarrays(raw, labels, shuffle=True)

    # the fed phase is bracketed by raw device_put probes: the tunnel
    # rate-limits sustained transfers, so a floor measured minutes earlier
    # does not bound a later fed phase — fed is judged against the floor
    # measured in ITS OWN window (fed ≈ floor ⇒ the train loop adds no
    # host-side overhead beyond the wire)
    import jax as _jax

    def _wire_probe():
        one = raw[:batch_size]
        t0 = time.perf_counter()
        buf = _jax.device_put(one)
        buf.block_until_ready()
        float(jnp.sum(buf[:1, 0, 0].astype(jnp.float32)))
        return round(batch_size / (time.perf_counter() - t0), 1)

    try:
        # the fed add-on costs another big compile + sustained transfers;
        # if the device measurement already ate most of the child's
        # timeout (slow-tunnel day), skip it rather than let the
        # subprocess kill take the headline down with it
        if time.perf_counter() - _T0 > 400:
            raise RuntimeError("child budget: device phase too slow, "
                               "fed add-on skipped")
        _wire_probe()  # untimed warmup: compile the readback, first put
        floor_before = _wire_probe()
        # transfer-light measurement (8 iters = ONE 8-step dispatch group):
        # the tunnel's rate limiter punishes anything heavier
        fed = round(_fed_rate(fed_est, fed_set, batch_size, iters=8,
                              warm_iters=8, steps_per_dispatch=8), 1)
        floor_after = _wire_probe()
        wire_floor = {"before": floor_before, "after": floor_after}
    except Exception as e:  # the fed add-on must not lose the headline
        fed = {"error": repr(e)[:200]}
        wire_floor = None
    return _BenchResult(
        metric="resnet50_train_images_per_sec",
        value=dev_rate,
        unit="images/s",
        mfu=_mfu(flops, steps, elapsed),
        detail={"fixed_device_batch": True, "batch_size": batch_size,
                "image": "224x224x3",
                "optimizer": "sgd+momentum",
                "device_images_per_sec": dev_rate,
                "fed_images_per_sec": fed,
                "fed_wire_floor_images_per_sec": wire_floor,
                "fed_note": "fed = Estimator.train from host ndarrays "
                            "(shuffle+uint8 transfer+device normalize+step, "
                            "wall clock, 8 steps/dispatch); wire_floor = "
                            "raw device_put bandwidth probed immediately "
                            "before/after — the tunnel RATE-LIMITS "
                            "sustained transfers (52→9 img/s raw within "
                            "minutes), so fed is only meaningful against "
                            "its own window's floor. fed ≈ floor means "
                            "the train loop adds no host-side overhead "
                            "beyond the wire; a direct-attached chip "
                            "moves the floor to PCIe (>8GB/s, ~50k "
                            "img/s) where the host-shuffle rate (~29k "
                            "img/s, pipeline row) takes over",
                "loop": "differenced: chained double-dispatch of one "
                        "compiled N-step scan",
                **_roofline_fields(flops, bytes_step, elapsed, steps),
                "roofline_note": "at the architecture's memory floor: the "
                                 "analytic streaming minimum for ResNet-50 "
                                 "b256 bf16 (conv fwd+dx+dW, BN stats/"
                                 "apply/grad) is ~62-65GB/step vs 77 "
                                 "measured; the residue is C=64 tensors "
                                 "padding to 128 HBM lanes (physical > "
                                 "logical bytes) and fusion-boundary "
                                 "re-reads inside XLA's conv mega-fusions "
                                 "(verified: BN apply + relu + dW "
                                 "reductions already fuse INTO the conv "
                                 "kernels). The 1x1 bottleneck convs are "
                                 "intrinsically memory-bound on v5e "
                                 "(51 flops/byte vs the 240 needed), so "
                                 "MFU ~0.33 at 97-99% of roofline is the "
                                 "bf16 ceiling; the remaining lever is "
                                 "int8 training",
                "flops_per_step": flops})


def bench_resnet50_int8(batch_size: int = 256, steps: int = 20):
    """Quantized-DATAFLOW int8 ResNet-50 training (round-5): int8 tensors
    BETWEEN layers with delayed scaling and a whole-backbone custom vjp
    (``ops/int8_dataflow.py``). The bf16 step sits at 97-99% of the HBM
    roofline (resnet50 row), so this is the byte-cut lever — round-4
    measured per-layer int8 insertion byte-NEGATIVE (82.8GB vs 77.2GB);
    the dataflow design is the fix. MFU here divides by the bf16 peak, so
    >0.5 is possible when int8 MXU convs (2x peak) dominate."""
    import jax.numpy as jnp

    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.keras import objectives, optimizers
    from analytics_zoo_tpu.models.image.imageclassification import resnet
    from analytics_zoo_tpu.parallel.mesh import shard_batch

    ctx = init_tpu_context()
    batch_size = max(ctx.num_devices, (batch_size // ctx.num_devices)
                     * ctx.num_devices)
    rs = np.random.RandomState(0)

    def measure(bsz):
        model = resnet(50, num_classes=2, input_shape=(224, 224, 3),
                       dataflow="int8")
        est = Estimator(
            model=model,
            loss_fn=objectives.get("sparse_categorical_crossentropy"),
            optimizer=optimizers.SGD(0.1, momentum=0.9),
            compute_dtype=jnp.bfloat16)
        x = rs.rand(bsz, 224, 224, 3).astype(np.float32)
        y = rs.randint(0, 2, bsz).astype(np.float32)
        bx, by = shard_batch(est.mesh, (x, y))
        return _run_steps_differenced(est, bx, by, steps), bsz

    try:
        (elapsed, flops, bytes_step), used_b = measure(batch_size)
    except Exception as e:
        # ONLY the big-HLO remote-compile rejection warrants a half-batch
        # retry (HTTP 413 on the bf16 b512 program); a genuine failure in
        # the int8 path must surface immediately, not burn another full
        # compile on a smaller batch
        oversize = any(s in repr(e) for s in ("413", "Payload Too Large",
                                              "content length"))
        if batch_size <= 128 or not (oversize or _transient(e)):
            raise
        (elapsed, flops, bytes_step), used_b = measure(batch_size // 2)
    rate = round(used_b * steps / elapsed, 1)
    return _BenchResult(
        metric="resnet50_int8_dataflow_images_per_sec",
        value=rate, unit="images/s",
        mfu=_mfu(flops, steps, elapsed),
        detail={"fixed_device_batch": True, "batch_size": used_b,
                "image": "224x224x3",
                "device_images_per_sec": rate,
                "dataflow": "int8 inter-layer tensors, delayed scaling, "
                            "int8 MXU convs fwd, bf16 dgrad/wgrad, int8 "
                            "saved activations",
                "loop": "differenced: chained double-dispatch of one "
                        "compiled N-step scan",
                **_roofline_fields(flops, bytes_step, elapsed, steps),
                "note": "compare bytes_per_step against the bf16 resnet50 "
                        "row (77GB-class): the int8 dataflow's win is "
                        "bytes, and any images/s gain follows from it; "
                        "numerics are STE-quantized (tests/"
                        "test_int8_dataflow.py gates op grads at cos>0.97 "
                        "vs the float mirror and end-to-end descent)",
                "flops_per_step": flops})


def bench_ncf(batch_size: int = 32768, steps: int = 50, warmup: int = 5):
    """NCF MovieLens-1M training throughput (north-star #1). The model is
    tiny, so small batches are dispatch-bound — 32k keeps the chip busy
    (8192 measures ~2.7M samples/s vs ~9.4M here)."""
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.keras import objectives, optimizers
    from analytics_zoo_tpu.models import NeuralCF
    from analytics_zoo_tpu.parallel.mesh import shard_batch

    ctx = init_tpu_context()
    if batch_size % ctx.num_devices:
        batch_size = (batch_size // ctx.num_devices) * ctx.num_devices
    users, items = 6040, 3706
    rs = np.random.RandomState(0)
    x = np.stack([rs.randint(1, users + 1, batch_size),
                  rs.randint(1, items + 1, batch_size)], 1).astype(np.float32)
    y = rs.randint(0, 2, batch_size).astype(np.float32)
    def make_est():
        ncf = NeuralCF(users, items, 2, user_embed=64, item_embed=64,
                       hidden_layers=[128, 64, 32], mf_embed=32)
        return Estimator(
            model=ncf._ensure_built(),
            loss_fn=objectives.get("sparse_categorical_crossentropy"),
            optimizer=optimizers.Adam(1e-3))

    est = make_est()
    bx, by = shard_batch(est.mesh, (x, y))
    del warmup
    elapsed, flops, bytes_step = _run_steps_differenced(est, bx, by, steps)
    ab = _embedding_fused_ab(make_est, bx, by, steps)
    rate = round(batch_size * steps / elapsed, 1)
    mfu = _mfu(flops, steps, elapsed)
    roofline = _roofline_fields(flops, bytes_step, elapsed, steps)
    return _BenchResult(
        metric="ncf_train_samples_per_sec",
        value=rate,
        unit="samples/s",
        mfu=mfu,
        detail={"fixed_device_batch": True, "model": "NeuralCF ml-1m (embed 64, mlp 128-64-32, mf 32)",
                "batch_size": batch_size,
                "device_samples_per_sec": rate,
                "loop": "differenced: chained double-dispatch of one "
                        "compiled N-step scan",
                **roofline,
                **_roofline_utilization(mfu, roofline),
                **ab,
                "flops_per_step": flops})


def bench_widedeep(batch_size: int = 8192, steps: int = 30, warmup: int = 5):
    """Wide&Deep Census-shape training throughput (north-star #3): sparse
    wide table via gather + scatter-add grads — the allreduce stress case."""
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.keras import objectives, optimizers
    from analytics_zoo_tpu.models.recommendation.wide_and_deep import (
        ColumnFeatureInfo, WideAndDeep)
    from analytics_zoo_tpu.parallel.mesh import shard_batch

    ctx = init_tpu_context()
    if batch_size % ctx.num_devices:
        batch_size = (batch_size // ctx.num_devices) * ctx.num_devices
    # census-like columns + one large hashed cross (stress the wide table)
    ci = ColumnFeatureInfo(
        wide_base_cols=["edu", "occ"], wide_base_dims=[16, 1000],
        wide_cross_cols=["edu_occ"], wide_cross_dims=[100000],
        indicator_cols=["work", "marital"], indicator_dims=[9, 7],
        embed_cols=["edu_e", "occ_e"], embed_in_dims=[16, 1000],
        embed_out_dims=[8, 8],
        continuous_cols=["age", "hours"])
    wnd = WideAndDeep("wide_n_deep", 2, ci, hidden_layers=(40, 20, 10))
    rs = np.random.RandomState(0)
    offsets = np.cumsum([0] + ci.wide_dims)[:-1]
    wide = np.stack([rs.randint(0, d, batch_size) + off
                     for d, off in zip(ci.wide_dims, offsets)], 1)
    ind = np.stack([rs.randint(0, d, batch_size)
                    for d in ci.indicator_dims], 1)
    emb = np.stack([rs.randint(0, d, batch_size)
                    for d in ci.embed_in_dims], 1)
    cont = rs.rand(batch_size, 2).astype(np.float32)
    y = rs.randint(0, 2, batch_size).astype(np.float32)
    def make_est():
        return Estimator(
            model=wnd._ensure_built(),
            loss_fn=objectives.get("sparse_categorical_crossentropy"),
            optimizer=optimizers.Adam(1e-3))

    est = make_est()
    batch = shard_batch(est.mesh, ([wide.astype(np.int32),
                                    ind.astype(np.int32),
                                    emb.astype(np.int32), cont], y))
    bx, by = batch
    del warmup
    elapsed, flops, bytes_step = _run_steps_differenced(est, bx, by, steps)
    ab = _embedding_fused_ab(make_est, bx, by, steps)
    # Criteo-scale host feature prep: 1M rows through the hashed-cross path
    # (vectorized unique-gather crc32, models/recommendation/wide_and_deep.py)
    import pandas as pd

    from analytics_zoo_tpu.models.recommendation.wide_and_deep import (
        cross_columns)
    n_prep = 1_000_000
    prep_df = pd.DataFrame({
        "c1": rs.randint(0, 10000, n_prep),
        "c2": rs.choice([f"tok{i}" for i in range(5000)], n_prep)})
    cross_columns(prep_df.head(16), ["c1", "c2"], 100)  # warm imports
    t0 = time.perf_counter()
    cross_columns(prep_df, ["c1", "c2"], 100000)
    prep_rows_per_sec = round(n_prep / (time.perf_counter() - t0), 1)
    rate = round(batch_size * steps / elapsed, 1)
    mfu = _mfu(flops, steps, elapsed)
    roofline = _roofline_fields(flops, bytes_step, elapsed, steps)
    return _BenchResult(
        metric="widedeep_train_samples_per_sec",
        value=rate,
        unit="samples/s",
        mfu=mfu,
        detail={"fixed_device_batch": True, "batch_size": batch_size, "wide_dim": sum(ci.wide_dims),
                "device_samples_per_sec": rate,
                "loop": "differenced: chained double-dispatch of one "
                        "compiled N-step scan",
                **roofline,
                **_roofline_utilization(mfu, roofline),
                **ab,
                "roofline_note": "logical-bytes fraction understates the "
                                 "physical roofline: the census MLP's "
                                 "40/20/10-wide activations pad to 128 "
                                 "lanes in HBM (2-3x the logical bytes), "
                                 "so the step is at its physical memory "
                                 "bound; bf16 compute measured no byte "
                                 "cut (0.522GB either way). Larger "
                                 "batches amortize further: b32768 "
                                 "measures ~10.7M samples/s",
                "prep_cross_columns_rows_per_sec": prep_rows_per_sec,
                "prep_rows": n_prep,
                "flops_per_step": flops})


def bench_widedeep_sharded(batch_size: int = 8192, steps: int = 20,
                           warmup: int = 5):
    """Wide&Deep with the VOCAB-SHARDED sparse-embedding engine
    (parallel/embedding.py): a 100M-row wide table trains with all-to-all
    lookups and segment-sum row-subset gradients — per-device HBM holds
    1/S of the table (asserted), the backward never materializes a
    densified [vocab, dim] gradient, and optimizer state for untouched
    rows is neither read nor written. Reports samples/s against the
    dense-replicated baseline layout."""
    import jax
    import jax.numpy as jnp
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.keras import objectives, optimizers
    from analytics_zoo_tpu.models.recommendation.wide_and_deep import (
        ColumnFeatureInfo, WideAndDeep)
    from analytics_zoo_tpu.parallel import embedding as embed_engine
    from analytics_zoo_tpu.parallel.mesh import shard_batch

    ctx = init_tpu_context()
    if batch_size % ctx.num_devices:
        batch_size = max(ctx.num_devices,
                         (batch_size // ctx.num_devices) * ctx.num_devices)
    on_cpu = jax.default_backend() == "cpu"
    # the headline config is the 100M-row cross table; the CPU scale-down
    # keeps the same code path at a laptop-sized vocab
    cross_dim = int(os.environ.get(
        "BENCH_SHARDED_VOCAB", "1000000" if on_cpu else "100000000"))
    del warmup

    def build(shard, vocab):
        ci = ColumnFeatureInfo(
            wide_base_cols=["edu", "occ"], wide_base_dims=[16, 1000],
            wide_cross_cols=["edu_occ"], wide_cross_dims=[vocab],
            indicator_cols=["work", "marital"], indicator_dims=[9, 7],
            embed_cols=["edu_e", "occ_e"], embed_in_dims=[16, 1000],
            embed_out_dims=[8, 8],
            continuous_cols=["age", "hours"])
        wnd = WideAndDeep("wide_n_deep", 2, ci, hidden_layers=(40, 20, 10),
                          shard_embeddings=shard)
        rs = np.random.RandomState(0)
        offsets = np.cumsum([0] + ci.wide_dims)[:-1]
        wide = np.stack([rs.randint(0, d, batch_size) + off
                         for d, off in zip(ci.wide_dims, offsets)], 1)
        ind = np.stack([rs.randint(0, d, batch_size)
                        for d in ci.indicator_dims], 1)
        emb = np.stack([rs.randint(0, d, batch_size)
                        for d in ci.embed_in_dims], 1)
        cont = rs.rand(batch_size, 2).astype(np.float32)
        y = rs.randint(0, 2, batch_size).astype(np.float32)
        est = Estimator(
            model=wnd._ensure_built(),
            loss_fn=objectives.get("sparse_categorical_crossentropy"),
            optimizer=optimizers.Adam(1e-3))
        bx, by = shard_batch(est.mesh, ([wide.astype(np.int32),
                                         ind.astype(np.int32),
                                         emb.astype(np.int32), cont], y))
        return est, bx, by, ci

    est, bx, by, ci = build(True, cross_dim)
    elapsed, flops, bytes_step = _run_steps_differenced(est, bx, by, steps)
    rate = round(batch_size * steps / elapsed, 1)

    # asserted HBM footprint: the wide table's per-device bytes must be
    # the dense-replicated table / shard count, plus at most one padding
    # row per shard (the cold tier, when used, is host DRAM — zero HBM)
    spec = est._sharded_table_specs().get(("wide_linear", "table"))
    total_dim = sum(ci.wide_dims)
    dense_table_bytes = total_dim * 2 * 4  # [total_dim, num_classes] f32
    if spec is not None:
        pad_slack = spec.dim * 4  # <= 1 padded row per shard
        footprint_ok = bool(
            spec.device_bytes <= dense_table_bytes / spec.shards
            + pad_slack)
        if not footprint_ok:
            raise AssertionError(
                f"per-device table bytes {spec.device_bytes} exceed "
                f"dense/{spec.shards} + padding "
                f"({dense_table_bytes / spec.shards + pad_slack:.0f})")
        shards = spec.shards
        device_table_bytes = spec.device_bytes
    else:  # single-device fallback (no axis to shard over)
        footprint_ok, shards, device_table_bytes = (True, 1,
                                                    dense_table_bytes)

    # dense-replicated baseline at a vocab the replicated layout can hold
    dense_vocab = min(cross_dim,
                      int(os.environ.get("BENCH_SHARDED_DENSE_VOCAB",
                                         "1000000")))
    dense_rate, dense_err = None, None
    try:
        dest, dbx, dby, _ = build(None, dense_vocab)
        delapsed, _df, _db = _run_steps_differenced(dest, dbx, dby, steps)
        dense_rate = round(batch_size * steps / delapsed, 1)
    except Exception as exc:  # baseline OOM/unsupported: sharded run stands
        dense_err = str(exc)[:120]

    # host-DRAM cold tier probe: a small Embedding trains its cold tail
    # through the pure_callback fetch + io_callback SGD path
    from analytics_zoo_tpu.keras.layers.embedding import Embedding
    cold_layer = Embedding(4096, 16, name="bench_cold", cold_rows=1024)
    cparams, cstate = cold_layer.build(jax.random.PRNGKey(0), (None, 8))
    cold_ids = np.random.RandomState(1).randint(
        0, 4096, (256, 8)).astype(np.int32)

    def cold_loss(p):
        out, _ = cold_layer.call(p, cstate, jnp.asarray(cold_ids))
        return jnp.sum(out * out)

    g = jax.grad(cold_loss)(cparams)
    jax.block_until_ready(g["embeddings"])
    t0 = time.perf_counter()
    jax.block_until_ready(jax.grad(cold_loss)(cparams)["embeddings"])
    cold_step_ms = round((time.perf_counter() - t0) * 1e3, 2)
    cold_bytes = cold_layer._cold_tier.nbytes
    cold_layer._cold_tier.close()

    exch = embed_engine.exchange_cost_bytes(spec, batch_size) \
        if spec is not None else {}
    mfu = _mfu(flops, steps, elapsed)
    roofline = _roofline_fields(flops, bytes_step, elapsed, steps)
    return _BenchResult(
        metric="widedeep_sharded_train_samples_per_sec",
        value=rate,
        unit="samples/s",
        mfu=mfu,
        detail={"fixed_device_batch": True, "batch_size": batch_size,
                "wide_dim": total_dim, "shards": shards,
                "device_samples_per_sec": rate,
                "per_device_table_bytes": device_table_bytes,
                "dense_replicated_table_bytes": dense_table_bytes,
                "hbm_footprint_ok": footprint_ok,
                "dense_baseline_vocab": dense_vocab,
                "dense_baseline_samples_per_sec": dense_rate,
                "dense_baseline_error": dense_err,
                "sharded_vs_dense_samples_ratio":
                    round(rate / dense_rate, 3) if dense_rate else None,
                "cold_tier_bytes": cold_bytes,
                "cold_tier_grad_step_ms": cold_step_ms,
                "loop": "differenced: chained double-dispatch of one "
                        "compiled N-step scan",
                **{k: round(v / 1e6, 3) for k, v in exch.items()},
                **roofline,
                **_roofline_utilization(mfu, roofline),
                "roofline_note": "gather/exchange-bound: judge this "
                                 "workload by hbm_roofline_fraction (and "
                                 "profile.roofline_utilization_ratio in "
                                 "the live profiler), not MFU",
                "flops_per_step": flops})


def bench_bert(batch_size: int = 128, seq_len: int = 128, steps: int = 10,
               warmup: int = 2):
    """BERT-base fine-tune step via the capture-style task estimator
    (north-star #4); exercises the attention stack on hardware."""
    from analytics_zoo_tpu.capture.text import BERTClassifier, bert_input_pack
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.parallel.mesh import shard_batch

    ctx = init_tpu_context()
    batch_size = max(ctx.num_devices, (batch_size // ctx.num_devices)
                     * ctx.num_devices)
    import jax.numpy as jnp
    bert_cfg = dict(vocab=30522, hidden_size=768, n_block=12, n_head=12,
                    max_position_len=512, intermediate_size=3072,
                    compute_dtype=jnp.bfloat16)
    clf = BERTClassifier(2, bert_config=bert_cfg)
    rs = np.random.RandomState(0)
    tokens = rs.randint(1, 30000, (batch_size, seq_len))
    x = bert_input_pack(tokens)
    y = rs.randint(0, 2, batch_size).astype(np.float32)
    est = clf.model.get_estimator()
    bx, by = shard_batch(est.mesh, (x, y))
    numerics_ok = _fused_short_numerics_gate(seq_len)
    del warmup
    elapsed, flops, bytes_step = _run_steps_differenced(est, bx, by, steps)
    # the fused short-attention pallas kernel hides its scores/apply
    # matmuls from XLA's cost analysis: add them analytically
    # (train = 3x fwd; fwd = 4*B*S^2*H per layer for QK^T + PV), instead
    # of paying a second full-model remote compile for a use_flash=False
    # reference lowering as earlier rounds did (r3 cross-check: analytic
    # correction + cost analysis lands within 5% of the reference-lowering
    # number, the residue being XLA's non-matmul flop counting)
    if flops is not None:
        flops += 3 * 4 * batch_size * seq_len * seq_len \
            * bert_cfg["hidden_size"] * bert_cfg["n_block"]
    rate = round(batch_size * steps / elapsed, 1)
    _note_partial(metric="bert_base_finetune_samples_per_sec", value=rate,
                  unit="samples/s", device_samples_per_sec=rate,
                  mfu=_mfu(flops, steps, elapsed))

    # fed add-on: the token wire is 2 int32 arrays (~130KB/batch), so unlike
    # resnet the tunnel cannot hide the loop machinery — fed/device ratio IS
    # the Estimator.train overhead measurement
    from analytics_zoo_tpu.feature import FeatureSet
    fed_clf = BERTClassifier(2, bert_config=bert_cfg)
    fed_est = fed_clf.model.get_estimator()
    rs2 = np.random.RandomState(1)
    fed_tokens = rs2.randint(1, 30000, (batch_size * 16, seq_len))
    fed_x = bert_input_pack(fed_tokens)
    fed_y = rs2.randint(0, 2, batch_size * 16).astype(np.float32)
    fed_set = FeatureSet.from_ndarrays(fed_x, fed_y, shuffle=True)
    try:
        if time.perf_counter() - _T0 > 400:
            raise RuntimeError("child budget: device phase too slow, "
                               "fed add-on skipped")
        fed = round(_fed_rate(fed_est, fed_set, batch_size, iters=32,
                              warm_iters=16, steps_per_dispatch=16), 1)
    except Exception as e:
        fed = {"error": repr(e)[:200]}
    return _BenchResult(
        metric="bert_base_finetune_samples_per_sec",
        value=rate,
        unit="samples/s",
        mfu=_mfu(flops, steps, elapsed),
        detail={"fixed_device_batch": True, "batch_size": batch_size,
                "seq_len": seq_len,
                "model": "BERT-base (12L, 768h, 12 heads)",
                "device_samples_per_sec": rate,
                "fed_samples_per_sec": fed,
                "numerics_ok": numerics_ok is not None,
                "numerics_rel_err": numerics_ok,
                "loop": "differenced: chained double-dispatch of one "
                        "compiled N-step scan",
                **_roofline_fields(flops, bytes_step, elapsed, steps),
                "flops_per_step": flops})


def _gil_bound_ab(mesh, workers: int = 4):
    """A/B the per-record transform tiers on a deliberately GIL-bound
    (pure-Python) transform: eager thread-pool materialization vs lazy
    streaming (thread) vs the mp shared-memory worker pool — each measured
    as FED rate (host transform → DeviceFeed → sharded device batch), with
    a per-stage gather/transform/shard breakdown from the lazy pipeline's
    stage counters plus a timed shard_fn. On a single-core host the mp
    tier has no parallelism to exploit and the ratio collapses to ~1x
    (minus IPC) — ``host_cpus`` is reported so the ratio is read in
    context; with n cores the thread tier stays GIL-serialized while mp
    scales ~n×."""
    import math

    import jax

    from analytics_zoo_tpu.feature import FeatureSet, Lambda
    from analytics_zoo_tpu.feature.device_feed import DeviceFeed
    from analytics_zoo_tpu.parallel.mesh import shard_batch

    cpus = os.cpu_count() or 1
    gn, gd, gbatch = 2048, 512, 256
    rs = np.random.RandomState(3)
    gx = rs.rand(gn, gd).astype(np.float32)
    gy = rs.randint(0, 2, gn).astype(np.float32)

    def gil_bound(rec):
        # pure-Python per-record loop: holds the GIL end to end, so thread
        # pools serialize on it while forked workers do not
        acc = 0.0
        for v in rec[:256].tolist():
            acc += math.sin(v) * 0.5
        out = rec.copy()
        out[0] = np.float32(acc)
        return out

    def fresh():
        return FeatureSet.from_ndarrays(gx, gy, shuffle=False)

    steps = gn // gbatch

    def consume(host_it, shard_time):
        def timed_shard(m, b):
            t0 = time.perf_counter()
            out = shard_batch(m, b)
            shard_time[0] += time.perf_counter() - t0
            return out

        feed = DeviceFeed(host_it, mesh, shard_fn=timed_shard)
        try:
            done = 0
            for x, _ in feed:
                jax.block_until_ready(x)
                done += 1
                if done >= steps:
                    break
        finally:
            feed.close()

    def eager_rate(mode, nw):
        # fed rate INCLUDING the eager materialization: transform the whole
        # set, then stream one epoch to device — the cost a user pays per
        # epoch when the transform is applied up front
        shard_t = [0.0]
        t0 = time.perf_counter()
        tfs = fresh().transform(Lambda(gil_bound), num_workers=nw, mode=mode)
        t_transform = time.perf_counter() - t0
        consume(tfs.train_iterator(gbatch), shard_t)
        total = time.perf_counter() - t0
        return gn / total, {"transform_s": round(t_transform, 3),
                            "shard_s": round(shard_t[0], 3),
                            "total_s": round(total, 3)}

    def stream_rate(mode, nw):
        lz = fresh().transform(Lambda(gil_bound), num_workers=nw,
                               mode=mode, lazy=True)
        try:
            lz.prepare(gbatch)  # fork/slab spin-up outside the timed window
            shard_t = [0.0]
            t0 = time.perf_counter()
            consume(lz.train_iterator(gbatch), shard_t)
            total = time.perf_counter() - t0
            stages = {"gather_s": round(lz.stats["gather_s"], 3),
                      "transform_s": round(lz.stats["transform_s"], 3),
                      "shard_s": round(shard_t[0], 3),
                      "total_s": round(total, 3)}
            return gn / total, stages
        finally:
            lz.close()

    loop_rate, loop_stages = eager_rate("loop", 0)
    eager_thread, eager_stages = eager_rate("thread", workers)
    stream_thread, thread_stages = stream_rate("thread", workers)
    mp_workers = max(2, min(workers, cpus))
    stream_mp, mp_stages = stream_rate("mp", mp_workers)
    return {
        "transform": "pure-python sin-loop, 256 terms/record (GIL-bound)",
        "records": gn, "record_bytes": gd * 4, "batch_size": gbatch,
        "host_cpus": cpus, "thread_workers": workers,
        "mp_workers": mp_workers,
        "eager_loop_records_per_sec": round(loop_rate, 1),
        "eager_thread_records_per_sec": round(eager_thread, 1),
        "stream_thread_records_per_sec": round(stream_thread, 1),
        "stream_mp_records_per_sec": round(stream_mp, 1),
        "stream_mp_bytes_per_sec": round(stream_mp * gd * 4, 1),
        "mp_vs_eager_thread_speedup": round(stream_mp / eager_thread, 2),
        "stages": {"eager_loop": loop_stages,
                   "eager_thread": eager_stages,
                   "stream_thread": thread_stages,
                   "stream_mp": mp_stages},
        "note": "parity of every tier vs the eager per-record loop is "
                "gated bit-identical in tests/test_worker_pool.py; the "
                "mp speedup needs cores — on host_cpus=1 the forked "
                "workers time-slice one core and the ratio reads as IPC "
                "overhead, not the data plane's scaling",
    }


def bench_input_pipeline(batch_size: int = 256, steps: int = 30):
    """Host input pipeline for the ResNet-50 shape. Two strategies:

    - host_normalize: uint8 → vectorized f32 normalize on host → device_put
      (4 bytes/px over the wire);
    - device_normalize (the TPU-first path): ship raw uint8 (1 byte/px) and
      normalize on device, where XLA fuses it into the first conv for free.

    The headline value is the device_normalize rate — it must comfortably
    exceed the model's images/sec so the chip never starves. A second
    section A/Bs the per-record transform tiers (eager thread pool vs
    streaming vs the mp shared-memory pool) on a GIL-bound transform with
    a gather/transform/shard stage breakdown (``_gil_bound_ab``)."""
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.feature.device_feed import DeviceFeed
    from analytics_zoo_tpu.feature.preprocessing import BatchLambda
    import jax
    import jax.numpy as jnp

    ctx = init_tpu_context()
    n = batch_size * 4
    rs = np.random.RandomState(0)
    raw = rs.randint(0, 255, (n, 224, 224, 3), dtype=np.uint8)
    labels = rs.randint(0, 2, n).astype(np.float32)
    from analytics_zoo_tpu.models.image.imageclassification import (
        IMAGENET_MEAN as mean, IMAGENET_STD as std)

    def run(fs, device_fn=None):
        feed = DeviceFeed(fs.train_iterator(batch_size), ctx.mesh)
        try:
            x, y = next(feed)
            if device_fn is not None:
                x = device_fn(x)
            jax.block_until_ready(x)
            start = time.perf_counter()
            done = 0
            for x, y in feed:
                if device_fn is not None:
                    x = device_fn(x)
                jax.block_until_ready(x)
                done += 1
                if done >= steps:
                    break
            return batch_size * done / (time.perf_counter() - start)
        finally:
            feed.close()  # endless iterator: stop the producer thread

    host_fs = FeatureSet.from_ndarrays(raw, labels, shuffle=True).transform(
        BatchLambda(lambda b: (b.astype(np.float32) - mean) / std))
    host_rate = run(host_fs)

    dev_norm = jax.jit(
        lambda b: (b.astype(jnp.bfloat16) - mean.astype(jnp.bfloat16))
        / std.astype(jnp.bfloat16))
    dev_rate = run(FeatureSet.from_ndarrays(raw, labels, shuffle=True),
                   device_fn=dev_norm)

    # host-only rate (no device transfer): what the shuffle+gather path can
    # sustain — on a direct-attached chip THIS is the number that must beat
    # the model's consumption, the wire rates above are tunnel-bound
    host_fs2 = FeatureSet.from_ndarrays(raw, labels, shuffle=True)
    it = host_fs2.train_iterator(batch_size)
    next(it)
    t0 = time.perf_counter()
    for _ in range(steps):
        next(it)
    host_only_rate = batch_size * steps / (time.perf_counter() - t0)
    try:
        gil_ab = _gil_bound_ab(ctx.mesh)
    except Exception as e:  # the A/B must not lose the headline
        gil_ab = {"error": repr(e)[:200]}
    return _BenchResult(
        metric="input_pipeline_images_per_sec",
        value=round(dev_rate, 1),
        unit="images/s", mfu=None,
        detail={"batch_size": batch_size, "image": "224x224x3",
                "device_normalize_uint8_transfer": round(dev_rate, 1),
                "host_normalize_f32_transfer": round(host_rate, 1),
                "host_only_shuffle_gather": round(host_only_rate, 1),
                "includes": "shuffle+gather+device_put+normalize",
                "gil_transform_ab": gil_ab,
                "note": "bench-host bound: absolute rate tracks the TPU "
                        "tunnel's transfer bandwidth, which varies run to "
                        "run; the uint8-vs-f32 RATIO is the stable signal"})


def bench_etl_to_train(rows: int = 200_000, nparts: int = 8,
                       batch_size: int = 2048, epochs: int = 2):
    """Distributed ETL → training handoff: a synthetic table goes through
    the XShard engine (partition → per-partition transform wave →
    ``to_featureset``) and straight into ``Estimator.train``. Two paths:

    - slab (the zero-copy tentpole): ETL workers write partition rows
      into ONE shared feature/label segment the FeatureSet wraps —
      training batches read the bytes the workers wrote;
    - gather (``data.handoff='gather'``): the eager baseline — concat
      every partition in the driver, then copy again into feature
      arrays.

    The headline is the slab path's ingest→transform→train bytes/s; the
    record also carries the zero-copy vs eager-gather ratio with BIT
    parity of the resulting feature/label arrays asserted, plus a
    per-stage attribution recorded through the step-phase profiler
    (``loop="etl"`` series on the metrics page)."""
    import pandas as pd

    from analytics_zoo_tpu.common import metrics as zoo_metrics
    from analytics_zoo_tpu.common import profiler as zoo_profiler
    from analytics_zoo_tpu.common.config import global_config
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.xshard.engine import EtlEngine, XShard

    init_tpu_context()
    rs = np.random.RandomState(0)
    df = pd.DataFrame({
        "a": rs.rand(rows), "b": rs.rand(rows), "c": rs.rand(rows),
        "y": rs.rand(rows).astype(np.float32)})
    cfg = global_config()

    def run(mode):
        cfg.set("data.handoff", mode)
        eng = EtlEngine(num_workers=min(4, os.cpu_count() or 1))
        try:
            stages = {}
            t0 = time.perf_counter()
            xs = XShard.from_pandas(df, nparts, engine=eng)
            stages["partition"] = time.perf_counter() - t0
            t0 = time.perf_counter()
            xs = xs.map(lambda d: d.assign(z=d.a * d.b + d.c))
            stages["transform"] = time.perf_counter() - t0
            t0 = time.perf_counter()
            fs = xs.to_featureset(["a", "b", "c", "z"], "y")
            stages["handoff"] = time.perf_counter() - t0
            payload = (np.asarray(fs.features).nbytes
                       + np.asarray(fs.labels).nbytes)
            est = _ratio_estimator()
            t0 = time.perf_counter()
            est.train(fs, batch_size=batch_size, epochs=epochs)
            stages["train"] = time.perf_counter() - t0
            total = sum(stages.values())
            # feature/label copies survive engine close for the parity
            # assert (the slab views are engine-independent, but copies
            # make the comparison unambiguous)
            feats = np.asarray(fs.features).copy()
            labels = np.asarray(fs.labels).copy()
            return stages, total, payload, feats, labels
        finally:
            cfg.unset("data.handoff")
            eng.close()

    run("slab")  # warm: XLA compile of the train step, forks, allocators
    slab_stages, slab_s, payload, slab_x, slab_y = run("slab")
    _note_partial(metric="etl_to_train_bytes_per_sec",
                  value=round(payload / slab_s, 1), unit="bytes/s",
                  slab_pipeline_s=round(slab_s, 3))
    gather_stages, gather_s, _, gather_x, gather_y = run("gather")
    if not (np.array_equal(slab_x, gather_x)
            and np.array_equal(slab_y, gather_y)):
        raise RuntimeError("zero-copy handoff diverged from the eager "
                           "gather baseline")

    # stage attribution through the step-phase profiler: the etl loop's
    # phase series must land on the metrics page like train/eval phases
    zoo_profiler.set_enabled(True)
    try:
        for phase, seconds in slab_stages.items():
            zoo_profiler.record_phase("etl", phase, seconds)
    finally:
        zoo_profiler.set_enabled(False)
    expo = zoo_metrics.expose_text()
    profiler_ok = ("zoo_profile_phase_seconds" in expo
                   and 'loop="etl"' in expo and 'phase="handoff"' in expo)

    return _BenchResult(
        metric="etl_to_train_bytes_per_sec",
        value=round(payload / slab_s, 1),
        unit="bytes/s", mfu=None,
        detail={"rows": rows, "partitions": nparts,
                "feature_payload_mb": round(payload / 1e6, 2),
                "slab_stages_s": {k: round(v, 3)
                                  for k, v in slab_stages.items()},
                "gather_stages_s": {k: round(v, 3)
                                    for k, v in gather_stages.items()},
                "slab_pipeline_s": round(slab_s, 3),
                "gather_pipeline_s": round(gather_s, 3),
                "zero_copy_vs_gather_ratio": round(gather_s / slab_s, 2),
                "handoff_parity_ok": True,
                "profiler_etl_phases_ok": bool(profiler_ok),
                "note": "ratio compares identical pipelines differing "
                        "only in the handoff: shared-segment writes vs "
                        "driver concat + copy; parity is bitwise"})


def _bert_serving_rate(requests: int = 256, batch_size: int = 32,
                       seq_len: int = 128):
    """North-star #5 names ResNet AND BERT batch inference: token-tensor
    records through the same queue→claim→predict→writeback loop, BERT-base
    classifier on device. Median of 3 passes."""
    import tempfile

    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.capture.text import BERTClassifier, bert_input_pack
    from analytics_zoo_tpu.inference import InferenceModel
    from analytics_zoo_tpu.serving import ClusterServing, ServingConfig
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue

    cfg_b = dict(vocab=30522, hidden_size=768, n_block=12, n_head=12,
                 max_position_len=512, intermediate_size=3072,
                 compute_dtype=jnp.bfloat16)
    clf = BERTClassifier(2, bert_config=cfg_b)
    est = clf.model.get_estimator()
    rs = np.random.RandomState(0)
    sample = bert_input_pack(rs.randint(1, 30000, (batch_size, seq_len)))
    est._ensure_initialized(__import__(
        "analytics_zoo_tpu.parallel.mesh", fromlist=["shard_batch"]
    ).shard_batch(est.mesh, (sample, None))[0])

    def fwd(params, x):
        # wire records arrive as [seq] float32 token rows; rebuild the
        # 4-array BERT input inside the trace (bert_input_pack is
        # numpy/host-side)
        tokens = x.astype(jnp.int32)
        b, s = tokens.shape
        packed = [tokens,
                  jnp.zeros((b, s), jnp.int32),
                  jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s)),
                  (tokens != 0).astype(jnp.float32)]
        y, _ = est.model.call(params, est.model_state, packed,
                              training=False)
        return y

    im = InferenceModel(concurrent_num=2).load_jax(fwd, est.params)
    src = f"dir://{tempfile.mkdtemp(prefix='zoo_bench_bertserv_')}"
    cfg = ServingConfig(data_src=src, batch_size=batch_size,
                        batch_wait_ms=5, input_dtype="float32",
                        image_shape=(seq_len,))
    serving = ClusterServing(cfg, model=im)
    inq, outq = InputQueue(src), OutputQueue(src)
    toks = rs.randint(1, 30000, (batch_size, seq_len)).astype(np.float32)
    for i in range(batch_size):
        inq.enqueue_tensor(f"warm{i}", toks[i])
    warmed = 0
    while warmed < batch_size:
        warmed += serving.serve_once()
    outq.query(f"warm{batch_size - 1}", timeout_s=300)

    walls = []
    for tag in ("ba", "bb", "bc"):
        for i in range(requests):
            inq.enqueue_tensor(f"{tag}{i}", toks[i % batch_size])
        start = time.perf_counter()
        serving.start()
        assert outq.query(f"{tag}{requests - 1}",
                          timeout_s=600) is not None
        walls.append(time.perf_counter() - start)
        serving.stop()
    walls.sort()
    return {"bert_records_per_sec": round(requests / walls[1], 1),
            "bert_batch_size": batch_size, "bert_seq_len": seq_len,
            "bert_wall_scatter": [round(requests / w, 1) for w in walls]}


def bench_serving(requests: int = 512, batch_size: int = 64):
    """Cluster-serving batch inference (north-star #5): full queue → claim →
    predict → result-writeback loop over a file queue with a ResNet-50
    classifier on 224px jpg records, plus a BERT-base token-record
    sub-measurement — the reference's published serving pair."""
    import tempfile

    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.inference import InferenceModel
    from analytics_zoo_tpu.models.image.imageclassification import resnet
    from analytics_zoo_tpu.serving import ClusterServing, ServingConfig
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
    import jax

    init_tpu_context()
    # uint8 wire + on-device normalize: 4x less tunnel traffic per image
    model = resnet(50, num_classes=10, input_shape=(224, 224, 3),
                   preprocess="imagenet_uint8")
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")
    im = InferenceModel(concurrent_num=2).load_keras(
        model, *model.build(jax.random.PRNGKey(0)))
    src = f"dir://{tempfile.mkdtemp(prefix='zoo_bench_serving_')}"
    cfg = ServingConfig(data_src=src, batch_size=batch_size,
                        batch_wait_ms=5, input_dtype="uint8")
    serving = ClusterServing(cfg, model=im)
    rs = np.random.RandomState(0)
    # the serving wire contract ships ENCODED images (reference: base64 jpg
    # over redis), not raw float tensors
    images = [rs.randint(0, 255, (224, 224, 3), dtype=np.uint8)
              for _ in range(batch_size)]
    inq, outq = InputQueue(src), OutputQueue(src)
    # warm the compile at the REAL bucket (a full batch), not bucket 1
    for i in range(batch_size):
        inq.enqueue_image(f"warm{i}", images[i])
    warmed = 0
    while warmed < batch_size:
        warmed += serving.serve_once()
    outq.query(f"warm{batch_size - 1}", timeout_s=120)
    # pipelined loop: claim+decode thread / device dispatch / writeback
    # thread run concurrently (serving/server.py run()). The tunnel's RPC
    # latency swings 0.1-2s run to run: report the MEDIAN of three passes
    # with the scatter alongside (max-of-N would bias upward).
    def measure(tag):
        for i in range(requests):
            inq.enqueue_image(f"{tag}{i}", images[i % batch_size])
        dev0 = serving.device_seconds
        start = time.perf_counter()
        serving.start()
        assert outq.query(f"{tag}{requests - 1}", timeout_s=600) is not None
        wall = time.perf_counter() - start
        serving.stop()
        return wall, max(serving.device_seconds - dev0, 1e-9)

    passes = [measure(t) for t in ("ra", "rb", "rc")]
    walls = sorted(p[0] for p in passes)
    devs = sorted(p[1] for p in passes)
    elapsed = walls[1]  # median
    dev_secs = devs[1]
    _note_partial(metric="serving_records_per_sec",
                  value=round(requests / elapsed, 1), unit="records/s",
                  device_records_per_sec=round(requests / dev_secs, 1))
    try:
        if time.perf_counter() - _T0 > 400:
            raise RuntimeError("child budget: resnet serving too slow, "
                               "bert sub-bench skipped")
        bert = _bert_serving_rate()
    except Exception as e:  # the add-on must not lose the headline
        bert = {"bert_error": repr(e)[:200]}
    return _BenchResult(
        metric="serving_records_per_sec",
        value=round(requests / elapsed, 1),
        unit="records/s", mfu=None,
        detail={"model": "resnet50 224px", **bert,
                "batch_size": batch_size,
                "queue": "file", "payload": "encoded jpg (uint8 wire)",
                "includes": "claim+decode+predict+writeback (pipelined)",
                "device_records_per_sec": round(requests / dev_secs, 1),
                "wall_records_per_sec": round(requests / elapsed, 1),
                "loop": "median of 3 passes",
                "wall_scatter_records_per_sec": [
                    round(requests / w, 1) for w in walls],
                "note": "bench-host bound: the tunneled TPU adds ~0.1-2s "
                        "RPC latency per dispatch/fetch; on a directly "
                        "attached chip the same loop is compute-bound. "
                        "device_records_per_sec divides by the blocking "
                        "device-fetch time accumulated in the writeback "
                        "stage (dispatch and decode overlap it)"})




def bench_serving_slo(requests: int = 360, batch_size: int = 16):
    """Serving SLO layer under a synthetic overload ramp: enqueue at
    0.5x, 1.5x and 3x of the measured capacity (deadline-stamped
    requests), and report p50/p99 terminal latency, shed rate and
    deadline-miss rate from the deep-health surface. The ramp's sheds and
    deadline errors are the SLO layer doing its job — the invariant
    checked before any number is published is that EVERY request got
    exactly one terminal result (value or error)."""
    import tempfile

    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.inference import InferenceModel
    from analytics_zoo_tpu.serving import ClusterServing, ServingConfig
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue

    init_tpu_context()
    im = InferenceModel(concurrent_num=2).load_jax(
        lambda p, x: x.reshape(x.shape[0], -1).mean(1, keepdims=True), {})
    root = tempfile.mkdtemp(prefix="zoo_bench_slo_")
    src = f"dir://{root}"
    cfg = ServingConfig(data_src=src, image_shape=(64,),
                        batch_size=batch_size, batch_wait_ms=5,
                        input_dtype="float32",
                        max_pending=4 * batch_size,
                        default_deadline_ms=2000,
                        health_path=os.path.join(root, "health.json"),
                        health_interval_s=0.25)
    serving = ClusterServing(cfg, model=im)
    inq, outq = InputQueue(src), OutputQueue(src)
    rs = np.random.RandomState(0)
    vec = rs.rand(64).astype(np.float32)

    # capacity probe: warm + measure the synchronous serve rate
    n_probe = batch_size * 4
    for i in range(n_probe):
        inq.enqueue_tensor(f"probe{i}", vec)
    t0 = time.perf_counter()
    done = 0
    while done < n_probe:
        done += serving.serve_once()
    cap_rps = n_probe / max(time.perf_counter() - t0, 1e-9)

    serving.start()
    phases = (0.5, 1.5, 3.0)
    per_phase = requests // len(phases)
    total = per_phase * len(phases)
    t_ramp = time.perf_counter()
    k = 0
    for mult in phases:
        # open-loop bursts: real overload arrives in clumps, and per-
        # request sleep pacing can never outrun a fast host's capacity
        burst = max(1, int(mult * batch_size))
        gap = burst / max(cap_rps * mult, 1.0)
        sent = 0
        while sent < per_phase:
            n = min(burst, per_phase - sent)
            for _ in range(n):
                inq.enqueue_tensor(f"r{k}", vec, deadline_ms=2000)
                k += 1
            sent += n
            time.sleep(gap * n / burst)
    deadline = time.monotonic() + 120
    answered = {}
    while time.monotonic() < deadline and len(answered) < total:
        for uri, res in outq.dequeue().items():
            if uri.startswith("r"):
                answered[uri] = res
        time.sleep(0.05)
    wall = time.perf_counter() - t_ramp
    serving.drain(timeout_s=30)
    snap = serving.health_snapshot()
    if len(answered) != total:
        raise RuntimeError(
            f"SLO invariant violated: {total - len(answered)} of {total} "
            f"requests never received a terminal result")
    ok = sum(1 for r in answered.values() if "value" in r)
    shed = snap["counters"]["shed"]
    expired = snap["counters"]["expired"]
    # an empty latency window reads p50/p99 = null BY CONTRACT (see
    # docs/observability.md) — possible here only if every request shed
    # before claim; the headline metric must stay numeric for parsers
    p99 = snap["latency_ms"]["p99"]
    return _BenchResult(
        metric="serving_slo_p99_ms",
        value=p99 if p99 is not None else 0.0,
        unit="ms", mfu=None,
        detail={"requests": total, "batch_size": batch_size,
                "capacity_records_per_sec": round(cap_rps, 1),
                "ramp": "0.5x / 1.5x / 3x of measured capacity",
                "wall_records_per_sec": round(total / wall, 1),
                "p50_ms": snap["latency_ms"]["p50"],
                "p99_ms": snap["latency_ms"]["p99"],
                "latency_window": snap["latency_ms"]["window"],
                "served_ok": ok,
                "shed_rate": round(shed / total, 4),
                "deadline_miss_rate": round(expired / total, 4),
                "error_results": total - ok,
                "terminal_state": snap["state"],
                "note": "every request got exactly one terminal result "
                        "(gated before publishing); sheds and deadline "
                        "errors under the 3x phase are the admission "
                        "control working as designed — deadline_ms=2000, "
                        "max_pending=4 batches"})


def bench_serving_brownout(requests: int = 480, batch_size: int = 16):
    """Overload survival tier end to end: one ClusterServing instance
    driven at ~3x its measured capacity with a criticality-stamped mix
    (30% critical / 30% default / 40% sheddable). The critical class
    rides ResilientClient (retry budget + full-jitter backoff on
    retriable terminals); the other lanes are enqueued open-loop and
    absorb the sheds lane-priority-first. Reports critical-class goodput
    (the headline), per-lane goodput, the peak brownout rung the
    pressure controller reached, and the client's measured retry
    amplification — gated on the exactly-one-terminal invariant before
    any number is published (docs/serving.md "Overload survival")."""
    import tempfile
    import threading
    from concurrent.futures import ThreadPoolExecutor

    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.inference import InferenceModel
    from analytics_zoo_tpu.serving import ClusterServing, ServingConfig
    from analytics_zoo_tpu.serving.client import (InputQueue, OutputQueue,
                                                  ResilientClient)

    init_tpu_context()
    im = InferenceModel(concurrent_num=2).load_jax(
        lambda p, x: x.reshape(x.shape[0], -1).mean(1, keepdims=True), {})

    class StallModel:
        """Host stall dominates each batch so the overload phase outlives
        the shed cadence on any machine (the fleet-bench trick) — without
        it a fast CPU drains the whole ramp between two shed ticks and
        the brownout/shed machinery never engages."""

        STALL_S = 0.05

        def predict(self, x):
            time.sleep(self.STALL_S)
            return im.predict(x)

        def predict_async(self, x):
            f = im.predict_async(x)

            def fetch():
                time.sleep(self.STALL_S)
                return f()
            return fetch

    root = tempfile.mkdtemp(prefix="zoo_bench_brownout_")
    src = f"dir://{root}"
    cfg = ServingConfig(data_src=src, image_shape=(64,),
                        batch_size=batch_size, batch_wait_ms=5,
                        input_dtype="float32",
                        max_pending=2 * batch_size,
                        default_deadline_ms=2000,
                        health_interval_s=0.1)
    serving = ClusterServing(cfg, model=StallModel())
    inq, outq = InputQueue(src), OutputQueue(src)
    rs = np.random.RandomState(0)
    vec = rs.rand(64).astype(np.float32)

    # capacity probe: warm + measure the synchronous serve rate, one
    # batch-sized wave at a time so the probe stays under max_pending
    # (a shed probe record would never be "served" and the count-served
    # loop below would spin forever)
    def probe_wave(tag):
        for i in range(batch_size):
            inq.enqueue_tensor(f"probe{tag}-{i}", vec)
        got = 0
        while got < batch_size:
            got += serving.serve_once()

    probe_wave("warm")
    t0 = time.perf_counter()
    for w in range(3):
        probe_wave(w)
    cap_rps = 3 * batch_size / max(time.perf_counter() - t0, 1e-9)

    def lane_of(i):
        r = i % 10
        return ("critical" if r < 3 else
                "default" if r < 6 else "sheddable")

    serving.start()
    client = ResilientClient(src)
    lanes = {"critical": [], "default": [], "sheddable": []}
    answered, alock = {}, threading.Lock()

    def call_critical(uri):
        def enq(attempt_uri):
            inq.enqueue_tensor(attempt_uri, vec, deadline_ms=2000,
                               criticality="critical")
        res = client.call(uri, enq, timeout_s=60.0)
        with alock:
            answered[uri] = res

    peak_rung, sent = 0, 0
    gap = batch_size / max(cap_rps * 3.0, 1.0)   # ~3x offered rate
    t_ramp = time.perf_counter()
    with ThreadPoolExecutor(max_workers=8) as pool:
        while sent < requests:
            for _ in range(min(batch_size, requests - sent)):
                uri, lane = f"r{sent}", lane_of(sent)
                lanes[lane].append(uri)
                if lane == "critical":
                    pool.submit(call_critical, uri)
                else:
                    inq.enqueue_tensor(uri, vec, deadline_ms=2000,
                                       criticality=lane)
                sent += 1
            peak_rung = max(peak_rung,
                            serving.health_snapshot()["brownout_level"])
            time.sleep(gap)
        # sequential long-polls: get_result is non-destructive, so the
        # client threads' own polling is never robbed of a terminal
        for lane in ("default", "sheddable"):
            for uri in lanes[lane]:
                answered[uri] = outq.query(uri, timeout_s=120)
            peak_rung = max(peak_rung,
                            serving.health_snapshot()["brownout_level"])
    wall = time.perf_counter() - t_ramp
    serving.drain(timeout_s=30)
    snap = serving.health_snapshot()
    missing = [u for us in lanes.values() for u in us
               if answered.get(u) is None]
    if missing:
        raise RuntimeError(
            f"overload invariant violated: {len(missing)} of {requests} "
            f"requests never received a terminal result")
    good = {lane: sum(1 for u in us if "value" in answered[u])
            for lane, us in lanes.items()}
    n_crit = len(lanes["critical"])
    amp = client.attempts_sent / max(client.requests_sent, 1)
    return _BenchResult(
        metric="serving_brownout_critical_goodput",
        value=round(good["critical"] / max(n_crit, 1), 4),
        unit="ratio", mfu=None,
        detail={"requests": requests, "batch_size": batch_size,
                "capacity_records_per_sec": round(cap_rps, 1),
                "offered": "~3x measured capacity, "
                           "30/30/40 critical/default/sheddable",
                "wall_records_per_sec": round(requests / wall, 1),
                "goodput_critical": good["critical"],
                "goodput_default": good["default"],
                "goodput_sheddable": good["sheddable"],
                "offered_critical": n_crit,
                "peak_brownout_level": peak_rung,
                "retry_amplification": round(amp, 3),
                "shed_total": snap["counters"]["shed"],
                "deadline_miss_total": snap["counters"]["expired"],
                "terminal_state": snap["state"],
                "note": "every request got exactly one terminal result "
                        "(gated before publishing); sheds land on the "
                        "sheddable lane first and the retry budget "
                        "bounds amplification at 1 + "
                        "client.retry_budget_ratio"})


def _fleet_server_proc(root: str, name: str, stall_s: float,
                       batch_size: int, done_q):
    """Subprocess: one fleet instance — ClusterServing on its private
    spool under ``<root>/inst/<name>`` whose results land in the FRONT
    result store, health file on a fast cadence so the router sees live
    gauges (and a SIGKILL as a frozen, aging file). Serves until the DONE
    flag appears; a ``RELOAD_<name>`` flag triggers one hot
    ``reload_model`` mid-traffic (the rolling-deploy leg)."""
    import jax
    jax.config.update("jax_platforms", "cpu")

    from analytics_zoo_tpu.inference import InferenceModel
    from analytics_zoo_tpu.serving import ClusterServing, ServingConfig
    from analytics_zoo_tpu.serving.fleet import instance_queue

    def fwd(p, x):
        return x.reshape(x.shape[0], -1).mean(1, keepdims=True)

    def stall_model():
        im = InferenceModel().load_jax(fwd, {})

        class StallModel:
            """Host stall dominates the batch so fleet scaling is
            measurable on any machine (the multiserver-test trick)."""

            def predict(self, x):
                time.sleep(stall_s)
                return im.predict(x)

            def predict_async(self, x):
                f = im.predict_async(x)

                def fetch():
                    time.sleep(stall_s)
                    return f()
                return fetch
        return StallModel()

    cfg = ServingConfig(data_src=f"dir://{root}/inst/{name}",
                        batch_size=batch_size, batch_wait_ms=2,
                        input_dtype="float32",
                        health_path=os.path.join(root,
                                                 f"{name}.health.json"),
                        health_interval_s=0.1)
    srv = ClusterServing(cfg, model=stall_model(),
                         queue=instance_queue(root, name))
    with open(os.path.join(root, f"READY_{name}"), "w") as f:
        f.write("1")
    served, reloads = 0, 0
    deadline = time.time() + 600
    while time.time() < deadline:
        if reloads == 0 and os.path.exists(
                os.path.join(root, f"RELOAD_{name}")):
            srv.reload_model(model=stall_model())
            reloads += 1
        n = srv.serve_once()
        served += n
        if not n:
            if os.path.exists(os.path.join(root, "DONE")):
                break
            time.sleep(0.005)
    done_q.put((name, served, reloads))


def bench_serving_fleet(requests: int = 1200, batch_size: int = 4,
                        stall_s: float = 0.08):
    """Fleet tier end to end (docs/fleet.md): three REAL server processes
    behind one telemetry-driven FleetRouter, with a mid-run SIGKILL of
    one instance (its claimed work re-placed from the failover map, its
    spool reclaimed, a warm standby registered in its place) and a
    rolling ``reload_model`` on a second instance. Headline = sustained
    routed throughput over a single-instance baseline at the same
    offered load — gated on the invariant that EVERY request got exactly
    one terminal result, kill and reload included. A second leg routes
    generative streams across two in-process schedulers and kills one
    mid-decode: the orphaned streams must finish on the survivor via
    prefix continuation (tokens/s + failover count in detail)."""
    import multiprocessing as mp
    import signal
    import tempfile

    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.serving import (FleetInstance, FleetRouter,
                                           fleet as zfleet)
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
    from analytics_zoo_tpu.serving.fleet import instance_queue
    from analytics_zoo_tpu.serving.queues import FileQueue

    init_tpu_context()
    ctx = mp.get_context("spawn")
    rs = np.random.RandomState(0)
    vec = rs.rand(64).astype(np.float32)

    def spawn(root: str, names) -> dict:
        done_q = ctx.Queue()
        procs = {nm: ctx.Process(target=_fleet_server_proc,
                                 args=(root, nm, stall_s, batch_size,
                                       done_q))
                 for nm in names}
        for p in procs.values():
            p.start()
        deadline = time.time() + 180
        while time.time() < deadline:
            if all(os.path.exists(os.path.join(root, f"READY_{nm}"))
                   for nm in names):
                break
            time.sleep(0.05)
        return {"procs": procs, "done_q": done_q}

    def finish(root: str, fleet: dict) -> dict:
        with open(os.path.join(root, "DONE"), "w") as f:
            f.write("1")
        reports = {}
        live = [p for p in fleet["procs"].values() if p.is_alive()]
        for _ in live:
            nm, served, reloads = fleet["done_q"].get(timeout=60)
            reports[nm] = {"served": served, "reloads": reloads}
        for p in fleet["procs"].values():
            p.join(timeout=30)
        return reports

    def drive(root: str, names, n: int, kill: str = "",
              reload_on: str = "", standby: str = "") -> dict:
        """Enqueue n deadline-stamped requests to the front and run the
        router inline until every terminal lands. The kill fires at 35%
        answered (standby registered with the router in the same pass),
        the rolling reload at 55%."""
        fleet = spawn(root, list(names) + ([standby] if standby else []))
        front = FileQueue(root)
        insts = {nm: FleetInstance(
            nm, instance_queue(root, nm),
            os.path.join(root, f"{nm}.health.json"))
            for nm in list(names) + ([standby] if standby else [])}
        router = FleetRouter(front,
                             [insts[nm] for nm in names],
                             stale_after_s=0.5, health_refresh_s=0.1,
                             # operator-tuned cold-start estimate: an
                             # instance with no service history yet (the
                             # warm standby) scores at the fleet's known
                             # per-record time instead of a pessimistic
                             # default that starves it of its fair share
                             default_service_s=stall_s / batch_size)
        inq = InputQueue(f"dir://{root}")
        outq = OutputQueue(f"dir://{root}")
        res_dir = os.path.join(root, "results")

        def n_results() -> int:
            # file COUNT only — parsing every result json each poll would
            # put an O(results^2) read loop inside the timed region
            try:
                return sum(1 for f in os.listdir(res_dir)
                           if not f.startswith("."))
            except FileNotFoundError:
                return 0

        t0 = time.perf_counter()
        for i in range(n):
            inq.enqueue_tensor(f"r{i}", vec, deadline_ms=120_000)
        killed = reloaded = False
        deadline = time.time() + 420
        done = 0
        while time.time() < deadline and done < n:
            router.route_once()
            done = n_results()
            if kill and not killed and done >= 0.35 * n:
                os.kill(fleet["procs"][kill].pid, signal.SIGKILL)
                # the fleet answer to a dead instance: register the warm
                # standby; the router reclaims the victim's spool and
                # re-places its claimed-but-unanswered work
                router.instances.append(insts[standby])
                router._last_refresh = -1e18
                killed = True
            if reload_on and not reloaded and done >= 0.55 * n:
                with open(os.path.join(root, f"RELOAD_{reload_on}"),
                          "w") as f:
                    f.write("1")
                reloaded = True
            time.sleep(0.005)
        wall = time.perf_counter() - t0
        answered = {u: r for u, r in outq.dequeue().items()
                    if u.startswith("r")}
        reports = finish(root, fleet)
        router.stop()
        if len(answered) != n:
            raise RuntimeError(
                f"fleet invariant violated: {n - len(answered)} of {n} "
                f"requests never received a terminal result")
        errors = sum(1 for r in answered.values() if "error" in r)
        return {"rps": n / wall, "errors": errors, "reports": reports}

    # -- single-instance baseline at the same offered load ---------------
    single = drive(tempfile.mkdtemp(prefix="zoo_fleet_one_"), ["s0"],
                   max(batch_size * 10, requests // 3))
    _note_partial(single_records_per_sec=round(single["rps"], 1))
    # -- 3 instances + mid-run SIGKILL + rolling reload + warm standby ----
    routed = drive(tempfile.mkdtemp(prefix="zoo_fleet_three_"),
                   ["a", "b", "c"], requests,
                   kill="a", reload_on="b", standby="d")
    speedup = routed["rps"] / max(single["rps"], 1e-9)
    reloads = sum(r["reloads"] for r in routed["reports"].values())
    _note_partial(metric="serving_fleet_speedup",
                  value=round(speedup, 2), unit="x",
                  routed3_records_per_sec=round(routed["rps"], 1))

    # -- generative leg: routed streams + mid-decode kill = continuation -
    from analytics_zoo_tpu.capture.lm import TransformerLM
    from analytics_zoo_tpu.serving import GenerativeServing, ServingConfig
    lm = TransformerLM(vocab_size=128, hidden=32, n_block=2, n_head=2,
                       max_len=64, seed=0)
    lm.fit(rs.randint(0, 128, (32, 12)), batch_size=8, epochs=1)
    groot = tempfile.mkdtemp(prefix="zoo_fleet_gen_")
    gfront = FileQueue(groot)
    gsrvs, ginsts = [], []
    for nm in ("ga", "gb"):
        q = instance_queue(groot, nm)
        hp = os.path.join(groot, f"{nm}.health.json")
        gsrvs.append(GenerativeServing(
            ServingConfig(data_src=f"dir://{groot}/inst/{nm}", slots=4,
                          max_new_tokens=16, stream_interval=2,
                          health_path=hp, health_interval_s=0.02),
            lm, queue=q))
        # slots=4 so the 24 streams decode in overlapping waves — the
        # kill lands while the victim holds mid-flight streams whose
        # partials become failover prefixes
        ginsts.append(FleetInstance(nm, q, hp, slots=4))
    # prewarm OFF the routed path: the first decode step per prefill
    # bucket cold-compiles for seconds, which would freeze health long
    # enough for the router to declare a busy-compiling instance dead.
    # Warm the buckets continuation re-prefill can hit (prompt alone and
    # prompt+prefix) the way ClusterServing prewarms before traffic.
    for srv, inst in zip(gsrvs, ginsts):
        for j, plen in enumerate((5, 12, 20)):
            inst.queue.enqueue(f"warm_{inst.name}_{j}",
                               {"prompt": rs.randint(0, 128,
                                                     (plen,)).tolist(),
                                "max_new_tokens": 2})
        for _ in range(64):
            if not srv.serve_step() and not inst.queue.pending_count():
                break
    for srv in gsrvs:
        # one idle step each AFTER both prewarms: the first server's
        # health would otherwise be a prewarm-duration old when the
        # router takes its first snapshot — and look dead on arrival
        srv.serve_step()
    grouter = FleetRouter(gfront, ginsts, stale_after_s=0.5,
                          health_refresh_s=0.05)
    ginq = InputQueue(f"dir://{groot}")
    goutq = OutputQueue(f"dir://{groot}")
    n_streams, new_tokens = 24, 16
    failovers0 = zfleet._M_FAILOVERS.value()
    t0 = time.perf_counter()
    for i in range(n_streams):
        ginq.enqueue_prompt(f"g{i}", rs.randint(0, 128, (5,)).tolist(),
                            max_new_tokens=new_tokens)
    dead = False
    terminals = {}
    deadline = time.time() + 240
    while time.time() < deadline and len(terminals) < n_streams:
        grouter.route_once()
        for s in (gsrvs[1:] if dead else gsrvs):
            s.serve_step()
        results = {u: r for u, r in goutq.dequeue().items()
                   if u.startswith("g")}
        terminals = {u: r for u, r in results.items()
                     if "value" in r or "error" in r}
        mid_flight = any(4 <= len(r.get("stream") or []) <= 10
                         for r in results.values()
                         if not r.get("done", True))
        if not dead and len(terminals) >= n_streams // 4 and mid_flight:
            dead = True  # SIGKILL equivalent, deliberately MID-wave (a
            #   partial with 4..10 of 16 tokens is in flight): ga stops
            #   stepping with streams resident in its slots; its frozen
            #   health ages out and the router re-places the orphans
            #   WITH their accumulated token prefixes
    gwall = time.perf_counter() - t0
    grouter.stop()
    if len(terminals) != n_streams:
        raise RuntimeError(
            f"fleet invariant violated (generative leg): "
            f"{n_streams - len(terminals)} of {n_streams} streams never "
            f"received a terminal result")
    gen_failovers = int(zfleet._M_FAILOVERS.value() - failovers0)
    gen_errors = sum(1 for r in terminals.values() if "error" in r)

    return _BenchResult(
        metric="serving_fleet_speedup", value=round(speedup, 2),
        unit="x", mfu=None,
        detail={"requests": requests, "batch_size": batch_size,
                "stall_s": stall_s,
                "single_records_per_sec": round(single["rps"], 1),
                "routed3_records_per_sec": round(routed["rps"], 1),
                "speedup_vs_single": round(speedup, 2),
                "mid_run_kill": "a (SIGKILL at 35% answered; warm "
                                "standby d registered)",
                "rolling_reloads": reloads,
                "error_results": routed["errors"],
                "per_instance_served": {nm: r["served"] for nm, r in
                                        routed["reports"].items()},
                "gen_streams": n_streams,
                "gen_tokens_per_sec": round(
                    n_streams * new_tokens / gwall, 1),
                "gen_failovers": gen_failovers,
                "gen_error_results": gen_errors,
                "note": "every request got exactly one terminal result "
                        "(gated before publishing) across the SIGKILL, "
                        "the spool reclaim + re-placement, and the "
                        "rolling reload; the generative leg's orphaned "
                        "streams finished on the survivor via "
                        "token-identical prefix continuation"})


class _FakeStreamRedis:
    """Minimal in-process stand-in for the redis stream surface RedisQueue
    drives (XADD / XREADGROUP '>' / XACK / result hashes): the outage-round
    CPU probe runs the SAME consumer-group claim/ack machinery when no
    server is reachable. XAUTOCLAIM/XINFO are deliberately absent —
    RedisQueue degrades past them the same way it does on an old server."""

    def __init__(self):
        import threading
        self._lock = threading.Lock()
        self._streams = {}  # stream -> [(entry id, encoded fields)]
        self._cursor = {}   # (stream, group) -> next undelivered index
        self._hashes = {}
        self._seq = 0

    def xgroup_create(self, stream, group, mkstream=False):
        with self._lock:
            self._streams.setdefault(stream, [])
            self._cursor.setdefault((stream, group), 0)

    def xadd(self, stream, fields):
        with self._lock:
            self._seq += 1
            eid = f"{self._seq}-0".encode()
            enc = {(k if isinstance(k, bytes) else str(k).encode()):
                   (v if isinstance(v, bytes) else str(v).encode())
                   for k, v in fields.items()}
            self._streams.setdefault(stream, []).append((eid, enc))
            return eid

    def xreadgroup(self, group, consumer, streams, count=None, block=None):
        out = []
        with self._lock:
            for stream in streams:
                entries = self._streams.get(stream, [])
                cur = self._cursor.setdefault((stream, group), 0)
                take = entries[cur:cur + (count or len(entries))]
                if take:
                    self._cursor[(stream, group)] = cur + len(take)
                    out.append((stream.encode(), list(take)))
        return out

    def xack(self, stream, group, *ids):
        return len(ids)

    def xlen(self, stream):
        with self._lock:
            return len(self._streams.get(stream, []))

    def hset(self, key, mapping):
        with self._lock:
            h = self._hashes.setdefault(key, {})
            for k, v in mapping.items():
                h[k if isinstance(k, bytes) else str(k).encode()] = (
                    v if isinstance(v, bytes) else str(v).encode())

    def hgetall(self, key):
        with self._lock:
            return dict(self._hashes.get(key, {}))

    def pipeline(self):
        outer = self

        class _Pipe:
            def __init__(self):
                self.ops = []

            def xadd(self, stream, fields):
                self.ops.append((stream, fields))

            def execute(self):
                for stream, fields in self.ops:
                    outer.xadd(stream, fields)
                self.ops = []

        return _Pipe()


def _fleet_redis_client(require: bool):
    """A reachable server (``ZOO_BENCH_REDIS=host:port``, default
    localhost:6379) or — when ``require`` is off — the in-process stream
    fake, so outage rounds still exercise the consumer-group machinery."""
    spec = os.environ.get("ZOO_BENCH_REDIS") or "localhost:6379"
    host, _, port = spec.partition(":")
    try:
        import redis
        cli = redis.StrictRedis(host=host, port=int(port or 6379), db=0,
                                socket_connect_timeout=1.0,
                                socket_timeout=5.0)
        cli.ping()
        return cli, f"redis://{host}:{int(port or 6379)}"
    except Exception as e:
        if require:
            raise RuntimeError(
                f"serving_fleet_redis needs a reachable redis server "
                f"(ZOO_BENCH_REDIS=host:port): {e}; outage rounds land "
                f"via --ratio against the in-process stream fake") from e
        return _FakeStreamRedis(), f"in-process fake ({e.__class__.__name__})"


def _consumer_group_ab(client, n: int, stall_s: float, batch_size: int,
                       k: int, die_after_claim: bool = False,
                       claim_lease_s=None):
    """Drive n requests through ONE shared stream with a consumer group of
    k RedisQueue consumers (XREADGROUP '>' = exactly-one-consumer
    delivery; XACK only after the result hash lands). ``die_after_claim``
    kills consumer 0 right after its first claim, before it acks — the
    abandoned batch must come back via XAUTOCLAIM redelivery onto a
    survivor. Returns (wall seconds, per-consumer claim counts)."""
    import threading
    import uuid

    from analytics_zoo_tpu.serving.queues import RedisQueue

    stream = f"bench:fleet:{uuid.uuid4().hex[:8]}"
    front = RedisQueue(client=client, stream=stream, group="bench",
                       claim_lease_s=claim_lease_s)
    front.enqueue_many([(f"u{i}", {"value": [0.0]}) for i in range(n)])
    claims = [0] * k
    stop = threading.Event()

    def worker(idx: int):
        q = RedisQueue(client=client, stream=stream, group="bench",
                       claim_lease_s=claim_lease_s)
        while not stop.is_set():
            got = q.claim_batch(batch_size)
            if not got:
                time.sleep(0.001)
                continue
            claims[idx] += len(got)
            if die_after_claim and idx == 0:
                return  # claimed, never acked: the group's PEL holds it
            time.sleep(stall_s)  # one model batch per claim
            for uri, _rec in got:
                q.put_result(uri, {"value": [1.0]})

    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(k)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    done, deadline = 0, time.time() + 180
    while done < n and time.time() < deadline:
        done = sum(1 for i in range(n)
                   if front.get_result(f"u{i}") is not None)
        time.sleep(0.02)
    wall = time.perf_counter() - t0
    stop.set()
    for t in threads:
        t.join(timeout=10)
    if done < n:
        raise RuntimeError(
            f"consumer group dropped requests: {done}/{n} answered "
            f"(k={k}, die_after_claim={die_after_claim})")
    return wall, claims


def bench_serving_fleet_redis(requests: int = 900, batch_size: int = 4,
                              stall_s: float = 0.08):
    """serving_fleet's cross-host leg over the reference wire contract
    (XADD to one shared stream, consumer-group reads, HSET results): 3
    consumers vs 1 at the same offered load, with a mid-run consumer
    death that abandons a claimed-but-unacked batch — the entries sit in
    the group's PEL until XAUTOCLAIM redelivers them to a survivor, so
    the run still ends exactly-one-terminal (result writes are
    idempotent). Needs a reachable server (``ZOO_BENCH_REDIS``); outage
    rounds land a record via the --ratio probe, which runs the same
    machinery against the in-process stream fake."""
    client, backend = _fleet_redis_client(require=True)
    n = requests
    t1, _ = _consumer_group_ab(client, n, stall_s, batch_size, 1)
    single_rps = n / t1
    _note_partial(metric="serving_fleet_redis_speedup",
                  single_consumer_records_per_sec=round(single_rps, 1))
    t3, claims = _consumer_group_ab(client, n, stall_s, batch_size, 3,
                                    die_after_claim=True, claim_lease_s=1.0)
    speedup = t1 / max(t3, 1e-9)
    redelivered = sum(claims) - n  # the dead consumer's abandoned claims
    return _BenchResult(
        metric="serving_fleet_redis_speedup", value=round(speedup, 2),
        unit="x", mfu=None,
        detail={"backend": backend, "requests": n,
                "batch_size": batch_size, "stall_s": stall_s,
                "single_consumer_records_per_sec": round(single_rps, 1),
                "group3_records_per_sec": round(n / t3, 1),
                "per_consumer_claims": claims,
                "redelivered_after_consumer_death": redelivered,
                "note": "consumer 0 dies after its first claim without "
                        "acking; XAUTOCLAIM hands the abandoned entries "
                        "to a survivor past the 1s lease — every request "
                        "still got exactly one terminal result"})


def _kv_pool_hbm_gb(lm, num_pages: int, page_len: int,
                    int8: bool = False) -> float:
    """Paged KV pool HBM footprint across all blocks, in GB (int8 pools
    add the per-position f32 scale sidecar)."""
    elems = num_pages * lm.n_head * page_len * (lm.hidden // lm.n_head)
    payload = elems * (1 if int8 else 4)
    if int8:
        payload += num_pages * page_len * 4 * 2  # scale_k + scale_v
    return lm.n_block * 2 * payload / 1e9


def bench_generate(streams=(8, 32, 128), max_new_tokens: int = 32,
                   prompt_len: int = 9, paged_streams: int = 512):
    """Token-level continuous batching through the generative scheduler:
    N concurrent streams share a fixed pool of 32 KV slots, joining and
    leaving the fused decode step as they start/finish. Reports end-to-end
    tokens/s and p99 TTFT at 8/32/128 concurrent streams — the 128 level
    exercises mid-stream joins (4 generations of requests through the same
    slots). A final 512-stream level runs the PAGED KV engine (512
    resident slots backed by a page pool sized to actual stream lengths,
    not 512 x max_len rectangles) and reports the headline HBM-efficiency
    figure ``tokens_per_s_per_hbm_gb`` (baseline-tracked)."""
    import tempfile

    from analytics_zoo_tpu.capture.lm import TransformerLM
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.serving import GenerativeServing, ServingConfig
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue

    init_tpu_context()
    rs = np.random.RandomState(0)
    lm = TransformerLM(vocab_size=512, hidden=128, n_block=2, n_head=4,
                       max_len=64, seed=0)
    lm.fit(rs.randint(0, 512, (64, 24)), batch_size=16, epochs=1)
    src = f"dir://{tempfile.mkdtemp(prefix='zoo_bench_generate_')}"
    cfg = ServingConfig(data_src=src, slots=32,
                        max_new_tokens=max_new_tokens)
    srv = GenerativeServing(cfg, lm)
    inq, outq = InputQueue(src), OutputQueue(src)
    n_prompts = max(max(streams), paged_streams)
    prompts = [rs.randint(0, 512, (prompt_len,)).tolist()
               for _ in range(n_prompts)]
    # warm the prefill bucket + the fused step compile before timing
    inq.enqueue_prompt("warm", prompts[0])
    srv.start()
    assert outq.query("warm", timeout_s=600) is not None
    detail = {"slots": 32, "max_new_tokens": max_new_tokens,
              "prompt_len": prompt_len, "model": "tiny TransformerLM"}
    for c in streams:
        t0 = time.perf_counter()
        for i in range(c):
            inq.enqueue_prompt(f"c{c}_{i}", prompts[i])
        for i in range(c):
            assert outq.query(f"c{c}_{i}", timeout_s=600) is not None
        wall = time.perf_counter() - t0
        snap = srv.health_snapshot()
        detail[f"tokens_per_sec_c{c}"] = round(
            c * max_new_tokens / wall, 1)
        detail[f"ttft_p99_ms_c{c}"] = snap["ttft_ms"]["p99"]
        _note_partial(metric="generate_tokens_per_sec",
                      value=detail[f"tokens_per_sec_c{c}"],
                      unit="tokens/s", **detail)
    srv.drain(timeout_s=60)
    snap = srv.health_snapshot()
    detail["tokens_total"] = snap["tokens_total"]
    detail["terminal_state"] = snap["state"]
    # -- paged KV level: every stream resident at once, pool-backed -------
    page_len = 16
    per_stream = -(-max(16, prompt_len + max_new_tokens) // page_len)
    kv_pages = paged_streams * per_stream + 1
    psrc = f"dir://{tempfile.mkdtemp(prefix='zoo_bench_paged_')}"
    pcfg = ServingConfig(data_src=psrc, slots=paged_streams,
                         max_new_tokens=max_new_tokens,
                         kv_pages=kv_pages, kv_page_len=page_len)
    psrv = GenerativeServing(pcfg, lm)
    pinq, poutq = InputQueue(psrc), OutputQueue(psrc)
    pinq.enqueue_prompt("warm", prompts[0])
    psrv.start()
    assert poutq.query("warm", timeout_s=600) is not None
    c = paged_streams
    t0 = time.perf_counter()
    for i in range(c):
        pinq.enqueue_prompt(f"p{i}", prompts[i])
    for i in range(c):
        assert poutq.query(f"p{i}", timeout_s=600) is not None
    wall = time.perf_counter() - t0
    psnap = psrv.health_snapshot()
    psrv.drain(timeout_s=60)
    hbm_gb = _kv_pool_hbm_gb(lm, kv_pages, page_len)
    detail[f"tokens_per_sec_c{c}"] = round(c * max_new_tokens / wall, 1)
    detail[f"ttft_p99_ms_c{c}"] = psnap["ttft_ms"]["p99"]
    detail["paged_streams"] = c
    detail["kv_pages"] = kv_pages
    detail["kv_page_len"] = page_len
    detail["kv_pool_hbm_gb"] = round(hbm_gb, 6)
    detail["tokens_per_s_per_hbm_gb"] = round(
        detail[f"tokens_per_sec_c{c}"] / hbm_gb, 1)
    detail["note"] = ("end-to-end over the file queue (enqueue → slot "
                      "join → fused decode step → partial stream → "
                      "terminal); ttft_p99 per level reads the rolling "
                      "histogram window after that level; the 512 level "
                      "runs the paged KV engine with every stream "
                      "resident and tokens_per_s_per_hbm_gb divides its "
                      "throughput by the page-pool footprint")
    return _BenchResult(
        metric="generate_tokens_per_sec",
        value=detail.get(f"tokens_per_sec_c{streams[1]}"),
        unit="tokens/s", mfu=None, detail=detail)


# v5e per-chip HBM capacity (GB): the budget tp_decode's
# exceeds-one-device assertion is judged against on real rounds
_DEVICE_HBM_GB = 16.0


def bench_tp_decode(streams: int = 64, max_new_tokens: int = 32,
                    prompt_len: int = 9):
    """Sharded-KV decode for a generative model ONE device cannot hold:
    every stream reserves its full ``max_len`` context in the paged pool,
    the pool's PAGE axis shards over ``kv_shard`` devices, and the fused
    step gathers each stream's pages to the compute device — so the
    serving tier carries a KV footprint that provably exceeds a single
    chip's HBM while staying token-identical to the unsharded engine.
    The premise is ASSERTED before timing: (KV pool + replicated params)
    must exceed one device's budget, and the per-device share after
    sharding must fit. A CPU smoke run asserts the same arithmetic
    against a budget scaled to the cpu-sized model (detail carries the
    budget it was judged against)."""
    import tempfile

    import jax
    from analytics_zoo_tpu.capture.lm import TransformerLM
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.serving import GenerativeServing, ServingConfig
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue

    init_tpu_context()
    n_dev = jax.local_device_count()
    kv_shard = max(d for d in (8, 4, 2, 1)
                   if d <= n_dev and n_dev % d == 0)
    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        budget_gb = _DEVICE_HBM_GB
        lm = TransformerLM(vocab_size=32000, hidden=2048, n_block=8,
                           n_head=16, max_len=2048, seed=0)
    else:
        # cpu-sized model, same assertion arithmetic at a scaled budget
        budget_gb = 0.004
        lm = TransformerLM(vocab_size=512, hidden=128, n_block=2,
                           n_head=4, max_len=64, seed=0)
    page_len = 16
    kv_pages = streams * (lm.max_len // page_len) + 1
    kv_pages += (-kv_pages) % kv_shard  # PAGE axis shards evenly
    rs = np.random.RandomState(0)
    lm.fit(rs.randint(0, lm.vocab_size, (64, 24)), batch_size=16,
           epochs=1)

    params_gb = sum(l.nbytes for l in
                    jax.tree_util.tree_leaves(lm.params)) / 1e9
    kv_gb = _kv_pool_hbm_gb(lm, kv_pages, page_len)
    total_gb = kv_gb + params_gb
    if total_gb <= budget_gb:
        raise AssertionError(
            f"tp_decode premise broken: KV pool ({kv_gb:.4f} GB) + params "
            f"({params_gb:.4f} GB) = {total_gb:.4f} GB fits one device's "
            f"{budget_gb:.4f} GB budget — nothing to shard")
    per_device_gb = kv_gb / kv_shard + params_gb  # params replicated
    if kv_shard > 1 and per_device_gb > budget_gb:
        raise AssertionError(
            f"tp_decode sizing broken: per-device share "
            f"{per_device_gb:.4f} GB still exceeds the {budget_gb:.4f} GB "
            f"budget at kv_shard={kv_shard}")
    _note_partial(metric="tp_decode_tokens_per_sec", value=None,
                  unit="tokens/s", kv_shard=kv_shard, kv_pages=kv_pages,
                  kv_pool_hbm_gb=round(kv_gb, 6),
                  params_hbm_gb=round(params_gb, 6),
                  hbm_budget_gb=budget_gb,
                  hbm_exceeds_one_device=True)

    src = f"dir://{tempfile.mkdtemp(prefix='zoo_bench_tp_decode_')}"
    cfg = ServingConfig(data_src=src, slots=streams,
                        max_new_tokens=max_new_tokens, kv_pages=kv_pages,
                        kv_page_len=page_len, kv_shard=kv_shard)
    srv = GenerativeServing(cfg, lm)
    inq, outq = InputQueue(src), OutputQueue(src)
    prompts = [rs.randint(0, lm.vocab_size, (prompt_len,)).tolist()
               for _ in range(streams)]
    inq.enqueue_prompt("warm", prompts[0])  # compile before timing
    srv.start()
    assert outq.query("warm", timeout_s=600) is not None
    t0 = time.perf_counter()
    for i in range(streams):
        inq.enqueue_prompt(f"s{i}", prompts[i])
    for i in range(streams):
        assert outq.query(f"s{i}", timeout_s=600) is not None
    wall = time.perf_counter() - t0
    snap = srv.health_snapshot()
    srv.drain(timeout_s=60)

    toks = round(streams * max_new_tokens / wall, 1)
    # analytic roofline for the fused step (XLA's cost analysis cannot
    # see through the scheduler loop): every step re-reads the replicated
    # params plus on average half of each stream's resident KV
    head_dim = lm.hidden // lm.n_head
    kv_read = (streams * lm.n_block * 2 * (lm.max_len // 2)
               * lm.n_head * head_dim * 4)
    bytes_step = params_gb * 1e9 + kv_read
    flops = streams * 2.0 * (params_gb * 1e9 / 4)
    mfu = _mfu(flops, max_new_tokens, wall)
    roofline = _roofline_fields(flops, bytes_step, wall, max_new_tokens)
    return _BenchResult(
        metric="tp_decode_tokens_per_sec", value=toks, unit="tokens/s",
        mfu=mfu,
        detail={"streams": streams, "max_new_tokens": max_new_tokens,
                "kv_shard": kv_shard, "kv_pages": kv_pages,
                "kv_page_len": page_len,
                "kv_pool_hbm_gb": round(kv_gb, 6),
                "params_hbm_gb": round(params_gb, 6),
                "total_hbm_gb": round(total_gb, 6),
                "per_device_hbm_gb": round(per_device_gb, 6),
                "hbm_budget_gb": budget_gb,
                "hbm_budget_is_device": bool(on_tpu),
                "hbm_exceeds_one_device": True,   # asserted above
                "sharded_fits_ok": bool(kv_shard > 1) or None,
                "kv_shards_reported": snap.get("kv_shards"),
                "kv_pages_free_min_shard":
                    snap.get("kv_pages_free_min_shard"),
                "ttft_p99_ms": snap["ttft_ms"]["p99"],
                "roofline_note": "analytic accounting (params + half the "
                                 "resident KV per fused step); decode is "
                                 "bytes-bound — judge by "
                                 "hbm_roofline_fraction, not MFU",
                **roofline,
                "flops_per_step": flops})


def bench_moe_train(batch_size: int = 4096, d: int = 256,
                    hidden: int = 512, experts: int = 8, steps: int = 10):
    """MoE-vs-dense training throughput at EQUAL per-token FLOPs: a
    top-1 MoE layer (``experts`` FFNs of width ``hidden``, expert axis
    sharded, fixed-size all-to-all exchange) against a dense FFN of the
    same width. Each token runs one d→hidden→d FFN either way, so the
    samples/s delta is pure routing + exchange cost while the MoE holds
    ``experts``x the FFN parameters — capacity at constant step FLOPs.
    Both sides train through the real Estimator; dropped-token
    accounting drains into ``parallel.moe_dropped_tokens_total`` and
    rides the record (never silent)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
    from analytics_zoo_tpu.keras.layers import Dense
    from analytics_zoo_tpu.parallel import moe as moe_mod

    init_tpu_context()
    n_dev = jax.local_device_count()
    ep = max(dv for dv in (4, 2, 1)
             if dv <= n_dev and n_dev % dv == 0 and experts % dv == 0)
    mesh = Mesh(np.asarray(jax.devices()).reshape(n_dev // ep, ep),
                ("data", "expert"))
    exchange = "alltoall" if ep > 1 else "dense"
    rs = np.random.RandomState(0)
    x = rs.rand(batch_size, d).astype(np.float32)
    y = (x.sum(1) > d / 2).astype(np.float32)
    bx, by = jnp.asarray(x), jnp.asarray(y)

    moe_est = Estimator(
        model=Sequential([
            moe_mod.MoE(num_experts=experts, hidden_dim=hidden, k=1,
                        capacity_factor=1.25,
                        group_size=batch_size // ep, exchange=exchange,
                        name="bench_moe"),
            Dense(2, name="head")]),
        loss_fn=objectives.get("sparse_categorical_crossentropy"),
        optimizer=optimizers.SGD(0.1), mesh=mesh,
        param_sharding_rules=[moe_mod.moe_sharding_rule])
    dense_est = Estimator(
        model=Sequential([Dense(hidden, activation="relu", name="fc1"),
                          Dense(d, name="fc2"), Dense(2, name="head")]),
        loss_fn=objectives.get("sparse_categorical_crossentropy"),
        optimizer=optimizers.SGD(0.1))

    with mesh:
        elapsed, flops, bytes_step = _run_steps_differenced(
            moe_est, bx, by, steps)
    rate = round(batch_size * steps / elapsed, 1)
    _note_partial(metric="moe_train_samples_per_sec", value=rate,
                  unit="samples/s", experts=experts,
                  expert_shards=ep, exchange=exchange)
    delapsed, _df, _db = _run_steps_differenced(dense_est, bx, by, steps)
    dense_rate = round(batch_size * steps / delapsed, 1)

    # one real epoch exercises the per-epoch drain so the drop counter
    # the record reports is the PUBLISHED metric, not a private count
    # (the dense estimator's init installed ITS mesh as the layer-build
    # default, so the expert mesh goes back in for the drain epoch)
    from analytics_zoo_tpu.parallel import set_default_mesh
    drops0 = moe_mod._M_DROPPED.value()
    fs = FeatureSet.from_ndarrays(x, y, shuffle=False)
    set_default_mesh(mesh)
    try:
        with mesh:
            moe_est.train(fs, batch_size=batch_size, epochs=1)
    finally:
        set_default_mesh(None)
    drops = int(moe_mod._M_DROPPED.value() - drops0)

    def _pbytes(est):
        return sum(l.nbytes for l in
                   jax.tree_util.tree_leaves(est.params))

    moe_bytes, dense_bytes = _pbytes(moe_est), _pbytes(dense_est)
    mfu = _mfu(flops, steps, elapsed)
    roofline = _roofline_fields(flops, bytes_step, elapsed, steps)
    return _BenchResult(
        metric="moe_train_samples_per_sec", value=rate, unit="samples/s",
        mfu=mfu,
        detail={"batch_size": batch_size, "experts": experts,
                "expert_hidden": hidden, "expert_shards": ep,
                "exchange": exchange,
                "dense_samples_per_sec": dense_rate,
                "moe_vs_dense_samples_ratio":
                    round(rate / dense_rate, 3) if dense_rate else None,
                "moe_param_bytes": moe_bytes,
                "dense_param_bytes": dense_bytes,
                "param_capacity_multiple":
                    round(moe_bytes / dense_bytes, 2),
                "moe_dropped_tokens": drops,
                "note": "equal per-token FLOPs by construction (one "
                        "d->hidden->d FFN per token both sides); the MoE "
                        "column buys parameter capacity, the ratio prices "
                        "its routing + exchange overhead",
                **roofline,
                "flops_per_step": flops})


def _ops_burst_type():
    """The one registration site for the bench burst event type (the
    event-names lint holds every type to a single owning call site)."""
    from analytics_zoo_tpu.ops import events as zoo_events
    return zoo_events.event_type(
        "bench.ops_burst",
        "Synthetic burst event from bench.py's obs legs (serving soak "
        "and ratio-mode emit probe).")


def bench_obs_overhead(batch_size: int = 256, steps_per_epoch: int = 16,
                       d: int = 64, rounds: int = 3):
    """Telemetry-plane cost, measured end to end.

    Part 1 — train-loop A/B: identical epochs with (a) the metrics
    registry disabled and no trace session vs (b) the full registry
    enabled AND a live chrome-trace session recording every span, plus
    (c) the full OPS PLANE live — structured event log, metric-history
    sampler thread and the SLO alert engine over the default rules. The
    headline is the throughput delta (%); the target is < 2% for both
    (b) and (c) — telemetry that taxes the hot path more than that would
    get turned off in production and rot. Rounds interleave a/b/c and
    take medians so the number is a property of the code, not of which
    half of the run the host's background noise landed in.

    Part 2 — a traced serving soak (threaded pipeline loop + a concurrent
    forked transform-worker pool, the unified-platform shape): the dumped
    trace must be Perfetto-loadable, contain at least one COMPLETE
    enqueue→claim→decode→dispatch→result flow chain, and carry spans from
    >= 2 pids (the forked workers). The soak also runs with the event
    log enabled under a concurrent event burst: every burst event must
    read back from the spool and the serving lifecycle transition must
    land next to them. Gated before any number is published.
    """
    import json as json_mod
    import tempfile

    from analytics_zoo_tpu.common import metrics as zoo_metrics
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
    from analytics_zoo_tpu.keras.layers import Dense
    from analytics_zoo_tpu.utils.trace import trace

    ctx = init_tpu_context()
    batch_size = max(ctx.num_devices,
                     (batch_size // ctx.num_devices) * ctx.num_devices)
    n = batch_size * steps_per_epoch
    rs = np.random.RandomState(0)
    x = rs.rand(n, d).astype(np.float32)
    y = (x.sum(1) > d / 2).astype(np.float32)
    est = Estimator(
        model=Sequential([Dense(256, activation="relu"), Dense(2)]),
        loss_fn=objectives.get("sparse_categorical_crossentropy"),
        optimizer=optimizers.SGD(0.1))
    fs = FeatureSet.from_ndarrays(x, y, shuffle=False)
    est.train(fs, batch_size=batch_size, epochs=1)  # compile warmup

    tdir = tempfile.mkdtemp(prefix="zoo_bench_obs_")
    reg = zoo_metrics.default_registry()

    def one_epoch():
        # ``epochs=`` is a CUMULATIVE MaxEpoch trigger (checkpoint-resume
        # semantics): on a warm estimator ``train(..., epochs=1)`` is a
        # no-op. Each round must ask for one MORE epoch explicitly.
        from analytics_zoo_tpu.common.triggers import MaxEpoch
        before = est.global_step
        t0 = time.perf_counter()
        est.train(fs, batch_size=batch_size, end_trigger=MaxEpoch(est.epoch))
        dt = time.perf_counter() - t0
        if est.global_step != before + steps_per_epoch:
            raise RuntimeError(
                f"A/B epoch ran {est.global_step - before} steps, expected "
                f"{steps_per_epoch} — the round would time a no-op")
        return dt

    def epoch_off():
        reg.set_enabled(False)
        try:
            return one_epoch()
        finally:
            reg.set_enabled(True)

    _trace_n = iter(range(10 ** 6))

    def epoch_on():
        path = os.path.join(tdir, f"train_{next(_trace_n)}.json")
        with trace(path):
            return one_epoch()

    from analytics_zoo_tpu.ops import alerts as zoo_alerts
    from analytics_zoo_tpu.ops import events as zoo_events
    from analytics_zoo_tpu.ops.history import MetricHistory

    def epoch_ops():
        # the full ops plane live around a registry-enabled epoch: event
        # spool + history sampler thread + alert engine on default rules
        zoo_events.reset_default(root=os.path.join(tdir, "ops_spool"),
                                 enabled=True)
        hist = MetricHistory()
        eng = zoo_alerts.AlertEngine(hist, zoo_alerts.default_rules())
        hist.start()
        eng.start()
        try:
            return one_epoch()
        finally:
            eng.stop()
            hist.stop()
            zoo_events.reset_default(enabled=False)

    offs, ons, opss = [], [], []
    for _ in range(rounds):
        offs.append(epoch_off())
        ons.append(epoch_on())
        opss.append(epoch_ops())
    off_s = sorted(offs)[len(offs) // 2]
    on_s = sorted(ons)[len(ons) // 2]
    ops_s = sorted(opss)[len(opss) // 2]
    overhead_pct = (on_s - off_s) / off_s * 100.0
    ops_overhead_pct = (ops_s - off_s) / off_s * 100.0
    off_rate = n / off_s
    on_rate = n / on_s
    ops_rate = n / ops_s
    _note_partial(metric="obs_overhead_pct", value=round(overhead_pct, 3),
                  unit="%", overhead_under_2pct=bool(overhead_pct < 2.0),
                  ops_overhead_pct=round(ops_overhead_pct, 3),
                  ops_under_2pct=bool(ops_overhead_pct < 2.0))

    # -- part 1b: step-phase profiler exposition gate -------------------------
    # one epoch with the attribution profiler ON: the phase histograms must
    # land in the Prometheus exposition (loop="train" series for dispatch/
    # execute), proving the full chain estimator → profiler → registry →
    # scrape text. The headline A/B above deliberately keeps the profiler
    # OFF on both sides: its execute-phase fence costs the loop its async
    # pipelining by design, which is attribution, not overhead.
    from analytics_zoo_tpu.common import profiler as zoo_profiler
    zoo_profiler.set_enabled(True)
    try:
        profiled_s = one_epoch()
    finally:
        zoo_profiler.set_enabled(False)
    zoo_profiler.sample_memory()  # stamps RSS/HBM gauges + zoo_build_info
    expo = zoo_metrics.expose_text()
    profiler_ok = ("zoo_profile_phase_seconds" in expo
                   and 'loop="train"' in expo
                   and 'phase="dispatch"' in expo
                   and 'phase="execute"' in expo
                   and "zoo_build_info" in expo)
    if not profiler_ok:
        raise RuntimeError("profiler exposition gate failed: phase series "
                           "missing from expose_text()")

    # -- part 2: traced serving soak + forked worker pool ---------------------
    from analytics_zoo_tpu.feature.worker_pool import (
        TransformWorkerPool, fork_available)
    from analytics_zoo_tpu.inference import InferenceModel
    from analytics_zoo_tpu.serving import ClusterServing, ServingConfig
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue

    im = InferenceModel(concurrent_num=2).load_jax(
        lambda p, xx: xx.reshape(xx.shape[0], -1).mean(1, keepdims=True), {})
    root = tempfile.mkdtemp(prefix="zoo_bench_obs_srv_")
    src = f"dir://{root}"
    cfg = ServingConfig(data_src=src, image_shape=(64,), batch_size=16,
                        batch_wait_ms=5, input_dtype="float32")
    serving = ClusterServing(cfg, model=im)
    inq, outq = InputQueue(src), OutputQueue(src)
    vec = rs.rand(64).astype(np.float32)
    soak_n = 64
    trace_path = os.path.join(tdir, "serving_soak.json")

    class _Chain:
        def apply(self, rec):
            return rec * 2.0

    # the soak doubles as an event-burst torture: the spool must keep
    # every event appended concurrently with the serving hot loop, and
    # the server's own lifecycle transition must land beside them
    import threading
    zoo_events.reset_default(root=os.path.join(tdir, "ops_soak_spool"),
                             enabled=True)
    burst_type = _ops_burst_type()
    burst_n = 1500

    def _burst():
        for i in range(burst_n):
            burst_type.emit(label="soak", n=i)

    burst_thread = threading.Thread(target=_burst, daemon=True)
    with trace(trace_path):
        serving.start()
        burst_thread.start()
        try:
            for i in range(soak_n):
                inq.enqueue_tensor(f"s{i}", vec)
            if fork_available():
                # concurrent host data plane: forked workers put their
                # pid-tagged spans on the same timeline
                feats = rs.rand(32, 16).astype(np.float32)
                pool = TransformWorkerPool(feats, _Chain(), rows=8,
                                           slots=2, num_workers=2)
                try:
                    batches = [np.arange(8), np.arange(8, 16)]
                    for _idx, _view in pool.map_index_batches(iter(batches)):
                        pass
                finally:
                    pool.close()
            deadline = time.monotonic() + 60
            answered = {}
            while time.monotonic() < deadline and len(answered) < soak_n:
                answered.update(outq.dequeue())
                time.sleep(0.02)
        finally:
            serving.drain(timeout_s=30)
    burst_thread.join(timeout=30)
    burst_seen = len(zoo_events.read_events(types=["bench.ops_burst"]))
    lifecycle_seen = len(zoo_events.read_events(
        types=["serving.lifecycle"]))
    event_burst_ok = bool(burst_seen == burst_n and lifecycle_seen >= 1)
    zoo_events.reset_default(enabled=False)
    if len(answered) != soak_n:
        raise RuntimeError(
            f"soak lost requests: {len(answered)}/{soak_n} answered")
    if not event_burst_ok:
        raise RuntimeError(
            f"event-burst soak lost events: {burst_seen}/{burst_n} burst "
            f"events, {lifecycle_seen} lifecycle events read back")

    events = json_mod.load(open(trace_path))  # Perfetto-loadable JSON
    spans = [e for e in events if e.get("ph") == "X"]
    chains = {}
    for s in spans:
        fid = (s.get("args") or {}).get("trace_id")
        if fid is not None:
            chains.setdefault(fid, set()).add(s["name"])
    need = {"serving.enqueue", "serving.claim", "serving.decode",
            "serving.dispatch", "serving.result"}
    complete = sum(1 for c in chains.values() if need <= c)
    pids = {s["pid"] for s in spans}
    if complete < 1:
        raise RuntimeError("no complete serving flow chain in the trace")
    if fork_available() and len(pids) < 2:
        raise RuntimeError(
            f"trace has spans from only {len(pids)} pid(s); forked worker "
            f"spans missing")

    return _BenchResult(
        metric="obs_overhead_pct",
        value=round(overhead_pct, 3),
        unit="%", mfu=None,
        detail={"batch_size": batch_size,
                "steps_per_epoch": steps_per_epoch,
                "rounds": rounds,
                "disabled_examples_per_sec": round(off_rate, 1),
                "enabled_traced_examples_per_sec": round(on_rate, 1),
                "ops_plane_examples_per_sec": round(ops_rate, 1),
                "overhead_pct": round(overhead_pct, 3),
                "overhead_under_2pct": bool(overhead_pct < 2.0),
                "ops_overhead_pct": round(ops_overhead_pct, 3),
                "ops_under_2pct": bool(ops_overhead_pct < 2.0),
                "event_burst_events": burst_seen,
                "event_burst_ok": event_burst_ok,
                "profiler_exposition_ok": profiler_ok,
                "profiled_examples_per_sec": round(n / profiled_s, 1),
                "soak_requests": soak_n,
                "flow_chains_complete": complete,
                "flow_chains_seen": len(chains),
                "flow_chain_ok": bool(complete >= 1),
                "trace_pids": len(pids),
                "trace_spans": len(spans),
                "note": "A/B/C medians over interleaved epochs: metrics "
                        "registry disabled vs registry + live trace "
                        "session vs full ops plane (event log + history "
                        "sampler + alert engine); soak gate = Perfetto-"
                        "loadable trace with a complete enqueue→claim→"
                        "decode→dispatch→result chain, spans from >= 2 "
                        "pids, and a lossless concurrent event burst"})


def _longseq_once(batch_size, heads, seq, head_dim, steps):
    """One differenced flash train-step measurement; returns a detail dict.

    Each step's inputs depend on the previous step's grads so the scan
    measures SERIAL step latency; eps is a RUNTIME zero (XLA cannot fold
    eps*grad away) and the scalar readback is the completion fence. FLOPs
    are analytic (9 causal-halved [S,S,D] matmuls/step — cost analysis
    cannot see inside the pallas custom calls)."""
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.ops.attention import flash_attention

    rs = np.random.RandomState(1)
    shape = (batch_size, heads, seq, head_dim)
    q, k, v = (jnp.asarray(rs.randn(*shape).astype(np.float32),
                           jnp.bfloat16) for _ in range(3))

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True)
                       .astype(jnp.float32))

    grad_fn = jax.grad(loss, argnums=(0, 1, 2))

    def chained(q, k, v, eps, n):
        def body(carry, _):
            cq, ck, cv = carry
            dq, dk, dv = grad_fn(cq, ck, cv)
            return (cq + eps * dq, ck + eps * dk, cv + eps * dv), ()
        (q, k, v), _ = jax.lax.scan(body, (q, k, v), None, length=n)
        return (q, k, v), jnp.sum(q.astype(jnp.float32))

    eps = jnp.bfloat16(0.0)
    flops = 9 * batch_size * heads * seq * seq * head_dim
    c1 = jax.jit(lambda q, k, v, e: chained(q, k, v, e, steps)
                 ).lower(q, k, v, eps).compile()
    (cq, ck, cv), s = c1(q, k, v, eps)
    float(s)
    float(c1(cq, ck, cv, eps)[1])

    def once():
        return float(c1(q, k, v, eps)[1])

    def twice():
        (mq, mk, mv), _ = c1(q, k, v, eps)
        return float(c1(mq, mk, mv, eps)[1])

    for _ in range(3):
        t1 = min(_timed(once) for _ in range(3))
        t2 = min(_timed(twice) for _ in range(3))
        if t2 - t1 > 1e-4:
            elapsed = t2 - t1
            return {"batch_size": batch_size, "head_dim": head_dim,
                    "tokens_per_sec": round(batch_size * seq * steps
                                            / elapsed, 1),
                    "mfu": _mfu(flops, steps, elapsed)}
    return {"batch_size": batch_size, "head_dim": head_dim,
            "error": "differenced timing collapsed"}


def bench_longseq(batch_size: int = 4, heads: int = 8, seq: int = 4096,
                  head_dim: int = 128, steps: int = 20, warmup: int = 3):
    """Long-context attention train step (the new long-context capability;
    no reference counterpart — SURVEY §5 notes the reference has none).
    Runs fwd+bwd through the pallas flash kernels (fused single-pass
    backward: K/V VMEM-resident, dq/dk/dv in one grid) at a sequence length
    where a materialized [S, S] probability matrix would dominate HBM.
    Headline is head_dim 128 — the modern LLM config, where the kernels are
    MXU-bound and MFU reflects kernel quality; head_dim 64 rides as the
    addendum (VPU-bound by construction: softmax ops per element rival its
    2·64 MXU flops, halving achievable MFU). Both kernel directions are
    numerics-gated against the XLA blockwise path in-process before any
    timing is published."""
    from analytics_zoo_tpu.common.context import init_tpu_context

    init_tpu_context()
    del warmup  # both compiled scan lengths are warmed inside _longseq_once
    gate_err = _flash_numerics_gate(head_dim, causal=True)
    head = _longseq_once(batch_size, heads, seq, head_dim, steps)
    if "error" in head:
        raise RuntimeError(f"longseq headline measurement failed: {head}")
    _note_partial(metric="longseq_attention_tokens_per_sec",
                  value=head["tokens_per_sec"], unit="tokens/s",
                  numerics_rel_err=gate_err)
    # addendum config: batch doubled, head_dim halved — the SAME FLOP
    # budget per step (token count doubles). Its failure must not lose the
    # already-measured headline. Gated independently: the d=64 tiling takes
    # different kernel paths than the d=128 headline gate covers.
    try:
        if time.perf_counter() - _T0 > 450:
            raise RuntimeError("child budget: d=128 phase too slow, "
                               "d=64 addendum skipped")
        d64_gate = _flash_numerics_gate(64, causal=True)
        d64 = _longseq_once(batch_size * 2, heads, seq, 64, steps)
        d64["numerics_rel_err"] = d64_gate
        d64["note"] = "VPU-bound at d=64: softmax work rivals MXU flops"
    except Exception as e:
        d64 = {"error": repr(e)[:200]}
    return _BenchResult(
        metric="longseq_attention_tokens_per_sec",
        value=head["tokens_per_sec"],
        unit="tokens/s",
        mfu=head["mfu"],
        detail={"batch_size": batch_size, "heads": heads, "seq_len": seq,
                "head_dim": head_dim, "causal": True,
                "numerics_ok": True, "numerics_rel_err": gate_err,
                "head_dim_64": d64,
                "kernel": "pallas flash fwd + fused single-pass bwd "
                          "(dq,dk,dv in one grid, K/V VMEM-resident)",
                "loop": "chained lax.scan, differenced double-dispatch timing",
                "flops_per_step": 9 * batch_size * heads * seq * seq
                * head_dim})


def bench_eval(n_records: int = 32768, batch_size: int = 1024,
               d: int = 256, reps: int = 3):
    """Eval/predict pipeline throughput (records/s) over a fixed
    FeatureSet: the async path (DeviceFeed prefetch + on-device
    accumulation, ONE host sync per pass) vs the ``eval.async=False``
    synchronous fallback (per-batch shard + blocking float()/np.asarray()
    round-trips — the pre-change loops, kept in estimator/sync_eval.py).
    The async/sync RATIO is the headline of the pipelining redesign; on a
    tunneled chip the sync path pays a full RPC round-trip per batch, so
    the gap there is the remote-attached worst case. Results are
    parity-checked in-process before any number is published."""
    from analytics_zoo_tpu.common.config import global_config
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
    from analytics_zoo_tpu.keras.layers import Dense

    ctx = init_tpu_context()
    batch_size = max(ctx.num_devices,
                     (batch_size // ctx.num_devices) * ctx.num_devices)
    model = Sequential([Dense(512, activation="relu"),
                        Dense(256, activation="relu"), Dense(2)])
    est = Estimator(model=model,
                    loss_fn=objectives.get("sparse_categorical_crossentropy"),
                    optimizer=optimizers.SGD(0.1), metrics=["accuracy"])
    rs = np.random.RandomState(0)
    n = n_records + 7  # ragged tail: the padded-tail path is in the loop
    x = rs.rand(n, d).astype(np.float32)
    y = (x.sum(1) > d / 2).astype(np.float32)
    fs = FeatureSet.from_ndarrays(x, y, shuffle=False)
    cfg = global_config()

    def with_flag(async_flag, fn):
        had = "eval.async" in cfg._overrides
        saved = cfg.get("eval.async")
        cfg.set("eval.async", async_flag)
        try:
            return fn()
        finally:
            if had:
                cfg.set("eval.async", saved)
            else:
                cfg.unset("eval.async")

    def timed_eval():
        est.evaluate(fs, batch_size)  # warm: compiles + first-pass costs
        t0 = time.perf_counter()
        for _ in range(reps):
            scores = est.evaluate(fs, batch_size)
        return n * reps / (time.perf_counter() - t0), scores

    def timed_predict():
        est.predict(fs, batch_size)
        t0 = time.perf_counter()
        for _ in range(reps):
            preds = est.predict(fs, batch_size)
        return n * reps / (time.perf_counter() - t0), preds

    sync_eval_rate, sync_scores = with_flag(False, timed_eval)
    async_eval_rate, async_scores = with_flag(True, timed_eval)
    sync_pred_rate, sync_preds = with_flag(False, timed_predict)
    async_pred_rate, async_preds = with_flag(True, timed_predict)
    parity = (sync_scores == async_scores
              and bool(np.array_equal(np.asarray(sync_preds),
                                      np.asarray(async_preds))))
    if not parity:
        raise RuntimeError(
            f"async/sync eval parity FAILED: {sync_scores} vs "
            f"{async_scores}")
    return _BenchResult(
        metric="eval_records_per_sec",
        value=round(async_eval_rate, 1),
        unit="records/s", mfu=None,
        detail={"records": n, "batch_size": batch_size,
                "model": f"mlp {d}-512-256-2", "reps": reps,
                "async_eval_records_per_sec": round(async_eval_rate, 1),
                "sync_eval_records_per_sec": round(sync_eval_rate, 1),
                "eval_speedup": round(async_eval_rate / sync_eval_rate, 2),
                "async_predict_records_per_sec": round(async_pred_rate, 1),
                "sync_predict_records_per_sec": round(sync_pred_rate, 1),
                "predict_speedup": round(async_pred_rate / sync_pred_rate,
                                         2),
                "parity_ok": parity,
                "includes": "host gather/shard + device forward + "
                            "metric/result handling, wall clock",
                "note": "sync = pre-change per-batch blocking loops "
                        "(eval.async=False fallback); async = DeviceFeed "
                        "prefetch, on-device accumulation, one host sync "
                        "per pass"})


def bench_quantized(batch_size: int = 32, steps: int = 30, warmup: int = 3):
    """ResNet-18 inference latency across precisions: fp32 vs bf16 vs
    calibrated int8 (activation observers + static grid — the reference's
    OpenVINO VNNI int8 role, ``examples/vnni/openvino/Perf.scala``)."""
    import jax
    import jax.numpy as jnp

    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.inference import InferenceModel
    from analytics_zoo_tpu.models.image.imageclassification import resnet

    init_tpu_context()
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(batch_size, 224, 224, 3).astype(np.float32))
    model = resnet(18, num_classes=1000, input_shape=(224, 224, 3))
    model.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")
    params, state = model.build(jax.random.PRNGKey(0))

    def measure(im):
        fwd = im._forward
        p = im._params
        eps = jnp.float32(0.0)

        def chained(p, x, eps):
            def body(carry, _):
                y = fwd(p, carry)
                s = jnp.sum(jnp.asarray(y, jnp.float32))
                return carry + eps * s, ()
            out, _ = jax.lax.scan(body, x, None, length=steps)
            return out, jnp.sum(out)

        c1 = jax.jit(chained).lower(p, x, eps).compile()
        mid, s = c1(p, x, eps)
        float(s)
        float(c1(p, mid, eps)[1])

        def once():
            return float(c1(p, x, eps)[1])

        def twice():
            m, _ = c1(p, x, eps)
            return float(c1(p, m, eps)[1])

        for _attempt in range(3):
            t1 = min(_timed(once) for _ in range(2))
            t2 = min(_timed(twice) for _ in range(2))
            if t2 - t1 > 1e-4:
                return round(batch_size * steps / (t2 - t1), 1)
        raise RuntimeError(
            f"differenced timing collapsed (t1={t1:.4f} t2={t2:.4f})")

    fp32 = measure(InferenceModel().load_keras(model, params, state))
    b16 = measure(InferenceModel().load_keras(model, params, state)
                  .quantize("bf16"))
    calib = [np.asarray(x[:8])]
    i8 = measure(InferenceModel().load_keras(model, params, state)
                 .quantize("int8", calibration_data=calib))
    return _BenchResult(
        metric="quantized_resnet18_images_per_sec",
        value=i8, unit="images/s", mfu=None,
        detail={"batch_size": batch_size, "model": "resnet18 224px 1000c",
                "fp32_images_per_sec": fp32,
                "bf16_images_per_sec": b16,
                "int8_calibrated_images_per_sec": i8,
                "loop": "differenced double-dispatch of one compiled scan"})


# run order = importance order: on a slow-tunnel day the budget guard
# skips from the END of this list (quantized/pipeline have stable
# previously-published numbers; the north stars and the new int8-dataflow
# row must always land)
def bench_recovery(batch_size: int = 256, steps_per_epoch: int = 8,
                   d: int = 64):
    """Elastic-recovery cost: wall-clock overhead of one injected step
    failure (checkpoint restore + replay + pipeline re-setup) vs the
    clean run, and the restore cost alone — the number that tells you
    what a preemption/chip failure actually costs at a given checkpoint
    cadence. Uses the ``train.step`` fault site (``common/faults.py``)
    with checkpoints every iteration, and parity-checks that the faulted
    run's final params are BIT-IDENTICAL to the clean run's before any
    number is published (recovery that changes the math is not
    recovery)."""
    import tempfile

    from analytics_zoo_tpu.common import faults
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.common.triggers import SeveralIteration
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
    from analytics_zoo_tpu.keras.layers import Dense

    ctx = init_tpu_context()
    batch_size = max(ctx.num_devices,
                     (batch_size // ctx.num_devices) * ctx.num_devices)
    n = batch_size * steps_per_epoch
    rs = np.random.RandomState(0)
    x = rs.rand(n, d).astype(np.float32)
    y = (x.sum(1) > d / 2).astype(np.float32)

    def make(ckpt_dir):
        est = Estimator(
            model=Sequential([Dense(256, activation="relu"), Dense(2)]),
            loss_fn=objectives.get("sparse_categorical_crossentropy"),
            optimizer=optimizers.SGD(0.1))
        est.set_checkpoint(ckpt_dir, SeveralIteration(1))
        return est

    def fs():
        return FeatureSet.from_ndarrays(x, y, shuffle=False)

    def run(inject_at=None):
        """Warm one epoch (compiles + first snapshot), then time two more
        epochs — with an optional single step failure in the middle."""
        ckpt = tempfile.mkdtemp(prefix="zoo_bench_recovery_")
        est = make(ckpt)
        est.train(fs(), batch_size=batch_size, epochs=1)
        est._ckpt_writer.wait()
        faults.reset()
        if inject_at is not None:
            faults.arm("train.step", at=inject_at, budget=1)
        try:
            t0 = time.perf_counter()
            est.train(fs(), batch_size=batch_size, epochs=3)
            elapsed = time.perf_counter() - t0
            fired = faults.fire_count("train.step") if inject_at else 0
        finally:
            faults.reset()
        est._ckpt_writer.wait()
        return elapsed, est, ckpt

    clean_s, est_clean, _ = run()
    timed_steps = 2 * steps_per_epoch
    clean_step_s = clean_s / timed_steps
    faulted_s, est_faulted, ckpt = run(inject_at=steps_per_epoch)

    import jax
    pa = jax.tree_util.tree_leaves(est_clean.get_params())
    pb = jax.tree_util.tree_leaves(est_faulted.get_params())
    parity = all(np.array_equal(a, b) for a, b in zip(pa, pb))
    if not parity:
        raise RuntimeError(
            "recovery parity FAILED: faulted run's final params differ "
            "from the clean run's")

    # restore cost alone (checksum verify + orbax read + device_put)
    t0 = time.perf_counter()
    est_faulted.load_checkpoint(est_faulted._latest_snapshot())
    restore_s = time.perf_counter() - t0

    recovery_s = max(0.0, faulted_s - clean_s)
    return _BenchResult(
        metric="recovery_seconds",
        value=round(recovery_s, 4),
        unit="s", mfu=None,
        detail={"clean_wall_s": round(clean_s, 4),
                "faulted_wall_s": round(faulted_s, 4),
                "restore_ms": round(restore_s * 1e3, 2),
                "clean_step_ms": round(clean_step_s * 1e3, 2),
                "recovery_vs_step": round(recovery_s / clean_step_s, 2)
                if clean_step_s > 0 else None,
                "batch_size": batch_size,
                "steps_per_epoch": steps_per_epoch,
                "checkpoint_cadence": "every iteration",
                "parity_ok": parity,
                "note": "recovery_seconds = faulted wall - clean wall for "
                        "an identical 2-epoch schedule with ONE injected "
                        "step failure (train.step site); includes restore "
                        "+ replay of the failed step + feed re-setup"})


def bench_online_learning(windows: int = 4, batch_size: int = 4096,
                          users: int = 200_000, items: int = 100_000):
    """Online loop throughput: clicks/s from queue → journal →
    `train_online` on a sharded NCF, with one trainer→server promotion
    timed on top (export_servable + canaried rollout, verified live).
    The metric is the END-TO-END stream rate — ingest thread, journal
    fsync, and the row-subset sparse step all on the clock."""
    import tempfile

    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.keras import objectives, optimizers
    from analytics_zoo_tpu.models import NeuralCF
    from analytics_zoo_tpu.online import Promoter, export_servable
    from analytics_zoo_tpu.serving.queues import make_queue
    from analytics_zoo_tpu.serving.server import (ClusterServing,
                                                  ServingConfig)

    ctx = init_tpu_context()
    batch_size = max(ctx.num_devices,
                     (batch_size // ctx.num_devices) * ctx.num_devices)
    epoch_records = batch_size * 2
    clicks = epoch_records * windows

    root = tempfile.mkdtemp(prefix="zoo_bench_online_")
    q = make_queue(f"dir://{root}/clicks")
    rs = np.random.RandomState(0)
    uid = rs.randint(1, users + 1, clicks)
    iid = rs.randint(1, items + 1, clicks)
    lab = ((uid % 2) == (iid % 2)).astype(int)
    t0 = time.perf_counter()
    for lo in range(0, clicks, 8192):
        q.enqueue_many([
            (f"c{i}", {"x": [int(uid[i]), int(iid[i])], "y": int(lab[i]),
                       "ts": 0.0})
            for i in range(lo, min(lo + 8192, clicks))])
    enqueue_s = time.perf_counter() - t0
    _note_partial(enqueue_mrec_per_sec=round(clicks / enqueue_s / 1e6, 3))

    ncf = NeuralCF(users, items, 2, user_embed=16, item_embed=16,
                   hidden_layers=(32, 16), mf_embed=16,
                   shard_embeddings=True)
    est = Estimator(model=ncf.build_model(),
                    loss_fn=objectives.get(
                        "sparse_categorical_crossentropy"),
                    optimizer=optimizers.SGD(0.1), mesh=ctx.mesh, seed=7)
    fs = FeatureSet.from_queue(q, os.path.join(root, "journal"),
                               epoch_records=epoch_records, watermark_s=0.0)
    try:
        # warm: first window pays compile + ingest spin-up
        est.train_online(fs, batch_size=batch_size,
                         max_steps=epoch_records // batch_size)
        t0 = time.perf_counter()
        est.train_online(fs, batch_size=batch_size,
                         max_steps=(clicks // batch_size))
        train_s = time.perf_counter() - t0
        timed_clicks = clicks - epoch_records
        _note_partial(metric="online_clicks_per_sec",
                      value=round(timed_clicks / train_s, 1), unit="rec/s",
                      steps=int(est.global_step))

        # promotion on top: export the live params, roll a 1-instance
        # fleet forward with the live-version verification on the clock
        t0 = time.perf_counter()
        export = export_servable(ncf, est, f"{root}/exports/v1")
        export_s = time.perf_counter() - t0
        # instance born on the first export; the second promotes onto it
        srv = ClusterServing(ServingConfig(
            data_src=f"dir://{root}/srv", model_path=export,
            model_type="zoo", image_shape=(2,), batch_size=4,
            batch_wait_ms=5))
        export2 = export_servable(ncf, est, f"{root}/exports/v2")
        t0 = time.perf_counter()
        version = Promoter({"canary": srv}).promote(export2)
        promote_s = time.perf_counter() - t0
    finally:
        fs.close()

    return _BenchResult(
        metric="online_clicks_per_sec",
        value=round(timed_clicks / train_s, 1),
        unit="rec/s", mfu=None,
        detail={"windows": windows, "batch_size": batch_size,
                "epoch_records": epoch_records, "clicks": clicks,
                "steps": int(est.global_step),
                "enqueue_mrec_per_sec": round(clicks / enqueue_s / 1e6, 3),
                "export_ms": round(export_s * 1e3, 1),
                "promote_ms": round(promote_s * 1e3, 1),
                "promoted_version": version,
                "note": "clicks/s through queue→journal→train_online on "
                        "sharded NCF (row-subset updates); promote_ms = "
                        "canaried rollout incl. load+prewarm+verify-live"})


_WORKLOADS = {
    "resnet50": bench_resnet50,
    "recovery": bench_recovery,
    "resnet50_int8": bench_resnet50_int8,
    "ncf": bench_ncf,
    "bert": bench_bert,
    "widedeep": bench_widedeep,
    "widedeep_sharded": bench_widedeep_sharded,
    "longseq": bench_longseq,
    "eval": bench_eval,
    "serving": bench_serving,
    "serving_slo": bench_serving_slo,
    "serving_brownout": bench_serving_brownout,
    "serving_fleet": bench_serving_fleet,
    "serving_fleet_redis": bench_serving_fleet_redis,
    "generate": bench_generate,
    "obs_overhead": bench_obs_overhead,
    "quantized": bench_quantized,
    "pipeline": bench_input_pipeline,
    "etl_to_train": bench_etl_to_train,
    "online_learning": bench_online_learning,
    "tp_decode": bench_tp_decode,
    "moe_train": bench_moe_train,
}

# spelling aliases accepted on the CLI (resolved in main, NOT in the dict —
# "all" must not run a workload twice)
_ALIASES = {"input_pipeline": "pipeline"}


_MARKER = "BENCH_RESULT_JSON:"

# Total wall budget for `python bench.py` (all workloads). The driver kills
# the whole run on ITS deadline and keeps only the last ~2000 chars of
# output, so the bench must (a) finish comfortably inside that and (b) emit
# a compact final line. Round 4 learned this the hard way: rc=124, empty
# tail, no number recorded for the round.
_BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "2700"))
_PER_WORKLOAD_S = float(os.environ.get("BENCH_WORKLOAD_S", "700"))


def _log(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - _T0:.0f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.perf_counter()


def _emit_partial_and_exit(name: str, why: str) -> None:
    """Child-side budget handler: print the best-so-far partial record on
    the marker line and exit 0 — degraded data beats no data."""
    rec = {"metric": _PARTIAL.get("metric", f"{name}_partial"),
           "value": _PARTIAL.get("value"),
           "unit": _PARTIAL.get("unit") or "",
           "mfu": _PARTIAL["detail"].get("mfu"),
           "partial": True,
           "detail": {**_PARTIAL["detail"], "error": why}}
    rec["detail"].pop("mfu", None)
    print(_MARKER + json.dumps(rec), flush=True)
    sys.stdout.flush()
    os._exit(0)


def _install_child_guard(name: str, budget_s: float) -> None:
    """--one mode: enforce the workload budget INSIDE the child. On SIGALRM
    (own budget) or SIGTERM/SIGINT (parent or driver gave up) the partial
    record stashed by _note_partial still goes out on stdout. This is the
    direct fix for rounds r04/r05: a hung TPU tunnel used to ride the
    subprocess SIGKILL to rc=124 with no JSON for the whole round."""
    import signal

    def guard(signum, _frame):
        try:
            why = f"budget exceeded (signal {signal.Signals(signum).name})"
        except ValueError:  # pragma: no cover
            why = f"budget exceeded (signal {signum})"
        _emit_partial_and_exit(name, why)

    for sig in (signal.SIGTERM, signal.SIGINT, signal.SIGALRM):
        signal.signal(sig, guard)
    if budget_s and budget_s > 0:
        signal.alarm(int(budget_s))


def _run_isolated(name: str, timeout_s: float) -> "_BenchResult":
    """Run one workload in a fresh interpreter. Workloads pollute each other
    inside one process (device buffers from earlier models linger, compile
    caches interact — the input-pipeline rate measured 16x slower after the
    BERT bench than standalone), so `all` isolates each in a subprocess.

    The child enforces the budget itself (SIGALRM ~30s before the parent
    deadline → partial record, rc 0). The parent timeout is a backstop:
    TERMinate (the child's guard prints its partial on the way out), then
    KILL only if even that hangs — and whatever marker line made it to
    stdout is still collected."""
    import subprocess
    child_budget = int(max(timeout_s - 30, 60))
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--one", name,
         "--budget", str(child_budget)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            out, err = proc.communicate(timeout=20)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, err = proc.communicate()
    for line in (out or "").splitlines():
        if line.startswith(_MARKER):
            return _BenchResult(json.loads(line[len(_MARKER):]))
    raise RuntimeError(
        f"workload {name} produced no result (rc={proc.returncode}): "
        f"{(out or '')[-500:]}\n{(err or '')[-1500:]}")


# -- CPU-parity ratio mode ----------------------------------------------------
# When the accelerator is unreachable (failed preflight, dead tunnel) or
# absent (CPU-only host), absolute samples/sec are meaningless — but RATIOS
# of two host-side strategies still exercise the same machinery the TPU run
# does: async-vs-sync eval pipelining, mp-vs-thread transform workers,
# uint8-vs-f32 transfer, multi-step dispatch grouping, telemetry no-op
# cost, checkpoint restore cost. Every workload maps to one of these
# proxies (_RATIO_PLAN), so even a dead-tunnel round lands one schema-valid
# record per workload instead of thirteen timeouts.


class _RatioChain:
    """Deliberately GIL-bound per-record transform (pure-Python loop):
    the workload mp workers beat and threads cannot."""

    def apply(self, rec):
        s = 0.0
        for v in rec[:2048:8]:
            s += float(v) * 1.0000001
        return rec + np.float32(s % 1.0)


def _ratio_regression(n=4096, d=16, seed=0):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, d).astype(np.float32)
    y = (x @ rs.randn(d, 1).astype(np.float32)).astype(np.float32)
    return x, y


def _ratio_estimator():
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
    from analytics_zoo_tpu.keras.layers import Dense
    model = Sequential([Dense(32, activation="tanh"), Dense(1)])
    return Estimator(model=model, loss_fn=objectives.get("mse"),
                     optimizer=optimizers.Adam(1e-2))


def _ratio_transfer():
    """uint8-vs-f32 host→device transfer: the wire-dtype optimization the
    image workloads (resnet50 fed phase, serving) are built on."""
    import jax
    rs = np.random.RandomState(0)
    batch = rs.randint(0, 255, (64, 224, 224, 3))
    u8 = batch.astype(np.uint8)
    f32 = batch.astype(np.float32)

    def put_s(x, reps=8):
        t0 = time.perf_counter()
        for _ in range(reps):
            jax.block_until_ready(jax.device_put(x))
        return (time.perf_counter() - t0) / reps

    put_s(u8, 2), put_s(f32, 2)  # warm the transfer path
    t_u8, t_f32 = put_s(u8), put_s(f32)
    return {"uint8_put_ms": round(t_u8 * 1e3, 2),
            "f32_put_ms": round(t_f32 * 1e3, 2),
            "uint8_vs_f32_transfer_ratio": round(t_f32 / max(t_u8, 1e-9), 2)}


def _ratio_transform():
    """mp-vs-thread FeatureSet.transform on a GIL-bound transform: the
    forked shared-memory tier's whole reason to exist."""
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.feature.worker_pool import fork_available
    rs = np.random.RandomState(0)
    x = rs.rand(256, 2048).astype(np.float32)

    def timed(mode):
        t0 = time.perf_counter()
        FeatureSet.from_ndarrays(x).transform(_RatioChain(), num_workers=2,
                                              mode=mode)
        return time.perf_counter() - t0

    timed("loop")  # warm allocators + import costs
    t_thread = timed("thread")
    t_mp = timed("mp") if fork_available() else None
    return {"thread_transform_s": round(t_thread, 3),
            "mp_transform_s": round(t_mp, 3) if t_mp else None,
            "host_cpus": os.cpu_count(),
            "mp_vs_thread_transform_ratio":
                round(t_thread / t_mp, 2) if t_mp else None}


def _ratio_dispatch():
    """Multi-step dispatch grouping (lax.scan) vs one dispatch per step on
    a tiny MLP — the per-dispatch host overhead amortization every train
    workload leans on."""
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.feature import FeatureSet
    init_tpu_context()
    x, y = _ratio_regression()

    def timed(spd):
        est = _ratio_estimator()
        fs = FeatureSet.from_ndarrays(x, y, shuffle=False)
        est.train(fs, batch_size=64, epochs=1, steps_per_dispatch=spd)
        t0 = time.perf_counter()
        est.train(fs, batch_size=64, epochs=2, steps_per_dispatch=spd)
        return time.perf_counter() - t0

    t1, t8 = timed(1), timed(8)
    return {"single_dispatch_s": round(t1, 3),
            "grouped_dispatch_s": round(t8, 3),
            "multi_dispatch_speedup": round(t1 / max(t8, 1e-9), 2)}


def _ratio_eval():
    """Async (DeviceFeed + on-device accumulation) vs sync evaluate on a
    tiny MLP — the eval workload's A/B, shrunk to CPU scale."""
    from analytics_zoo_tpu.common.config import global_config
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.feature import FeatureSet
    init_tpu_context()
    x, y = _ratio_regression(n=8192)
    est = _ratio_estimator()
    fs = FeatureSet.from_ndarrays(x, y, shuffle=False)
    est.train(fs, batch_size=512, epochs=1)
    cfg = global_config()

    def timed(async_flag):
        had = "eval.async" in cfg._overrides
        saved = cfg.get("eval.async")
        cfg.set("eval.async", async_flag)
        try:
            est.evaluate(fs, batch_size=512)  # warm
            t0 = time.perf_counter()
            for _ in range(3):
                est.evaluate(fs, batch_size=512)
            return (time.perf_counter() - t0) / 3
        finally:
            if had:
                cfg.set("eval.async", saved)
            else:
                cfg.unset("eval.async")

    t_sync, t_async = timed(False), timed(True)
    return {"sync_eval_s": round(t_sync, 3),
            "async_eval_s": round(t_async, 3),
            "async_vs_sync_eval_ratio":
                round(t_sync / max(t_async, 1e-9), 2)}


def _ratio_serving():
    """Batching amortization through one jitted forward: per-record
    latency at batch 1 vs batch 16 — the serving engine's core bet."""
    import jax
    import jax.numpy as jnp
    rs = np.random.RandomState(0)
    w1 = (rs.randn(128, 256) * 0.05).astype(np.float32)
    w2 = (rs.randn(256, 16) * 0.05).astype(np.float32)

    @jax.jit
    def fwd(x):
        return jnp.tanh(x @ w1) @ w2

    def per_record(bs, calls=64):
        x = rs.rand(bs, 128).astype(np.float32)
        jax.block_until_ready(fwd(x))  # compile this bucket
        t0 = time.perf_counter()
        for _ in range(calls):
            jax.block_until_ready(fwd(x))
        return (time.perf_counter() - t0) / calls / bs

    p1, p16 = per_record(1), per_record(16)
    return {"batch1_us_per_record": round(p1 * 1e6, 1),
            "batch16_us_per_record": round(p16 * 1e6, 1),
            "batch16_vs_batch1_serving_ratio": round(p1 / max(p16, 1e-12),
                                                     2)}


def _ratio_brownout():
    """Retry-budget containment against a backend shedding 100% of
    traffic: attempts per request under the token-bucket budget vs the
    naive retry-N-times client — the overload tier's core bet that
    retries can never become the overload they respond to."""
    from analytics_zoo_tpu.serving.client import RetryBudget
    n, retries = 400, 3
    budget = RetryBudget(0.1)
    budgeted = 0
    for _ in range(n):
        budgeted += 1            # the first attempt is always sent...
        budget.deposit()         # ...and earns ratio tokens
        for _ in range(retries):
            if not budget.try_spend():
                break
            budgeted += 1
    naive = n * (1 + retries)
    return {"budgeted_attempts_per_request": round(budgeted / n, 3),
            "naive_attempts_per_request": 1 + retries,
            "naive_vs_budgeted_retry_ratio": round(naive / budgeted, 2)}


def _ratio_obs():
    """Telemetry record cost, enabled vs disabled — the <1µs no-op
    contract, measured on a fresh registry so bench probes never pollute
    the process-global one. The ops-plane twin rides along: one private
    event log's emit cost enabled vs disabled, holding the structured
    event log to the same disabled-is-free discipline."""
    import shutil
    import tempfile

    from analytics_zoo_tpu.common import metrics as zoo_metrics
    from analytics_zoo_tpu.ops import events as zoo_events
    reg = zoo_metrics.Registry(1 << 10)
    try:
        h = reg.histogram("bench.ratio_probe_seconds", "ratio-mode probe")
        iters = 200000

        def per_call():
            t0 = time.perf_counter()
            for _ in range(iters):
                h.observe(0.001)
            return (time.perf_counter() - t0) / iters

        per_call()  # warm
        on = per_call()
        reg.set_enabled(False)
        off = per_call()
        reg.set_enabled(True)

        burst_type = _ops_burst_type()
        root = tempfile.mkdtemp(prefix="zoo_bench_ratio_ops_")
        log = zoo_events.EventLog(root=root, ring=256, enabled=True)
        ev_iters = 2000

        def per_emit():
            t0 = time.perf_counter()
            for i in range(ev_iters):
                log.emit(burst_type.name, label="ratio", n=i)
            return (time.perf_counter() - t0) / ev_iters

        per_emit()  # warm (opens the part file)
        emit_on = per_emit()
        log.set_enabled(False)
        emit_off = per_emit()
        log.close()
        shutil.rmtree(root, ignore_errors=True)
        return {"enabled_ns_per_record": round(on * 1e9, 1),
                "disabled_ns_per_record": round(off * 1e9, 1),
                "disabled_under_1us": bool(off < 1e-6),
                "enabled_vs_disabled_record_ratio":
                    round(on / max(off, 1e-12), 2),
                "enabled_event_emit_us": round(emit_on * 1e6, 2),
                "disabled_event_emit_ns": round(emit_off * 1e9, 1),
                "disabled_event_under_1us": bool(emit_off < 1e-6),
                "enabled_vs_disabled_event_ratio":
                    round(emit_on / max(emit_off, 1e-12), 2)}
    finally:
        reg.close()


def _ratio_recovery():
    """Checkpoint save/restore cost in units of train steps — elastic
    recovery's promise is restore ≈ a few steps, not a few epochs."""
    import shutil
    import tempfile
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.feature import FeatureSet
    init_tpu_context()
    x, y = _ratio_regression()
    est = _ratio_estimator()
    fs = FeatureSet.from_ndarrays(x, y, shuffle=False)
    est.train(fs, batch_size=64, epochs=1)  # compile warm
    t0 = time.perf_counter()
    est.train(fs, batch_size=64, epochs=1)
    step_s = (time.perf_counter() - t0) / (len(x) // 64)
    ckpt = tempfile.mkdtemp(prefix="zoo_bench_ratio_ckpt_")
    try:
        t0 = time.perf_counter()
        est.save_checkpoint(ckpt)
        save_s = time.perf_counter() - t0
        est2 = _ratio_estimator()
        t0 = time.perf_counter()
        est2.load_checkpoint(ckpt)
        restore_s = time.perf_counter() - t0
    finally:
        shutil.rmtree(ckpt, ignore_errors=True)
    return {"step_ms": round(step_s * 1e3, 2),
            "save_ms": round(save_s * 1e3, 1),
            "restore_ms": round(restore_s * 1e3, 1),
            "restore_vs_step_ratio": round(restore_s / max(step_s, 1e-9),
                                           1)}


def _ratio_embed():
    """Sparse-segment-sum embedding update vs the dense full-table grad +
    full-table optimizer write — the sharded engine's core arithmetic,
    measured on CPU: touched-rows work is O(ids x dim) while the dense
    update reads and writes the whole [vocab, dim] table every step. The
    all-to-all exchange is NOT part of this probe (host-emulated
    collectives measure the emulation, not ICI); its emulated timing is
    still reported as a detail field."""
    import jax
    import jax.numpy as jnp
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.parallel import embedding as embed_engine

    ctx = init_tpu_context()
    vocab, dim, n_ids, lr = 1 << 20, 32, 1 << 12, 0.1
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, vocab, n_ids).astype(np.int32))
    table = jnp.asarray((rs.randn(vocab, dim) * 0.01).astype(np.float32))

    # donate the table so both sides update in place, as the real train
    # step does — otherwise a full-table copy dominates both timings
    @partial(jax.jit, donate_argnums=(0,))
    def dense_step(t):
        g = jax.grad(lambda tt: jnp.sum(jnp.take(tt, ids, axis=0) ** 2))(t)
        return t + (-lr) * g  # full-table read+write

    @partial(jax.jit, donate_argnums=(0,))
    def sparse_step(t):
        # the per-shard arithmetic of parallel/embedding.py: dedup-unique,
        # segment-sum per unique id, scatter only the touched rows
        rows = jnp.take(t, ids, axis=0)
        u, inv = jnp.unique(ids, size=n_ids, fill_value=t.shape[0],
                            return_inverse=True)
        g_u = jax.ops.segment_sum(2.0 * rows, inv.ravel(),
                                  num_segments=n_ids)
        return t.at[u].add((-lr) * g_u, mode="drop")

    def timed(fn, arg, calls=20):
        cur = fn(jnp.copy(arg))  # compile; copy because fn may donate
        jax.block_until_ready(cur)
        t0 = time.perf_counter()
        for _ in range(calls):
            cur = fn(cur)
        jax.block_until_ready(cur)
        return (time.perf_counter() - t0) / calls

    dense_s, sparse_s = timed(dense_step, table), timed(sparse_step, table)
    out = {"vocab": vocab, "dim": dim, "ids_per_step": n_ids,
           "dense_step_ms": round(dense_s * 1e3, 3),
           "sparse_step_ms": round(sparse_s * 1e3, 3),
           "sparse_vs_dense_grad_ratio":
               round(dense_s / max(sparse_s, 1e-9), 2)}
    spec = embed_engine.make_shard_spec(vocab, dim, mesh=ctx.mesh)
    if spec is not None and embed_engine.can_run(spec, n_ids):
        pad = spec.padded - vocab
        sh_table = jnp.concatenate(
            [table, jnp.zeros((pad, dim), table.dtype)]) if pad else table

        @jax.jit
        def sharded_step(t):
            def loss(tt):
                rows, blob = embed_engine.sharded_lookup(tt, ids, spec)
                return jnp.sum(rows ** 2), blob
            (_l, blob), g = jax.value_and_grad(loss, has_aux=True)(t)
            new_t, _ = embed_engine.apply_row_update(
                "sgd", {"lr": lr}, spec, t, g, blob, {})
            return new_t

        out["shards"] = spec.shards
        out["sharded_emulated_step_ms"] = round(
            timed(sharded_step, sh_table, calls=5) * 1e3, 3)
        out["sharded_note"] = ("host-emulated collectives; exchange cost "
                               "is not representative of ICI")
    return out


def _ratio_embed_fused():
    """The fused multi-table embedding lookup (ops/embedding_kernels.py,
    ``kernels.fused_embedding``) vs the unfused per-table chain, measured
    on CPU where the win it can show is dispatch amortization: K tables
    of (gather + bag pool) plus the feature concat as K+1 separate jitted
    dispatches vs ONE jitted ``multi_table_lookup`` call — the shape of
    an NCF/Wide&Deep embedding tower. On the TPU the same fusion also
    keeps rows in VMEM through the pool and halves gather bytes in the
    int8 variant; neither is measurable here, so this probe is the
    dispatch-side proxy. Both paths are asserted bitwise identical
    before the ratio is published."""
    import jax
    import jax.numpy as jnp
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.ops import embedding_kernels as ek

    init_tpu_context()
    rs = np.random.RandomState(0)
    n_tables, vocab, dim, batch, bag = 24, 1 << 12, 8, 128, 2
    tables = [jnp.asarray((rs.randn(vocab, dim) * 0.01).astype(np.float32))
              for _ in range(n_tables)]
    indices = [jnp.asarray(rs.randint(0, vocab, (batch, bag))
                           .astype(np.int32)) for _ in range(n_tables)]
    combiners = ["sum"] * n_tables

    # the unfused reference: one jitted dispatch per table + the concat,
    # exactly the op chain the pre-fusion layers traced
    pool_one = jax.jit(partial(ek._gather_pool_ref, combiner="sum",
                               mask_negative=True))
    concat = jax.jit(lambda parts: jnp.concatenate(parts, axis=-1))

    def unfused():
        return concat([pool_one(t, i) for t, i in zip(tables, indices)])

    fused_call = jax.jit(lambda ts, ids: ek.multi_table_lookup(
        ts, ids, combiners))

    def fused():
        return fused_call(tables, indices)

    got_u = np.asarray(unfused())
    got_f = np.asarray(fused())
    parity_ok = bool(np.array_equal(got_u, got_f))
    if not parity_ok:
        raise RuntimeError(
            "fused multi_table_lookup diverged from the per-table "
            "reference — refusing to publish embedding_fused_speedup")

    def timed(fn, calls=50, repeats=3):
        jax.block_until_ready(fn())  # compile warm
        best = float("inf")
        for _ in range(repeats):  # min-of-repeats: scheduler-noise proof
            t0 = time.perf_counter()
            for _ in range(calls):
                out = fn()
            jax.block_until_ready(out)
            best = min(best, (time.perf_counter() - t0) / calls)
        return best

    unfused_s, fused_s = timed(unfused), timed(fused)
    return {"tables": n_tables, "vocab": vocab, "dim": dim,
            "batch": batch, "bag": bag,
            "unfused_dispatches": n_tables + 1, "fused_dispatches": 1,
            "unfused_lookup_ms": round(unfused_s * 1e3, 3),
            "fused_lookup_ms": round(fused_s * 1e3, 3),
            "embedding_fused_speedup":
                round(unfused_s / max(fused_s, 1e-9), 2),
            "parity_ok": parity_ok,
            "fused_note": ("dispatch-amortization proxy; on TPU the "
                           "pallas path additionally pools in VMEM and "
                           "halves gather bytes at int8")}


def _ratio_generate():
    """Continuous batching's core bet, isolated at the decode-engine
    level: one fused step over 32 occupied KV slots vs 32 serial
    per-request B=1 decodes of the same prompts. The batched loop mirrors
    the scheduler exactly (bucketed prefill into the slot caches, one
    jitted step + one host token-fetch per generated token), so the
    speedup is pure dispatch/compute amortization — and the two paths
    must stay bit-identical, which is asserted before the ratio is
    published."""
    import jax
    import jax.numpy as jnp
    from analytics_zoo_tpu.capture.lm import TransformerLM, prefill_bucket
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.ops.decode import init_slot_state

    init_tpu_context()
    rs = np.random.RandomState(0)
    streams, new_tokens, plen = 32, 8, 8
    lm = TransformerLM(vocab_size=64, hidden=32, n_block=2, n_head=2,
                       max_len=64, seed=0)
    lm.fit(rs.randint(0, 64, (32, 12)), batch_size=8, epochs=1)
    prompts = rs.randint(0, 64, (streams, plen))

    def serial():
        return np.stack([
            lm.generate(prompts[i:i + 1], max_new_tokens=new_tokens)[0]
            for i in range(streams)])

    params = lm.params
    tb = prefill_bucket(plen - 1, lm.max_len)
    padded = np.zeros((streams, tb), np.int32)
    padded[:, :plen - 1] = prompts[:, :-1]

    @jax.jit
    def step(tokens, state, caches):
        logits, caches = lm.slot_step(params, tokens, state["length"],
                                      caches)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        state = {"length": state["length"]
                 + state["active"].astype(jnp.int32),
                 "active": state["active"]}
        return nxt, state, caches

    def batched():
        caches = lm.init_slot_caches(streams)
        kvs = lm.prefill_kv(params, jnp.asarray(padded))
        caches = [{"k": c["k"].at[:, :, :tb, :].set(
                       k.astype(c["k"].dtype)),
                   "v": c["v"].at[:, :, :tb, :].set(
                       v.astype(c["v"].dtype))}
                  for c, (k, v) in zip(caches, kvs)]
        state = init_slot_state(streams)
        state = {"length": jnp.full((streams,), plen - 1, jnp.int32),
                 "active": jnp.ones((streams,),
                                    state["active"].dtype)}
        tokens = jnp.asarray(prompts[:, -1].astype(np.int32))
        out = []
        for _ in range(new_tokens):
            tokens, state, caches = step(tokens, state, caches)
            out.append(np.asarray(tokens))  # scheduler's per-step fetch
        return np.stack(out, axis=1)

    # compile the B=1 buckets with ONE stream (the timed pass reuses the
    # cached executables), the 32-slot prefill + fused step with a full one
    lm.generate(prompts[:1], max_new_tokens=new_tokens)
    batched()
    t0 = time.perf_counter()
    serial_out = serial()
    serial_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    batched_out = batched()
    batched_s = time.perf_counter() - t0
    total = streams * new_tokens
    out = {"decode_streams": streams,
           "new_tokens_per_stream": new_tokens,
           "serial_tokens_per_sec": round(total / serial_s, 1),
           "batched_tokens_per_sec": round(total / batched_s, 1),
           "decode_parity_ok": bool(np.array_equal(serial_out,
                                                   batched_out)),
           "batched_vs_serial_tokens_ratio":
               round(serial_s / max(batched_s, 1e-9), 2)}
    out.update(_ratio_paged(lm, rs, new_tokens, plen))
    return out


def _ratio_paged(lm, rs, new_tokens: int, plen: int, pstreams: int = 512,
                 page_len: int = 16):
    """Paged-512 vs contiguous-capacity at EQUAL KV HBM: 512 resident
    streams on a page pool holding one page each (their actual length)
    vs the number of contiguous ``max_len`` rectangles the same bytes
    buy. Both engines decode the same prompts; the shared rows are
    asserted bit-identical before the efficiency ratio is published —
    this is the CPU stand-in for the real-chip 512-stream bench level,
    so outage rounds still land a ``tokens_per_s_per_hbm_gb``."""
    import jax
    import jax.numpy as jnp
    from analytics_zoo_tpu.capture.lm import prefill_bucket
    from analytics_zoo_tpu.ops.decode import (_page_positions, _paged_write,
                                              init_slot_state)

    params = lm.params
    pl = page_len
    assert plen - 1 + new_tokens <= pl, "one page per stream by design"
    pool_pages = pstreams + 1
    # same KV bytes as `contig_cap` contiguous max_len rectangles
    contig_cap = max(1, (pool_pages - 1) * pl // lm.max_len)
    prompts = rs.randint(0, 64, (pstreams, plen))
    tb = prefill_bucket(plen - 1, lm.max_len)
    padded = np.zeros((pstreams, tb), np.int32)
    padded[:, :plen - 1] = prompts[:, :-1]
    width = lm.max_len // pl
    table = np.zeros((pstreams, width), np.int32)
    table[:, 0] = 1 + np.arange(pstreams)
    table = jnp.asarray(table)

    @jax.jit
    def prefill_paged(caches, kvs):
        positions = jnp.broadcast_to(
            jnp.arange(tb, dtype=jnp.int32)[None], (pstreams, tb))
        pages, offs = _page_positions(table, positions, pl)
        return [_paged_write(c, pages, offs, k.transpose(0, 2, 1, 3),
                             v.transpose(0, 2, 1, 3), True)
                for c, (k, v) in zip(caches, kvs)]

    @jax.jit
    def pstep(tokens, state, caches):
        logits, caches = lm.paged_slot_step(params, tokens,
                                            state["length"], table, caches)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        state = {"length": state["length"]
                 + state["active"].astype(jnp.int32),
                 "active": state["active"]}
        return nxt, state, caches

    @jax.jit
    def cstep(tokens, state, caches):
        logits, caches = lm.slot_step(params, tokens, state["length"],
                                      caches)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        state = {"length": state["length"]
                 + state["active"].astype(jnp.int32),
                 "active": state["active"]}
        return nxt, state, caches

    def run_paged():
        caches = lm.init_paged_caches(pool_pages, pl)
        kvs = lm.prefill_kv(params, jnp.asarray(padded))
        caches = prefill_paged(caches, kvs)
        state = init_slot_state(pstreams)
        state = {"length": jnp.full((pstreams,), plen - 1, jnp.int32),
                 "active": jnp.ones((pstreams,), state["active"].dtype)}
        tokens = jnp.asarray(prompts[:, -1].astype(np.int32))
        outs = []
        for _ in range(new_tokens):
            tokens, state, caches = pstep(tokens, state, caches)
            outs.append(np.asarray(tokens))
        return np.stack(outs, axis=1)

    def run_contig():
        n = contig_cap
        caches = lm.init_slot_caches(n)
        kvs = lm.prefill_kv(params, jnp.asarray(padded[:n]))
        caches = [{"k": c["k"].at[:, :, :tb, :].set(
                       k.astype(c["k"].dtype)),
                   "v": c["v"].at[:, :, :tb, :].set(
                       v.astype(c["v"].dtype))}
                  for c, (k, v) in zip(caches, kvs)]
        state = init_slot_state(n)
        state = {"length": jnp.full((n,), plen - 1, jnp.int32),
                 "active": jnp.ones((n,), state["active"].dtype)}
        tokens = jnp.asarray(prompts[:n, -1].astype(np.int32))
        outs = []
        for _ in range(new_tokens):
            tokens, state, caches = cstep(tokens, state, caches)
            outs.append(np.asarray(tokens))
        return np.stack(outs, axis=1)

    run_paged()  # compile both engines before timing
    run_contig()
    t0 = time.perf_counter()
    paged_out = run_paged()
    paged_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    contig_out = run_contig()
    contig_s = time.perf_counter() - t0
    head_dim = lm.hidden // lm.n_head
    paged_gb = (lm.n_block * 2 * pool_pages * lm.n_head * pl
                * head_dim * 4 / 1e9)
    contig_gb = (lm.n_block * 2 * contig_cap * lm.n_head * lm.max_len
                 * head_dim * 4 / 1e9)
    paged_eff = pstreams * new_tokens / paged_s / paged_gb
    contig_eff = contig_cap * new_tokens / contig_s / contig_gb
    return {"paged_streams": pstreams,
            "contiguous_capacity_streams": contig_cap,
            "paged_parity_ok": bool(np.array_equal(
                paged_out[:contig_cap], contig_out)),
            "paged_tokens_per_sec": round(
                pstreams * new_tokens / paged_s, 1),
            "contig_tokens_per_sec": round(
                contig_cap * new_tokens / contig_s, 1),
            "kv_pool_hbm_gb": round(paged_gb, 6),
            "tokens_per_s_per_hbm_gb": round(paged_eff, 1),
            "paged_vs_contig_hbm_efficiency_ratio": round(
                paged_eff / max(contig_eff, 1e-9), 2)}


def _ratio_etl():
    """Zero-copy slab handoff vs eager gather on a small table — the
    etl_to_train workload's A/B shrunk to CPU scale, bit parity
    asserted."""
    import pandas as pd

    from analytics_zoo_tpu.common.config import global_config
    from analytics_zoo_tpu.xshard.engine import EtlEngine, XShard

    rs = np.random.RandomState(0)
    n = 40_000
    df = pd.DataFrame({"a": rs.rand(n), "b": rs.rand(n),
                       "y": rs.rand(n).astype(np.float32)})
    cfg = global_config()

    def timed(mode):
        cfg.set("data.handoff", mode)
        eng = EtlEngine(num_workers=2)
        try:
            xs = XShard.from_pandas(df, 4, engine=eng).map(
                lambda d: d.assign(z=d.a + d.b))
            t0 = time.perf_counter()
            fs = xs.to_featureset(["a", "b", "z"], "y")
            dt = time.perf_counter() - t0
            return dt, np.asarray(fs.features).copy(), \
                np.asarray(fs.labels).copy()
        finally:
            cfg.unset("data.handoff")
            eng.close()

    timed("slab")  # warm forks + allocators
    t_slab, x_slab, y_slab = timed("slab")
    t_gather, x_gather, y_gather = timed("gather")
    parity = bool(np.array_equal(x_slab, x_gather)
                  and np.array_equal(y_slab, y_gather))
    if not parity:
        raise RuntimeError("slab handoff diverged from gather baseline")
    return {"slab_handoff_s": round(t_slab, 4),
            "gather_handoff_s": round(t_gather, 4),
            "handoff_parity_ok": parity,
            "zero_copy_vs_gather_ratio":
                round(t_gather / max(t_slab, 1e-9), 2)}


def _ratio_fleet():
    """Routed 3-instance fleet vs a single instance at equal offered
    load — the serving_fleet workload's A/B shrunk to CPU scale. Fake
    instances are threads draining their per-instance spool with a fixed
    per-record stall, so the ratio isolates what the ROUTER buys
    (placement spreading work) from accelerator throughput."""
    import tempfile
    import threading

    from analytics_zoo_tpu.serving.fleet import (FleetInstance,
                                                 FleetRouter,
                                                 instance_queue)
    from analytics_zoo_tpu.serving.queues import FileQueue

    n, stall_s = 90, 0.004

    def timed(k: int) -> float:
        root = tempfile.mkdtemp(prefix="zoo_ratio_fleet_")
        front = FileQueue(root)
        insts, stop = [], threading.Event()

        def worker(q):
            while not stop.is_set():
                batch = q.claim_batch(8)
                if not batch:
                    time.sleep(0.001)
                    continue
                for uri, _rec in batch:
                    time.sleep(stall_s)
                    q.put_result(uri, {"value": [1.0]})

        for i in range(k):
            q = instance_queue(root, f"s{i}")
            hp = os.path.join(root, f"s{i}.health.json")
            with open(hp, "w") as f:
                json.dump({"state": "running", "time": time.time(),
                           "queue_pending": 0, "in_flight": 0}, f)
            insts.append(FleetInstance(f"s{i}", q, hp))
        # one refresh, then optimistic depth bumps spread placement —
        # no health churn in the timed region
        router = FleetRouter(front, insts, stale_after_s=3600.0,
                             health_refresh_s=1e9)
        for i in range(n):
            front.enqueue(f"u{i}", {"value": [0.0]})
        threads = [threading.Thread(target=worker, args=(inst.queue,),
                                    daemon=True) for inst in insts]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        done = {}
        deadline = time.time() + 60
        while len(done) < n and time.time() < deadline:
            router.route_once()
            done.update(front.all_results())
            time.sleep(0.001)
        dt = time.perf_counter() - t0
        stop.set()
        for t in threads:
            t.join(timeout=5)
        router.stop()
        if len(done) < n:
            raise RuntimeError(
                f"ratio_fleet: only {len(done)}/{n} results at k={k}")
        return dt

    t1 = timed(1)
    t3 = timed(3)
    return {"single_records_per_sec": round(n / t1, 1),
            "routed3_records_per_sec": round(n / t3, 1),
            "routed3_vs_single_ratio": round(t1 / max(t3, 1e-9), 2)}


def _ratio_fleet_redis():
    """Consumer-group fan-out vs a single consumer on ONE shared stream —
    the serving_fleet_redis workload's A/B shrunk to CPU scale. Uses a
    real server when one is reachable; otherwise the SAME RedisQueue
    claim/ack machinery runs against the in-process stream fake, so an
    outage round still lands a record."""
    client, backend = _fleet_redis_client(require=False)
    n, stall_s, batch = 96, 0.004, 8
    t1, _ = _consumer_group_ab(client, n, stall_s, batch, 1)
    t3, claims = _consumer_group_ab(client, n, stall_s, batch, 3)
    return {"backend": backend,
            "single_consumer_records_per_sec": round(n / t1, 1),
            "group3_records_per_sec": round(n / t3, 1),
            "per_consumer_claims": claims,
            "group3_vs_single_ratio": round(t1 / max(t3, 1e-9), 2)}


def _ratio_online():
    """Online row-subset continual training vs full-batch retrain at
    equal clicks — the online_learning workload's win shrunk to CPU
    scale. Each of W click windows either (a) advances ONE continual
    trainer by a window of steps off the stream journal, or (b)
    retrains a fresh model from scratch on every click seen so far —
    the offline baseline an online loop replaces. Equal clicks served
    to the serving fleet either way; the ratio is wall time."""
    import tempfile

    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.keras import objectives, optimizers
    from analytics_zoo_tpu.models import NeuralCF
    from analytics_zoo_tpu.serving.queues import make_queue

    init_tpu_context()
    users, items, batch, windows = 400, 360, 32, 4
    window_records = batch * 4
    rs = np.random.RandomState(0)
    uid = rs.randint(1, users + 1, window_records * windows)
    iid = rs.randint(1, items + 1, window_records * windows)
    lab = ((uid % 2) == (iid % 2)).astype(np.float32)

    def make_est():
        ncf = NeuralCF(users, items, 2, user_embed=8, item_embed=8,
                       hidden_layers=(16, 8), mf_embed=8)
        return Estimator(model=ncf.build_model(),
                         loss_fn=objectives.get(
                             "sparse_categorical_crossentropy"),
                         optimizer=optimizers.SGD(0.1), seed=7)

    # (a) continual: one trainer follows the stream journal
    root = tempfile.mkdtemp(prefix="zoo_ratio_online_")
    q = make_queue(f"dir://{root}/clicks")
    q.enqueue_many([(f"c{i}", {"x": [int(uid[i]), int(iid[i])],
                               "y": int(lab[i]), "ts": 0.0})
                    for i in range(window_records * windows)])
    fs = FeatureSet.from_queue(q, os.path.join(root, "journal"),
                               epoch_records=window_records,
                               watermark_s=0.0)
    est = make_est()
    est.train_online(fs, batch_size=batch,
                     max_steps=window_records // batch)  # warm: compile
    t0 = time.perf_counter()
    for w in range(2, windows + 1):
        est.train_online(fs, batch_size=batch,
                         max_steps=w * (window_records // batch))
    online_s = time.perf_counter() - t0
    fs.close()

    # (b) full retrain: fresh model over ALL clicks so far, per window
    x_all = np.stack([uid, iid], 1).astype(np.float32)
    make_est().train(FeatureSet.from_ndarrays(
        x_all[:window_records], lab[:window_records], shuffle=False),
        batch_size=batch, epochs=1)  # warm: compile
    t0 = time.perf_counter()
    for w in range(2, windows + 1):
        n = window_records * w
        make_est().train(FeatureSet.from_ndarrays(
            x_all[:n], lab[:n], shuffle=False),
            batch_size=batch, epochs=1)
    retrain_s = time.perf_counter() - t0

    return {"online_continual_s": round(online_s, 4),
            "full_retrain_s": round(retrain_s, 4),
            "windows": windows, "window_records": window_records,
            "online_vs_retrain_ratio":
                round(retrain_s / max(online_s, 1e-9), 2)}


def _ratio_tp():
    """Sharded-KV decode vs the single-device pool, bit parity asserted —
    the tp_decode workload's premise shrunk to CPU scale. The paged
    pool's PAGE axis spreads over every local device and the fused
    step's page gathers keep decode token-identical, so sharding buys
    capacity without forking numerics. A tensor-parallel forward of the
    same checkpoint (column/row-parallel GSPMD rules) is also checked
    against the replicated loss before the ratio is published."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from analytics_zoo_tpu.capture.lm import TransformerLM, prefill_bucket
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.ops.decode import (_page_positions, _paged_write,
                                              init_slot_state,
                                              shard_paged_pool)
    from analytics_zoo_tpu.parallel import (param_sharding,
                                            transformer_tp_rules)

    init_tpu_context()
    rs = np.random.RandomState(0)
    streams, new_tokens, plen, pl = 16, 8, 9, 16
    lm = TransformerLM(vocab_size=64, hidden=32, n_block=2, n_head=2,
                       max_len=64, seed=0)
    lm.fit(rs.randint(0, 64, (32, 12)), batch_size=8, epochs=1)
    params = lm.params
    n_dev = jax.local_device_count()
    kv_shard = max(d for d in (8, 4, 2, 1)
                   if d <= n_dev and n_dev % d == 0)

    per_stream = 2  # two pages hold prompt + decode budget
    assert plen + new_tokens <= per_stream * pl
    pool = streams * per_stream + 1
    pool += (-pool) % kv_shard
    prompts = rs.randint(0, 64, (streams, plen))
    tb = prefill_bucket(plen - 1, lm.max_len)
    padded = np.zeros((streams, tb), np.int32)
    padded[:, :plen - 1] = prompts[:, :-1]
    table = np.zeros((streams, lm.max_len // pl), np.int32)
    table[:, 0] = 1 + 2 * np.arange(streams)
    table[:, 1] = 2 + 2 * np.arange(streams)
    table = jnp.asarray(table)

    @jax.jit
    def prefill_paged(caches, kvs):
        positions = jnp.broadcast_to(
            jnp.arange(tb, dtype=jnp.int32)[None], (streams, tb))
        pages, offs = _page_positions(table, positions, pl)
        return [_paged_write(c, pages, offs, k.transpose(0, 2, 1, 3),
                             v.transpose(0, 2, 1, 3), True)
                for c, (k, v) in zip(caches, kvs)]

    @jax.jit
    def pstep(tokens, state, caches):
        logits, caches = lm.paged_slot_step(params, tokens,
                                            state["length"], table, caches)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        state = {"length": state["length"]
                 + state["active"].astype(jnp.int32),
                 "active": state["active"]}
        return nxt, state, caches

    def run(shard):
        caches = lm.init_paged_caches(pool, pl)
        kvs = lm.prefill_kv(params, jnp.asarray(padded))
        caches = prefill_paged(caches, kvs)
        if shard > 1:
            caches = shard_paged_pool(caches, shard)
        state = init_slot_state(streams)
        state = {"length": jnp.full((streams,), plen - 1, jnp.int32),
                 "active": jnp.ones((streams,), state["active"].dtype)}
        tokens = jnp.asarray(prompts[:, -1].astype(np.int32))
        outs = []
        for _ in range(new_tokens):
            tokens, state, caches = pstep(tokens, state, caches)
            outs.append(np.asarray(tokens))  # scheduler's per-step fetch
        return np.stack(outs, axis=1)

    run(1)  # compile both layouts before timing
    run(kv_shard)
    t0 = time.perf_counter()
    base_out = run(1)
    base_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    shard_out = run(kv_shard)
    shard_s = time.perf_counter() - t0
    if not np.array_equal(base_out, shard_out):
        raise RuntimeError(
            "sharded-KV decode diverged from the single-device pool")

    # TP forward of the same checkpoint: GSPMD partitions the matmuls,
    # not the numbers — loss must match the replicated layout
    tp_ok, tp_shards = None, 1
    candidates = [d for d in (4, 2) if d <= n_dev and n_dev % d == 0
                  and lm.n_head % d == 0 and lm.intermediate % d == 0]
    if candidates:
        tp_shards = candidates[0]
        batch = jnp.asarray(prompts[:8].astype(np.int32))
        base_loss = float(jax.jit(lm._loss)(params, batch))
        tp_mesh = Mesh(np.asarray(jax.devices()[:tp_shards]), ("model",))
        shards = param_sharding(tp_mesh, params,
                                transformer_tp_rules("model"))
        tp_loss = float(jax.jit(lm._loss)(
            jax.device_put(params, shards), batch))
        tp_ok = bool(abs(tp_loss - base_loss)
                     <= 1e-5 * max(1.0, abs(base_loss)))
        if not tp_ok:
            raise RuntimeError(
                f"tensor-parallel loss {tp_loss} diverged from "
                f"replicated {base_loss}")
    total = streams * new_tokens
    return {"decode_streams": streams, "kv_shards": kv_shard,
            "new_tokens_per_stream": new_tokens,
            "unsharded_tokens_per_sec": round(total / base_s, 1),
            "sharded_tokens_per_sec": round(total / shard_s, 1),
            "sharded_decode_parity_ok": True,  # asserted above
            "tp_forward_shards": tp_shards,
            "tp_forward_parity_ok": tp_ok,
            "sharded_vs_unsharded_tokens_ratio":
                round(base_s / max(shard_s, 1e-9), 2)}


def _ratio_moe():
    """Expert all-to-all vs the dense-dispatch einsum on ONE MoE layer,
    bit parity asserted — the moe_train workload's exchange A/B shrunk
    to CPU scale. Same params, same routing: the fixed-size
    dedup→route→local-FFN→reverse exchange must be arithmetic-identical
    to the dense contraction (including the dropped-token count in the
    state leaf) before the throughput ratio is published."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.keras.engine import MOE_DROP_KEY
    from analytics_zoo_tpu.parallel import set_default_mesh
    from analytics_zoo_tpu.parallel.moe import MoE

    init_tpu_context()
    n_dev = jax.local_device_count()
    e, d, h, n_tok = 8, 16, 32, 2048
    ep = max(dv for dv in (4, 2, 1)
             if dv <= n_dev and n_dev % dv == 0 and e % dv == 0)
    x = jnp.asarray(
        np.random.RandomState(0).rand(n_tok, d).astype(np.float32))
    rng = jax.random.PRNGKey(0)

    def build(exchange):
        layer = MoE(num_experts=e, hidden_dim=h, k=1,
                    capacity_factor=1.25, group_size=n_tok // ep,
                    exchange=exchange, name="ratio_moe")
        params, state = layer.build(rng, (None, d))
        return layer, params, state

    dense_layer, params, state = build("dense")
    dense_fn = jax.jit(lambda p, s, v: dense_layer.call(p, s, v))
    if ep > 1:
        mesh = Mesh(np.asarray(jax.devices()).reshape(n_dev // ep, ep),
                    ("data", "expert"))
        set_default_mesh(mesh)
        try:
            a2a_layer, _p, _s = build("alltoall")
            a2a_fn = jax.jit(lambda p, s, v: a2a_layer.call(p, s, v))
            y_a2a, st_a2a = a2a_fn(params, state, x)  # trace + compile
        finally:
            set_default_mesh(None)
    else:  # single local device: no expert axis to exchange over
        a2a_fn = dense_fn
        y_a2a, st_a2a = a2a_fn(params, state, x)
    y_dense, st_dense = dense_fn(params, state, x)

    if not np.array_equal(np.asarray(y_dense), np.asarray(y_a2a)):
        raise RuntimeError(
            "all-to-all exchange diverged from the dense dispatch")
    drops_dense = int(st_dense[MOE_DROP_KEY])
    drops_a2a = int(st_a2a[MOE_DROP_KEY])
    if drops_dense != drops_a2a:
        raise RuntimeError(
            f"exchange drop counts diverged: dense={drops_dense} "
            f"alltoall={drops_a2a}")

    def timed(fn, iters=5):
        jax.block_until_ready(fn(params, state, x)[0])
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(params, state, x)[0]
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters

    dense_s = timed(dense_fn)
    a2a_s = timed(a2a_fn)
    return {"experts": e, "expert_shards": ep, "tokens": n_tok,
            "moe_exchange_parity_ok": True,  # asserted above
            "moe_drop_parity_ok": True,      # asserted above
            "moe_dropped_tokens": drops_a2a,
            "dense_dispatch_s": round(dense_s, 5),
            "alltoall_exchange_s": round(a2a_s, 5),
            "alltoall_vs_dense_exchange_ratio":
                round(dense_s / max(a2a_s, 1e-9), 2)}


_RATIO_IMPLS = {
    "transfer": _ratio_transfer,
    "transform": _ratio_transform,
    "dispatch": _ratio_dispatch,
    "eval": _ratio_eval,
    "serving": _ratio_serving,
    "brownout": _ratio_brownout,
    "obs": _ratio_obs,
    "recovery": _ratio_recovery,
    "embed": _ratio_embed,
    "embed_fused": _ratio_embed_fused,
    "generate": _ratio_generate,
    "etl": _ratio_etl,
    "fleet": _ratio_fleet,
    "fleet_redis": _ratio_fleet_redis,
    "online": _ratio_online,
    "tp": _ratio_tp,
    "moe": _ratio_moe,
}

#: every workload → (proxy impl, the detail key that becomes the record's
#: value). Keys must cover _WORKLOADS exactly (asserted by the smoke test).
_RATIO_PLAN = {
    "resnet50": ("transfer", "uint8_vs_f32_transfer_ratio"),
    "resnet50_int8": ("transfer", "uint8_vs_f32_transfer_ratio"),
    "quantized": ("transfer", "uint8_vs_f32_transfer_ratio"),
    "pipeline": ("transform", "mp_vs_thread_transform_ratio"),
    "ncf": ("embed_fused", "embedding_fused_speedup"),
    "widedeep": ("embed_fused", "embedding_fused_speedup"),
    "widedeep_sharded": ("embed", "sparse_vs_dense_grad_ratio"),
    "bert": ("dispatch", "multi_dispatch_speedup"),
    "longseq": ("dispatch", "multi_dispatch_speedup"),
    "eval": ("eval", "async_vs_sync_eval_ratio"),
    "serving": ("serving", "batch16_vs_batch1_serving_ratio"),
    "serving_slo": ("serving", "batch16_vs_batch1_serving_ratio"),
    "serving_brownout": ("brownout", "naive_vs_budgeted_retry_ratio"),
    "serving_fleet": ("fleet", "routed3_vs_single_ratio"),
    "serving_fleet_redis": ("fleet_redis", "group3_vs_single_ratio"),
    "obs_overhead": ("obs", "enabled_vs_disabled_record_ratio"),
    "recovery": ("recovery", "restore_vs_step_ratio"),
    "generate": ("generate", "batched_vs_serial_tokens_ratio"),
    "etl_to_train": ("etl", "zero_copy_vs_gather_ratio"),
    "online_learning": ("online", "online_vs_retrain_ratio"),
    "tp_decode": ("tp", "sharded_vs_unsharded_tokens_ratio"),
    "moe_train": ("moe", "alltoall_vs_dense_exchange_ratio"),
}

#: impl results shared across the workloads that proxy to the same impl
#: (and across smoke-test parametrizations)
_ratio_memo = {}


def _run_ratio(name: str) -> "_BenchResult":
    """One workload's CPU-parity record: run (or reuse) its proxy impl and
    wrap the ratio in the standard record schema."""
    impl_key, value_key = _RATIO_PLAN[name]
    detail = _ratio_memo.get(impl_key)
    if detail is None:
        detail = _RATIO_IMPLS[impl_key]()
        _ratio_memo[impl_key] = detail
    return _BenchResult(
        metric=f"{name}_cpu_ratio", value=detail.get(value_key),
        unit="ratio", mfu=None,
        detail={"mode": "cpu_ratio", "proxy_for": name, **detail})


def _call_with_alarm(fn, budget_s: float):
    """In-process per-workload budget (ratio mode runs without subprocess
    isolation): SIGALRM → TimeoutError, old handler restored."""
    import signal

    def fire(signum, frame):
        raise TimeoutError(f"ratio round exceeded {budget_s:.0f}s")

    old = signal.signal(signal.SIGALRM, fire)
    signal.alarm(int(max(budget_s, 1)))
    try:
        return fn()
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def _force_cpu_backend() -> None:
    """Point jax at the CPU backend before anything initializes it — the
    ratio impls must not hang on the same dead tunnel the preflight just
    diagnosed. env var covers the not-yet-imported case; config.update
    covers jax already imported (but no backend created yet)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    if "jax" in sys.modules:
        try:
            sys.modules["jax"].config.update("jax_platforms", "cpu")
        except Exception:
            pass


# -- resumable sharding + baseline diff ---------------------------------------

_STATE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "BENCH_STATE.json")


def _load_state() -> dict:
    try:
        with open(_STATE_PATH) as f:
            data = json.load(f)
        return {n: _BenchResult(r)
                for n, r in data.get("results", {}).items()}
    except Exception:
        return {}


def _save_state(results) -> None:
    tmp = _STATE_PATH + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump({"results": {n: dict(r) for n, r in results.items()}},
                      f)
        os.replace(tmp, _STATE_PATH)
    except OSError:
        pass


def _clear_state() -> None:
    try:
        os.remove(_STATE_PATH)
    except OSError:
        pass


def _select_shard(names, shard) -> list:
    """Deterministic round-robin split of the run order: shard (i, n)
    takes every n-th workload starting at i, so the expensive head rows
    spread across shards instead of all landing in shard 0."""
    if not shard:
        return list(names)
    i, n = shard
    return [name for idx, name in enumerate(names) if idx % n == i]


def _load_baseline() -> dict:
    path = os.environ.get("BENCH_BASELINE") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BASELINE.json")
    try:
        with open(path) as f:
            return json.load(f)
    except Exception:
        return {}


#: detail keys tracked in BASELINE.json alongside the headline value —
#: bytes-roofline fractions regress silently otherwise (a fast kernel
#: swap can hold samples/s while doubling HBM traffic)
_BASELINE_DETAIL_KEYS = {
    "generate": ("tokens_per_sec_c32", "ttft_p99_ms_c32",
                 "tokens_per_s_per_hbm_gb"),
    "ncf": ("hbm_roofline_fraction", "embedding_fused_speedup"),
    "widedeep": ("hbm_roofline_fraction", "embedding_fused_speedup"),
    "widedeep_sharded": ("hbm_roofline_fraction",
                         "sharded_vs_dense_samples_ratio"),
    "resnet50": ("hbm_roofline_fraction",),
    "etl_to_train": ("zero_copy_vs_gather_ratio",),
    "tp_decode": ("hbm_roofline_fraction", "kv_pool_hbm_gb"),
    "moe_train": ("hbm_roofline_fraction",
                  "moe_vs_dense_samples_ratio"),
}


def _baseline_diff(results, baseline=None):
    """Percent deltas vs BASELINE.json's optional ``workloads`` mapping
    (``{name: {value, unit}}``, written by ``--write-baseline``). Only
    numeric, same-unit pairs compare; None when nothing does (the
    reference itself publishes no absolute numbers). Baseline entries may
    also carry a ``detail`` sub-map of tracked keys
    (``_BASELINE_DETAIL_KEYS``) diffed as ``name.key``."""
    doc = baseline if baseline is not None else _load_baseline()
    base = doc.get("workloads") or {}
    diffs = {}
    for name, r in results.items():
        b = base.get(name)
        if not isinstance(b, dict):
            continue
        val, bval = r.get("value"), b.get("value")
        if isinstance(val, (int, float)) and isinstance(bval, (int, float)) \
                and bval and b.get("unit") == r.get("unit"):
            diffs[name] = round((val - bval) / abs(bval) * 100.0, 1)
        bdetail = b.get("detail")
        rdetail = r.get("detail") or {}
        if not isinstance(bdetail, dict):
            continue
        for key in _BASELINE_DETAIL_KEYS.get(name, ()):
            dv, dbv = rdetail.get(key), bdetail.get(key)
            if isinstance(dv, (int, float)) \
                    and isinstance(dbv, (int, float)) and dbv:
                diffs[f"{name}.{key}"] = round(
                    (dv - dbv) / abs(dbv) * 100.0, 1)
    return diffs or None


def _write_baseline(results) -> None:
    """--write-baseline: record this round's numeric results as the
    comparison floor for future runs (other BASELINE.json keys kept)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE.json")
    try:
        with open(path) as f:
            doc = json.load(f)
    except Exception:
        doc = {}
    doc["workloads"] = {}
    for n, r in results.items():
        if not isinstance(r.get("value"), (int, float)):
            continue
        entry = {"value": r.get("value"), "unit": r.get("unit", "")}
        if isinstance(r.get("mfu"), (int, float)):
            entry["mfu"] = r["mfu"]  # the roofline gate compares it
        tracked = {k: (r.get("detail") or {}).get(k)
                   for k in _BASELINE_DETAIL_KEYS.get(n, ())}
        tracked = {k: v for k, v in tracked.items()
                   if isinstance(v, (int, float))}
        if tracked:
            entry["detail"] = tracked
        doc["workloads"][n] = entry
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, path)


# -- roofline-regression gate --------------------------------------------------
# A fast kernel swap can hold samples/s while sliding off the roofline
# (e.g. doubling HBM traffic, or silently falling back to the unfused
# path). The gate makes such a slide fail the round loudly: each gated
# workload's hbm_roofline_fraction and MFU must not drop more than
# _GATE_TOL relative to the values --write-baseline recorded.

_GATE_WORKLOADS = ("ncf", "widedeep", "widedeep_sharded", "tp_decode",
                   "moe_train")
_GATE_KEYS = ("hbm_roofline_fraction", "mfu")
_GATE_TOL = float(os.environ.get("BENCH_GATE_TOL", "0.10"))


def _gate_check(results, baseline=None, tolerance=None):
    """Compare the gated workloads' roofline fractions and MFU against
    BASELINE.json; return human-readable failure strings (empty = pass).
    Exempt: cpu_ratio / failed records (no roofline to regress),
    workloads or keys absent from the baseline, and baseline values below
    1e-3 (a 10% slice of a 0.0001 MFU is measurement noise, not signal —
    gather-bound steps are judged by hbm_roofline_fraction instead)."""
    tol = _GATE_TOL if tolerance is None else tolerance
    doc = baseline if baseline is not None else _load_baseline()
    base = doc.get("workloads") or {}
    failures = []
    for name in _GATE_WORKLOADS:
        r, b = results.get(name), base.get(name)
        if not isinstance(r, dict) or not isinstance(b, dict):
            continue
        detail = r.get("detail") or {}
        if detail.get("mode") == "cpu_ratio" or "error" in detail \
                or str(r.get("metric", "")).endswith(("_failed",
                                                      "_skipped")):
            continue
        bdetail = b.get("detail") or {}
        for key in _GATE_KEYS:
            cur = r.get("mfu") if key == "mfu" else detail.get(key)
            ref = b.get("mfu") if key == "mfu" else bdetail.get(key)
            if not isinstance(cur, (int, float)) \
                    or not isinstance(ref, (int, float)) or ref < 1e-3:
                continue
            if cur < ref * (1.0 - tol):
                failures.append(
                    f"{name}.{key}: {cur:.6g} is more than {tol:.0%} "
                    f"below baseline {ref:.6g}")
    return failures


def _apply_gate(results, no_gate=False, baseline=None):
    """Run the gate and stamp the verdict into each gated record — the
    failure must be explicit in the emitted JSON, not only an exit code
    the driver may or may not keep. Returns the failure list (empty when
    passing, or when skipped via --no-gate)."""
    if no_gate:
        for name in _GATE_WORKLOADS:
            r = results.get(name)
            if isinstance(r, dict):
                r.setdefault("detail", {})["roofline_gate"] = "skipped"
        return []
    failures = _gate_check(results, baseline=baseline)
    failed = {f.split(".", 1)[0] for f in failures}
    for name in _GATE_WORKLOADS:
        r = results.get(name)
        if not isinstance(r, dict):
            continue
        d = r.setdefault("detail", {})
        if d.get("mode") == "cpu_ratio":
            continue  # exempt records carry no verdict
        d["roofline_gate_ok"] = name not in failed
        mine = [f for f in failures if f.startswith(name + ".")]
        if mine:
            d["roofline_gate_failures"] = mine
    return failures


def _validate_record(rec) -> list:
    """Record-schema check (shared with tests/test_bench_ratio.py):
    returns human-readable problems, empty = valid."""
    problems = []
    if not isinstance(rec, dict):
        return ["record must be a dict"]
    if not isinstance(rec.get("metric"), str) or not rec.get("metric"):
        problems.append("metric must be a non-empty string")
    if not isinstance(rec.get("unit"), str):
        problems.append("unit must be a string")
    v = rec.get("value")
    if v is not None and not isinstance(v, (int, float)):
        problems.append("value must be numeric or null")
    if not isinstance(rec.get("detail"), dict):
        problems.append("detail must be a dict")
    return problems


# keys hoisted from each workload's detail dict into the compact final line
# (everything else lives in BENCH_DETAIL.json + the full-detail stdout line)
_COMPACT_KEYS = {
    "resnet50": ("fed_images_per_sec", "hbm_roofline_fraction"),
    "resnet50_int8": ("bytes_per_step", "hbm_roofline_fraction"),
    "bert": ("fed_samples_per_sec", "numerics_ok"),
    "longseq": ("numerics_ok",),
    "ncf": ("hbm_roofline_fraction", "roofline_utilization",
            "embedding_fused_speedup", "roofline_gate_ok"),
    "widedeep": ("hbm_roofline_fraction", "roofline_utilization",
                 "embedding_fused_speedup", "roofline_gate_ok"),
    "widedeep_sharded": ("hbm_roofline_fraction", "roofline_utilization",
                         "hbm_footprint_ok",
                         "sharded_vs_dense_samples_ratio",
                         "roofline_gate_ok"),
    "eval": ("sync_eval_records_per_sec", "eval_speedup",
             "predict_speedup"),
    "quantized": ("fp32_images_per_sec",),
    "serving": ("bert_records_per_sec", "device_records_per_sec"),
    "serving_slo": ("p50_ms", "shed_rate", "deadline_miss_rate"),
    "generate": ("tokens_per_sec_c8", "tokens_per_sec_c128",
                 "tokens_per_sec_c512", "ttft_p99_ms_c32",
                 "tokens_per_s_per_hbm_gb"),
    "obs_overhead": ("overhead_under_2pct", "ops_under_2pct",
                     "event_burst_ok", "flow_chain_ok", "trace_pids"),
    "pipeline": (),
    "recovery": ("restore_ms", "recovery_vs_step", "parity_ok"),
    "etl_to_train": ("zero_copy_vs_gather_ratio", "handoff_parity_ok",
                     "profiler_etl_phases_ok"),
    "tp_decode": ("kv_shard", "hbm_exceeds_one_device",
                  "hbm_roofline_fraction", "ttft_p99_ms",
                  "roofline_gate_ok"),
    "moe_train": ("hbm_roofline_fraction", "moe_vs_dense_samples_ratio",
                  "moe_dropped_tokens", "roofline_gate_ok"),
}


def _compact_row(name, r):
    row = {"value": r.get("value"), "unit": r.get("unit")}
    if r.get("mfu") is not None:
        row["mfu"] = r["mfu"]
    d = r.get("detail") or {}
    for k in _COMPACT_KEYS.get(name, ()):
        if k in d and not isinstance(d[k], dict):
            row[k] = d[k]
    if "error" in d:
        row["error"] = str(d["error"])[:120]
    return row


def _emit_final(results, platform, num_devices, partial=False, note=None):
    """Write the full detail to BENCH_DETAIL.json + a full-detail stdout
    line, then a COMPACT final line (< ~1800 chars — the driver's tail
    capture is 2000 chars and truncation loses the headline, as happened
    in rounds 2-3)."""
    head = results.get("resnet50") or next(iter(results.values()))
    for r in results.values():  # children report platform; hoist + dedup
        d = r.get("detail") or {}
        if platform in (None, "unknown") and "platform" in d:
            platform, num_devices = d["platform"], d["num_devices"]
        d.pop("platform", None)
        d.pop("num_devices", None)
    full = {n: {"metric": r["metric"], "value": r["value"], "unit": r["unit"],
                "mfu": r.get("mfu"), **(r.get("detail") or {})}
            for n, r in results.items()}
    diff = _baseline_diff(results)
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_DETAIL.json"), "w") as f:
            json.dump({"partial": partial, "baseline_diff": diff,
                       "workloads": full}, f, indent=1)
    except OSError:
        pass
    print("BENCH_FULL_DETAIL: " + json.dumps(full), flush=True)
    compact = {
        "metric": head["metric"],
        "value": head["value"],
        "unit": head["unit"],
        "vs_baseline": diff,
        "detail": {
            "platform": platform,
            "num_devices": num_devices,
            "mfu": head.get("mfu"),
            "hbm_gbps_assumed": _HBM_GBPS,
            "full_detail": "BENCH_DETAIL.json",
            **({"partial": True} if partial else {}),
            **({"preflight": note} if note else {}),
            "workloads": {n: _compact_row(n, r) for n, r in results.items()},
        },
    }
    print(json.dumps(compact), flush=True)


def _parse_args(argv):
    """Tiny hand parser (argparse would swallow workload names that look
    like flags in driver logs): positional workload (or ``all``), plus
    --one NAME, --budget S, --ratio, --full, --shard i/n, --resume,
    --write-baseline, --no-gate."""
    args = {"which": "all", "one": None, "ratio": False, "full": False,
            "shard": None, "resume": False, "budget": None,
            "write_baseline": False, "no_gate": False}
    it = iter(argv)
    for a in it:
        if a == "--one":
            v = next(it)
            args["one"] = _ALIASES.get(v, v)
        elif a == "--budget":
            args["budget"] = float(next(it))
        elif a == "--ratio":
            args["ratio"] = True
        elif a == "--full":
            args["full"] = True
        elif a == "--resume":
            args["resume"] = True
        elif a == "--write-baseline":
            args["write_baseline"] = True
        elif a == "--no-gate":
            args["no_gate"] = True
        elif a == "--shard":
            i, n = next(it).split("/")
            args["shard"] = (int(i), int(n))
            if not 0 <= args["shard"][0] < args["shard"][1]:
                raise SystemExit(f"bad --shard {a}: need i/n with 0 <= i < n")
        elif a.startswith("-"):
            raise SystemExit(f"unknown flag {a}")
        else:
            args["which"] = _ALIASES.get(a, a)
    return args


def main():
    args = _parse_args(sys.argv[1:])
    if args["one"]:
        name = args["one"]
        # budget enforced in-process: on SIGALRM/SIGTERM the partial
        # record stashed so far still goes out on the marker line (r04/r05)
        _install_child_guard(
            name, args["budget"] if args["budget"]
            else max(_PER_WORKLOAD_S - 30, 60))
        result = _WORKLOADS[name]()
        result.setdefault("detail", {})
        from analytics_zoo_tpu.common.context import init_tpu_context
        child_ctx = init_tpu_context()  # cached: the workload already made it
        result["detail"]["platform"] = child_ctx.platform
        result["detail"]["num_devices"] = child_ctx.num_devices
        print(_MARKER + json.dumps(dict(result)), flush=True)
        # lingering non-daemon threads (inference pools, serving executors)
        # must not hold the interpreter open past the result
        sys.stdout.flush()
        os._exit(0)
    which = args["which"]
    names = list(_WORKLOADS) if which == "all" else [which]
    names = _select_shard(names, args["shard"])
    isolate = which == "all"
    ctx = None
    results = {}
    platform, num_devices = "unknown", None
    preflight_note = None
    per_cap = _PER_WORKLOAD_S

    if args["resume"]:
        for n, r in _load_state().items():
            if n in names and not str(r.get("metric", "")).endswith(
                    ("_failed", "_skipped")):
                results[n] = r
        if results:
            _log(f"resume: {len(results)} workload(s) carried over from "
                 f"{os.path.basename(_STATE_PATH)}: {sorted(results)}")

    def _finish(partial, code=0):
        if not results:
            results["none"] = _BenchResult(metric="no_workload_completed",
                                           value=None, unit="", mfu=None,
                                           detail={})
        if not partial and set(_WORKLOADS) <= set(results):
            _clear_state()  # full coverage landed: next round starts clean
        _emit_final(results, platform, num_devices, partial=partial,
                    note=preflight_note)
        sys.stdout.flush()
        os._exit(code)

    import signal
    for sig in (signal.SIGTERM, signal.SIGINT):
        # installed BEFORE the preflight: the driver's deadline kill must
        # produce a diagnostic final line even if it lands during the
        # (up-to-240s) preflight probe. Exit NONZERO (128+signum, the
        # shell convention) so anything keying on the return code records
        # a killed sweep as killed — the JSON contract (partial: true)
        # is unchanged
        signal.signal(sig,
                      lambda signum, _frame: _finish(partial=True,
                                                     code=128 + signum))

    ratio_mode = args["ratio"]
    probed_platform = None
    if isolate and not ratio_mode:
        # backend preflight in a THROWAWAY child: when the TPU tunnel is
        # down, jax backend init hangs indefinitely (observed >300s) — one
        # cheap probe here turns nine 700s futile child timeouts into a
        # fast sweep with a clear diagnostic in the final line
        import subprocess
        _log("preflight: probing device backend in a child")
        try:
            proc = subprocess.run(
                [sys.executable, "-c",
                 "import jax; d = jax.devices()[0]; "
                 "print(d.platform, d.device_kind)"],
                capture_output=True, text=True, timeout=240)
            ok = proc.returncode == 0
            tailtxt = (proc.stdout + proc.stderr).strip()[-200:]
        except Exception as e:
            ok, tailtxt = False, repr(e)[:200]
        if ok:
            last = tailtxt.splitlines()[-1] if tailtxt else ""
            probed_platform = (last.split() or ["unknown"])[0]
            _log(f"preflight ok: {last or '?'}")
        if not args["full"]:
            # degrade to CPU-parity ratios rather than limping through
            # absolute numbers that are either unobtainable (dead tunnel)
            # or meaningless (CPU backend)
            if not ok:
                ratio_mode = True
                preflight_note = (f"device backend preflight FAILED "
                                  f"({tailtxt}); CPU-parity ratio mode")
                _log(preflight_note)
                _force_cpu_backend()
            elif probed_platform == "cpu":
                ratio_mode = True
                preflight_note = "cpu backend: CPU-parity ratio mode"
                _log(preflight_note)
        elif not ok:
            preflight_note = (f"device backend preflight FAILED "
                              f"({tailtxt}); attempting workloads with "
                              f"shortened timeouts (--full)")
            _log(preflight_note)
            per_cap = 300.0

    if ratio_mode:
        # in-process (tiny CPU problems, nothing to isolate), SIGALRM as
        # the per-workload budget so one pathological proxy cannot zero
        # the round
        if args["ratio"]:
            _force_cpu_backend()
        for name in names:
            if name in results:  # resumed
                continue
            remaining = _BUDGET_S - (time.perf_counter() - _T0)
            if remaining < 60 and results:
                results[name] = _BenchResult(
                    metric=f"{name}_skipped", value=None, unit="", mfu=None,
                    detail={"error": "bench budget exhausted"})
                continue
            per = min(per_cap, max(remaining - 30, 60))
            _log(f"ratio mode: {name} (budget {per:.0f}s)")
            try:
                results[name] = _call_with_alarm(
                    lambda n=name: _run_ratio(n), per)
                _log(f"{name}: {results[name].get('value')} "
                     f"{results[name].get('unit')}")
            except Exception as e:
                _log(f"{name} ratio failed: {repr(e)[:200]}")
                results[name] = _BenchResult(
                    metric=f"{name}_failed", value=None, unit="", mfu=None,
                    detail={"mode": "cpu_ratio", "error": repr(e)})
            _save_state(results)
        platform = probed_platform or "cpu"
        if args["write_baseline"]:
            _write_baseline(results)
        gate_failures = _apply_gate(results, no_gate=args["no_gate"])
        if gate_failures:
            _log("roofline regression gate FAILED: "
                 + "; ".join(gate_failures))
            _finish(partial=False, code=3)
        _finish(partial=False)

    if not isolate:
        from analytics_zoo_tpu.common.context import init_tpu_context
        ctx = init_tpu_context()

    for name in names:
        if name in results:  # resumed from BENCH_STATE.json
            continue
        remaining = _BUDGET_S - (time.perf_counter() - _T0)
        if isolate and remaining < 150 and results:  # always try the first
            _log(f"budget exhausted ({remaining:.0f}s left): skipping {name}")
            results[name] = _BenchResult(
                metric=f"{name}_skipped", value=None, unit="", mfu=None,
                detail={"error": "bench budget exhausted"})
            continue
        # the tunnel to the remote compile service occasionally drops the
        # response mid-body on big HLO programs; retry before giving up —
        # but recompute the slice from the LIVE remaining budget each
        # attempt so a flapping workload can't starve the later rows
        for attempt in range(3):
            remaining = _BUDGET_S - (time.perf_counter() - _T0)
            if attempt > 0 and remaining < 150:
                _log(f"budget exhausted mid-retry of {name}")
                break
            per = min(per_cap, max(remaining - 60, 120))
            _log(f"running {name} (attempt {attempt + 1}, "
                 f"timeout {per:.0f}s)")
            try:
                results[name] = (_run_isolated(name, per) if isolate
                                 else _WORKLOADS[name]())
                _log(f"{name}: {results[name].get('value')} "
                     f"{results[name].get('unit')}")
                break
            except Exception as e:  # keep the headline line even if one fails
                _log(f"{name} attempt {attempt + 1} failed: {repr(e)[:200]}")
                results[name] = _BenchResult(metric=f"{name}_failed", value=None,
                                             unit="", mfu=None,
                                             detail={"error": repr(e)})
                if not _transient(e) or attempt == 2:
                    break
                time.sleep(5 * (attempt + 1))
        if isolate:
            _save_state(results)  # partial carry-over for --resume
    if ctx is not None:
        platform, num_devices = ctx.platform, ctx.num_devices
    if args["write_baseline"]:
        _write_baseline(results)
    gate_failures = _apply_gate(results, no_gate=args["no_gate"])
    if gate_failures:
        _log("roofline regression gate FAILED: " + "; ".join(gate_failures))
        _finish(partial=False, code=3)
    _finish(partial=False)


if __name__ == "__main__":
    sys.exit(main())
