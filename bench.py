"""Benchmark driver: prints ONE JSON line with the headline metric.

Round-1 flagship: NCF (MovieLens-1M scale) training throughput in samples/sec
on the available accelerator (BASELINE.json config #1). The reference
publishes no absolute numbers (`published: {}`), so ``vs_baseline`` is null.
"""
import json
import sys
import time

import numpy as np


def bench_ncf(batch_size: int = 8192, steps: int = 50, warmup: int = 5):
    import jax
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.keras import objectives, optimizers
    from analytics_zoo_tpu.models import NeuralCF

    ctx = init_tpu_context()
    ndev = ctx.num_devices
    if batch_size % ndev:
        batch_size = (batch_size // ndev) * ndev

    # MovieLens-1M dimensions
    users, items = 6040, 3706
    n = batch_size * 8
    rs = np.random.RandomState(0)
    x = np.stack([rs.randint(1, users + 1, n),
                  rs.randint(1, items + 1, n)], 1).astype(np.float32)
    y = rs.randint(0, 2, n).astype(np.float32)

    ncf = NeuralCF(users, items, 2, user_embed=64, item_embed=64,
                   hidden_layers=[128, 64, 32], mf_embed=32)
    model = ncf._ensure_built()
    est = Estimator(model=model,
                    loss_fn=objectives.get("sparse_categorical_crossentropy"),
                    optimizer=optimizers.Adam(1e-3))
    fs = FeatureSet.from_ndarrays(x, y)

    it = fs.train_iterator(batch_size)
    from analytics_zoo_tpu.feature import DeviceFeed
    feed = DeviceFeed(it, est.mesh)
    bx, by = next(feed)
    est._ensure_initialized(bx)
    step_fn = est._build_train_step()

    rng = jax.random.PRNGKey(0)
    params, opt_state, mstate = est.params, est.opt_state, est.model_state
    for i in range(warmup):
        params, opt_state, mstate, loss = step_fn(params, opt_state, mstate,
                                                  rng, bx, by)
    jax.block_until_ready(loss)

    start = time.perf_counter()
    for i in range(steps):
        bx, by = next(feed)
        params, opt_state, mstate, loss = step_fn(params, opt_state, mstate,
                                                  rng, bx, by)
    jax.block_until_ready(loss)
    elapsed = time.perf_counter() - start
    samples_per_sec = batch_size * steps / elapsed
    return samples_per_sec, ctx


def main():
    sps, ctx = bench_ncf()
    print(json.dumps({
        "metric": "ncf_train_samples_per_sec",
        "value": round(sps, 1),
        "unit": "samples/s",
        "vs_baseline": None,
        "detail": {
            "model": "NeuralCF ml-1m (embed 64, mlp 128-64-32, mf 32)",
            "batch_size": 8192,
            "platform": ctx.platform,
            "num_devices": ctx.num_devices,
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
