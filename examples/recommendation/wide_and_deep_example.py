"""Wide & Deep on census-shaped tabular data (north-star #3; reference
``pyzoo/zoo/examples/recommendation/wide_n_deep.py``).

Shows the full column workflow: a pandas frame, hash-crossed wide columns,
``ColumnFeatureInfo``, and the sparse wide table (gather + scatter-add
gradients — no giant one-hots).
"""
import argparse

import numpy as np

from analytics_zoo_tpu.models.recommendation.wide_and_deep import (
    ColumnFeatureInfo, WideAndDeep, cross_columns, features_from_dataframe)


def synthetic_census(n, seed=0):
    import pandas as pd
    rs = np.random.RandomState(seed)
    df = pd.DataFrame({
        "education": rs.randint(0, 16, n),
        "occupation": rs.randint(0, 1000, n),
        "workclass": rs.randint(0, 9, n),
        "marital": rs.randint(0, 7, n),
        "age": rs.uniform(17, 90, n).astype(np.float32),
        "hours": rs.uniform(1, 99, n).astype(np.float32),
    })
    df["label"] = ((df["education"] > 8) & (df["hours"] > 40)).astype(np.float32)
    return df


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=512)
    args = ap.parse_args()

    n = 4096 if args.smoke else 200_000
    df = synthetic_census(n)
    cross_dim = 1000 if args.smoke else 100_000
    df["edu_occ"] = cross_columns(df, ["education", "occupation"], cross_dim)

    info = ColumnFeatureInfo(
        wide_base_cols=["education", "occupation"], wide_base_dims=[16, 1000],
        wide_cross_cols=["edu_occ"], wide_cross_dims=[cross_dim],
        indicator_cols=["workclass", "marital"], indicator_dims=[9, 7],
        embed_cols=["education", "occupation"], embed_in_dims=[16, 1000],
        embed_out_dims=[8, 8],
        continuous_cols=["age", "hours"])
    xs, y = features_from_dataframe(df.assign(label=df["label"]), info)

    model = WideAndDeep("wide_n_deep", num_classes=2, column_info=info,
                        hidden_layers=(20, 10) if args.smoke else (40, 20, 10))
    model.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    result = model.fit(xs, y, batch_size=args.batch_size,
                       nb_epoch=args.epochs)
    print(f"train loss: {result['loss_history'][-1]:.4f}")
    print("eval:", {k: round(float(v), 4)
                    for k, v in model.evaluate(xs, y,
                                               batch_size=args.batch_size).items()})


if __name__ == "__main__":
    main()
