"""NeuralCF on implicit-feedback data (north-star #1; reference
``pyzoo/zoo/examples/recommendation/ncf_example.py``).

Trains the dual-tower (MF x MLP) recommender on synthetic MovieLens-shaped
interactions, evaluates, and produces per-user recommendations.
"""
import argparse

import numpy as np

from analytics_zoo_tpu.models import NeuralCF


def synthetic_interactions(users, items, n, seed=0):
    rs = np.random.RandomState(seed)
    uid = rs.randint(1, users + 1, n)
    iid = rs.randint(1, items + 1, n)
    # planted structure: users like items whose id shares parity
    label = ((uid % 2) == (iid % 2)).astype(np.float32)
    x = np.stack([uid, iid], axis=1).astype(np.float32)
    return x, label


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny CI config")
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--batch-size", type=int, default=1024)
    args = ap.parse_args()

    users, items, n = (200, 100, 4096) if args.smoke else (6040, 3706, 500_000)
    ncf = NeuralCF(users, items, num_classes=2,
                   user_embed=8 if args.smoke else 64,
                   item_embed=8 if args.smoke else 64,
                   hidden_layers=[16, 8] if args.smoke else [128, 64, 32],
                   mf_embed=4 if args.smoke else 32)
    ncf.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])

    x, y = synthetic_interactions(users, items, n)
    split = int(0.9 * n)
    result = ncf.fit(x[:split], y[:split], batch_size=args.batch_size,
                     nb_epoch=args.epochs)
    print(f"train loss: {result['loss_history'][-1]:.4f}")
    metrics = ncf.evaluate(x[split:], y[split:], batch_size=args.batch_size)
    print("eval:", {k: round(float(v), 4) for k, v in metrics.items()})

    # rank every item for users 1-3, keep the top 3 each
    cand_users = np.repeat(np.arange(1, 4), items)
    cand_items = np.tile(np.arange(1, items + 1), 3)
    recs = ncf.recommend_for_user(cand_users, cand_items, max_items=3)
    for uid, ranked in recs.items():
        print(f"user {uid} -> items {[int(i) for i, _, _ in ranked]}")


if __name__ == "__main__":
    main()
