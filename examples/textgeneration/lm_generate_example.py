"""Train a tiny character language model and generate from it.

Beyond-reference capability (the reference's generator is the RNN seq2seq
chatbot): ``TransformerLM`` trains with causal flash attention and decodes
off a static-shape KV cache — greedy, beam search, or sampled — with the
whole decode in one scan dispatch.

The toy corpus is arithmetic-progression "sentences" over a small
alphabet; after a few epochs the model continues any prompt correctly.
"""
import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--epochs", type=int, default=60)
    args = ap.parse_args()

    from analytics_zoo_tpu.capture import TransformerLM

    V, S = 16, 20
    n, epochs = (256, 40) if args.smoke else (2048, args.epochs)
    rs = np.random.RandomState(0)
    starts = rs.randint(0, V, n)
    strides = rs.choice([1, 2], n)
    data = (starts[:, None] + strides[:, None] * np.arange(S)[None]) % V

    lm = TransformerLM(vocab_size=V, hidden=48, n_block=2, n_head=4,
                       max_len=64)
    r = lm.fit(data, batch_size=64, epochs=epochs)
    print(f"next-token NLL: {r['loss_history'][0]:.3f} -> "
          f"{r['loss_history'][-1]:.3f}")

    prompt = data[:3, :6]
    greedy = lm.generate(prompt, max_new_tokens=8)
    beam = lm.generate(prompt, max_new_tokens=8, beam_size=4)
    sampled = lm.generate(prompt, max_new_tokens=8, temperature=0.7,
                          top_p=0.9)
    expected = np.stack([(prompt[i, -1] + strides[i] * np.arange(1, 9)) % V
                         for i in range(3)])
    for i in range(3):
        print(f"prompt {prompt[i].tolist()} stride {strides[i]}")
        print(f"  greedy : {greedy[i].tolist()}")
        print(f"  beam-4 : {beam[i].tolist()}")
        print(f"  sampled: {sampled[i].tolist()}")
        print(f"  expect : {expected[i].tolist()}")
    acc = (greedy == expected).mean()
    print(f"greedy continuation accuracy: {acc:.2f}")


if __name__ == "__main__":
    main()
