"""Online learning loop (docs/online.md): click feedback streams into a
`FeatureSet.from_queue`, a sharded NCF retrains on it continually with
`train_online`, and each snapshot is promoted onto a serving fleet —
canary first, verified live via `model_version`, rolled back on failure
— while the fleet keeps answering recommendation requests.
"""
import argparse
import os
import tempfile

import numpy as np


def simulated_clicks(users, items, n, seed=0):
    """Click records as queue payloads: features, label, event time."""
    rs = np.random.default_rng(seed)
    out = []
    for i in range(n):
        u = int(rs.integers(1, users + 1))
        v = int(rs.integers(1, items + 1))
        # planted structure: users click items whose id shares parity
        out.append((f"click-{i}", {"x": [u, v], "y": int(u % 2 == v % 2),
                                   "ts": 0.0}))
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true", help="tiny CI config")
    ap.add_argument("--rounds", type=int, default=3,
                    help="train→export→promote rounds")
    args = ap.parse_args()

    import jax
    from jax.sharding import Mesh

    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.keras import objectives
    from analytics_zoo_tpu.keras.optimizers import SGD
    from analytics_zoo_tpu.models import NeuralCF
    from analytics_zoo_tpu.online import Promoter, export_servable
    from analytics_zoo_tpu.serving import ClusterServing, ServingConfig
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue
    from analytics_zoo_tpu.serving.queues import make_queue

    init_tpu_context()
    root = tempfile.mkdtemp(prefix="zoo_online_example_")
    users, items = (40, 36) if args.smoke else (6040, 3706)
    steps_per_round = 4 if args.smoke else 200
    batch = 16 if args.smoke else 512
    epoch_records = 4 * batch

    # 1. the click stream: producers enqueue_many; the ingest thread
    # journals past the watermark under backpressure
    clicks = make_queue(f"dir://{root}/clicks")
    clicks.enqueue_many(simulated_clicks(
        users, items, epoch_records * (args.rounds + 1)))
    fs = FeatureSet.from_queue(clicks, os.path.join(root, "journal"),
                               epoch_records=epoch_records, watermark_s=0.0)

    # 2. continual trainer: sharded embeddings, row-subset updates
    ndev = len(jax.devices())
    mesh = Mesh(np.asarray(jax.devices()[:min(4, ndev)]), ("data",))
    ncf = NeuralCF(users, items, 2, user_embed=8, item_embed=8,
                   hidden_layers=(16, 8), mf_embed=8, shard_embeddings=True)
    est = Estimator(model=ncf.build_model(),
                    loss_fn=objectives.get("sparse_categorical_crossentropy"),
                    optimizer=SGD(0.1), mesh=mesh, seed=7)
    est.set_checkpoint(os.path.join(root, "ckpt"))

    # 3. a two-instance serving fleet born on the v0 export
    est.train_online(fs, batch_size=batch, max_steps=1)
    v0 = export_servable(ncf, est, f"{root}/exports/v0")
    servers = {}
    for name in ("canary", "replica"):
        cfg = ServingConfig(data_src=f"dir://{root}/srv-{name}",
                            model_path=v0, model_type="zoo",
                            image_shape=(2,), batch_size=4, batch_wait_ms=5)
        servers[name] = ClusterServing(cfg)
    prom = Promoter(servers, canary="canary")
    inq = InputQueue(f"dir://{root}/srv-canary")
    outq = OutputQueue(f"dir://{root}/srv-canary")

    # 4. the loop: train on the stream, serve it, promote each snapshot
    for r in range(1, args.rounds + 1):
        est.train_online(fs, batch_size=batch,
                         max_steps=est.global_step + steps_per_round,
                         snapshot_interval_s=30.0)
        inq.enqueue_tensor(f"round-{r}",
                           np.array([1.0 + r, 2.0], np.float32))
        while servers["canary"].serve_once():
            pass
        print(f"round {r}: step={est.global_step} "
              f"served={outq.query(f'round-{r}', timeout_s=30)}")
        version = prom.promote(
            export_servable(ncf, est, f"{root}/exports/v{r}"))
        live = {n: s.health_snapshot()["model_version"]
                for n, s in servers.items()}
        print(f"round {r}: promoted {version}, fleet live on {live}")
        assert set(live.values()) == {version}

    fs.close()
    print(f"done: {args.rounds} promotions, final step {est.global_step}, "
          f"clicks left in queue: {clicks.pending_count()}")


if __name__ == "__main__":
    main()
