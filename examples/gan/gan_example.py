"""GAN training with alternating generator/discriminator steps (reference
``pyzoo/zoo/examples/tensorflow/tfpark/gan`` — GANEstimator on MNIST; here
a 2D toy distribution so it runs anywhere in seconds).

The generator learns to map N(0,1) noise onto a shifted Gaussian mode; both
sub-networks are plain JAX functions, and the estimator fuses the d_steps +
g_steps schedule into ONE jitted device step (lax.fori_loop) per batch.
"""
import argparse

import jax
import jax.numpy as jnp
import numpy as np

from analytics_zoo_tpu.capture import GANEstimator
from analytics_zoo_tpu.keras import optimizers


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--steps", type=int, default=400)
    args = ap.parse_args()

    steps = 60 if args.smoke else args.steps
    rs = np.random.RandomState(0)
    real = (rs.randn(4096, 2) * 0.1 + np.array([2.0, -1.0])).astype(
        np.float32)

    def gen_init(rng, noise):
        k1, k2 = jax.random.split(rng)
        return {"w1": jax.random.normal(k1, (noise.shape[-1], 16)) * 0.1,
                "b1": jnp.zeros(16),
                "w2": jax.random.normal(k2, (16, 2)) * 0.1,
                "b2": jnp.zeros(2)}

    def gen_fn(p, z):
        h = jax.nn.relu(z @ p["w1"] + p["b1"])
        return h @ p["w2"] + p["b2"]

    def disc_init(rng, x):
        k1, k2 = jax.random.split(rng)
        return {"w1": jax.random.normal(k1, (x.shape[-1], 16)) * 0.1,
                "b1": jnp.zeros(16),
                "w2": jax.random.normal(k2, (16, 1)) * 0.1,
                "b2": jnp.zeros(1)}

    def disc_fn(p, x):
        h = jax.nn.relu(x @ p["w1"] + p["b1"])
        return h @ p["w2"] + p["b2"]

    def g_loss(fake_logits):
        return -jnp.mean(fake_logits)

    def d_loss(real_logits, fake_logits):
        return jnp.mean(jax.nn.softplus(-real_logits)) + \
            jnp.mean(jax.nn.softplus(fake_logits))

    gan = GANEstimator(gen_fn, disc_fn, g_loss, d_loss, gen_init, disc_init,
                       generator_optimizer=optimizers.Adam(1e-2),
                       discriminator_optimizer=optimizers.Adam(1e-2),
                       noise_dim=4, d_steps=1, g_steps=2)
    hist = gan.train(real, batch_size=128, steps=steps)
    samples = gan.generate(512)
    print(f"after {hist['iterations']} steps generator mean = "
          f"({samples.mean(0)[0]:+.2f}, {samples.mean(0)[1]:+.2f}); "
          f"target (+2.00, -1.00)")


if __name__ == "__main__":
    main()
