"""BERT sequence classification via the capture-style task estimator
(north-star #4; reference ``pyzoo/zoo/examples/tfpark/estimator`` BERT
classifier flow).

``--smoke`` uses a 2-layer toy BERT; the default is BERT-base shapes, which
the attention stack runs through the pallas flash kernel on TPU.
"""
import argparse

import numpy as np

from analytics_zoo_tpu.capture.text import BERTClassifier, bert_input_pack


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=64)
    args = ap.parse_args()

    if args.smoke:
        config = dict(vocab=1000, hidden_size=32, n_block=2, n_head=2,
                      max_position_len=64, intermediate_size=64)
        n, seq = 64, 16
    else:
        config = dict(vocab=30522, hidden_size=768, n_block=12, n_head=12,
                      max_position_len=512, intermediate_size=3072)
        n, seq = 2048, args.seq_len

    clf = BERTClassifier(num_classes=2, bert_config=config, optimizer="adam")
    rs = np.random.RandomState(0)
    tokens = rs.randint(1, config["vocab"], (n, seq))
    # planted signal: label = whether token 7 appears in the sequence
    labels = (tokens == 7).any(axis=1).astype(np.float32)

    result = clf.fit(tokens, labels, batch_size=args.batch_size,
                     epochs=args.epochs)
    print(f"fine-tune loss: {result['loss_history'][-1]:.4f}")

    probs = clf.predict(tokens[:8])
    print("predictions:", np.argmax(probs, axis=-1).tolist())


if __name__ == "__main__":
    main()
