"""Time-series anomaly detection (reference
``pyzoo/zoo/examples/anomalydetection/anomaly_detection.py``).

Trains the LSTM window-forecaster ``AnomalyDetector`` on a clean seasonal
signal, then flags the points whose forecast error is in the top
``anomaly_size`` — which recovers the synthetic spikes we injected.
"""
import argparse

import numpy as np

from analytics_zoo_tpu.models import AnomalyDetector, detect_anomalies, unroll


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--epochs", type=int, default=10)
    args = ap.parse_args()

    n, unroll_len, epochs = (200, 8, 2) if args.smoke else \
        (4000, 24, args.epochs)
    rs = np.random.RandomState(0)
    t = np.arange(n)
    series = np.sin(2 * np.pi * t / 50) + 0.05 * rs.randn(n)
    spike_idx = rs.choice(np.arange(unroll_len, n), size=max(3, n // 100),
                          replace=False)
    series[spike_idx] += 3.0  # injected anomalies

    x, y = unroll(series.astype(np.float32), unroll_length=unroll_len)
    m = AnomalyDetector(feature_shape=(unroll_len, 1),
                        hidden_layers=[16, 8], dropouts=[0.2, 0.2])
    m.default_compile()
    m.fit(x, y, batch_size=64, nb_epoch=epochs)

    pred = np.asarray(m.predict(x, batch_size=128)).ravel()
    report = detect_anomalies(y.ravel(), pred, anomaly_size=len(spike_idx))
    flagged = {i + unroll_len for i, (_, _, _, is_a) in enumerate(report)
               if is_a}
    hits = len(flagged & set(spike_idx.tolist()))
    print(f"flagged {len(flagged)} anomalies, "
          f"{hits}/{len(spike_idx)} injected spikes recovered")


if __name__ == "__main__":
    main()
