"""Multi-process (pod) training (reference RayOnSpark examples,
``pyzoo/zoo/examples/ray_on_spark``).

The launcher spawns N coordinated worker processes (``jax.distributed``),
each owning its local devices; FeatureSet shards per process, XLA handles
the cross-host gradient collectives, and rank failures kill the pod fast.
On a real TPU pod the same ``train_worker`` runs once per host instead.
"""
import argparse
import json
import os
import tempfile

import numpy as np


def train_worker(workdir: str) -> int:
    """Runs in every pod process (after jax.distributed.initialize)."""
    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
    from analytics_zoo_tpu.keras.layers import Activation, Dense

    ctx = init_tpu_context()
    rs = np.random.RandomState(0)
    x = rs.randn(512, 10).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.float32)
    fs = FeatureSet.from_ndarrays(x, y)  # auto per-process shard

    est = Estimator(
        model=Sequential([Dense(32), Activation("relu"), Dense(2)]),
        loss_fn=objectives.get("sparse_categorical_crossentropy"),
        optimizer=optimizers.Adam(1e-2))
    result = est.train(fs, batch_size=64, epochs=2)
    with open(os.path.join(workdir, f"rank{ctx.process_index}.json"), "w") as f:
        json.dump({"rank": ctx.process_index, "shard": fs.size,
                   "loss": result["loss_history"][-1]}, f)
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--processes", type=int, default=2)
    args = ap.parse_args()

    from analytics_zoo_tpu.cluster import PodLauncher
    workdir = tempfile.mkdtemp(prefix="pod_example_")
    launcher = PodLauncher(
        num_processes=args.processes,
        devices_per_process=2,   # virtual CPU devices; drop on real TPU hosts
        platform="cpu")
    launcher.run("examples.cluster.pod_train:train_worker", args=[workdir],
                 timeout=300)
    for name in sorted(os.listdir(workdir)):
        with open(os.path.join(workdir, name)) as f:
            print(name, json.load(f))


if __name__ == "__main__":
    main()
