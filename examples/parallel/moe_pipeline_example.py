"""Expert + pipeline parallelism on a simulated multi-chip mesh.

Runs everywhere: with no real multi-chip hardware it provisions virtual CPU
devices, exactly how CI validates the sharded paths. Shows the two newest
mesh axes — a switch-MoE block training over a (data, expert) mesh, and a
GPipe pipeline streaming microbatches over a `pipe` axis.
"""
import argparse
import os


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--real", action="store_true",
                    help="use the attached accelerators instead of a "
                         "simulated CPU mesh (needs >= --devices chips)")
    args = ap.parse_args()

    import jax
    if not args.real:  # simulate the mesh on virtual CPU devices; this must
        # happen before ANY backend initialization
        os.environ["XLA_FLAGS"] = " ".join(
            [f for f in os.environ.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
            + [f"--xla_force_host_platform_device_count={args.devices}"])
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from analytics_zoo_tpu.estimator import Estimator
    from analytics_zoo_tpu.feature import FeatureSet
    from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
    from analytics_zoo_tpu.keras.layers import Dense
    from analytics_zoo_tpu.parallel import MoE, gpipe, moe_sharding_rule

    n = jax.device_count()
    ep = next((c for c in (4, 2) if n % c == 0), 1)
    mesh = Mesh(np.asarray(jax.devices()).reshape(n // ep, ep),
                ("data", "expert"))

    # --- expert parallelism: MoE classifier over (data, expert) ----------
    model = Sequential([Dense(16, name="proj"),
                        MoE(num_experts=ep, hidden_dim=32, name="moe"),
                        Dense(2, activation="softmax", name="head")])
    est = Estimator(model=model,
                    loss_fn=objectives.get("sparse_categorical_crossentropy"),
                    optimizer=optimizers.Adam(1e-2), mesh=mesh,
                    param_sharding_rules=[moe_sharding_rule])
    rs = np.random.RandomState(0)
    x = rs.randn(32 * n, 8, 16).astype(np.float32)
    y = (x.mean(axis=-1) > 0).astype(np.float32)
    with mesh:
        result = est.train(FeatureSet.from_ndarrays(x, y),
                           batch_size=8 * n, epochs=2 if args.smoke else 8)
    print(f"MoE over dp={n // ep} x ep={ep}: loss "
          f"{result['loss_history'][-1]:.4f}; expert table sharding: "
          f"{est.params['moe']['w_in'].sharding.spec}")

    # --- pipeline parallelism: GPipe microbatch streaming ----------------
    pipe_mesh = Mesh(np.asarray(jax.devices()), ("pipe",))
    rngs = jax.random.split(jax.random.PRNGKey(0), n)
    stages = [{"w": jax.random.normal(r, (16, 16)) * 0.3,
               "b": jnp.zeros(16)} for r in rngs]

    def stage_fn(p, a):
        return jnp.tanh(a @ p["w"] + p["b"])

    stacked, fn = gpipe(pipe_mesh, stage_fn, stages, n_microbatches=4)
    xb = jax.random.normal(jax.random.PRNGKey(1), (32, 16))
    grads = jax.jit(jax.grad(lambda s: jnp.sum(fn(s, xb) ** 2)))(stacked)
    print(f"pipeline over {n} stages: fwd+bwd ok, grad norm "
          f"{float(jnp.linalg.norm(grads['w'])):.3f}, bubble fraction "
          f"{(n - 1) / (4 + n - 1):.2f}")


if __name__ == "__main__":
    main()
