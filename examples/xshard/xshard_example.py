"""Sharded pandas ETL → training set (reference
``pyzoo/zoo/examples/xshard`` — DataShards read_csv/apply/repartition).

Writes a small partitioned CSV dataset, reads it back as parallel pandas
shards, feature-engineers shard-wise (each shard transformed in a worker
process), then lowers the shards into a FeatureSet and fits a classifier.
"""
import argparse
import os
import tempfile

import numpy as np
import pandas as pd

from analytics_zoo_tpu import xshard
from analytics_zoo_tpu.keras import Sequential
from analytics_zoo_tpu.keras.layers import Dense


def add_ab(df):
    """Module-level transform: PodDataShards ships it to worker processes
    (the same picklability contract Ray imposes on remote functions)."""
    return df.assign(ab=df["a"] * df["b"])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--pod", action="store_true",
                    help="run the transform chain in pod worker processes")
    args = ap.parse_args()

    rows_per_file, files = (100, 3) if args.smoke else (20000, 8)
    rs = np.random.RandomState(0)
    with tempfile.TemporaryDirectory() as d:
        for i in range(files):
            x = rs.rand(rows_per_file, 3)
            pd.DataFrame({
                "a": x[:, 0], "b": x[:, 1], "c": x[:, 2],
                "label": (x.sum(1) > 1.5).astype(np.float32),
            }).to_csv(os.path.join(d, f"part-{i}.csv"), index=False)

        if args.pod:
            # distributed variant: each pod worker reads + transforms its
            # stride of files, the driver merges (RayDataShards role)
            pod = xshard.PodDataShards.read_csv(d, num_workers=2,
                                                timeout=300)
            shards = pod.transform_shard(add_ab).to_local().repartition(2)
        else:
            shards = xshard.read_csv(d)
            print(f"read {shards.num_partitions()} shards")
            # shard-wise feature engineering, then rebalance
            shards = shards.apply(add_ab).repartition(2)
        total = sum(len(s) for s in shards.collect())
        print(f"{total} rows across {shards.num_partitions()} shards "
              f"after repartition")

        fs = shards.to_featureset(feature_cols=["a", "b", "c", "ab"],
                                  label_cols=["label"])
        model = Sequential([Dense(8, activation="relu"),
                            Dense(2, activation="softmax")])
        model.compile(optimizer="adam",
                      loss="sparse_categorical_crossentropy",
                      metrics=["accuracy"])
        model.fit(fs, batch_size=64, nb_epoch=5 if args.smoke else 20)
        metrics = model.evaluate(fs, batch_size=64)
        print(f"train metrics: {metrics}")


if __name__ == "__main__":
    main()
