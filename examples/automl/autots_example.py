"""AutoML time-series pipeline search (reference
``pyzoo/zoo/examples/automl/nyc_taxi_dataset.py`` flow:
TimeSequencePredictor.fit → searched TimeSequencePipeline →
evaluate/predict/save/load).

Searches feature+model configs over a synthetic traffic-like series. Use
``--recipe random`` for a broader (longer) random search; smoke mode uses
the one-trial SmokeRecipe.
"""
import argparse
import tempfile

import numpy as np
import pandas as pd

from analytics_zoo_tpu.automl import (
    RandomRecipe, SmokeRecipe, TimeSequencePipeline, TimeSequencePredictor)


def make_series(n):
    rs = np.random.RandomState(0)
    ts = pd.date_range("2026-01-01", periods=n, freq="h")
    value = (10 + 3 * np.sin(np.arange(n) * 2 * np.pi / 24)
             + 0.5 * rs.randn(n))
    return pd.DataFrame({"datetime": ts, "value": value.astype(np.float32)})


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--recipe", default="smoke", choices=["smoke", "random"])
    args = ap.parse_args()

    df = make_series(120 if args.smoke else 2000)
    split = int(len(df) * 0.8)
    train_df, val_df = df.iloc[:split], df.iloc[split:]

    recipe = SmokeRecipe() if (args.smoke or args.recipe == "smoke") \
        else RandomRecipe()
    tsp = TimeSequencePredictor(future_seq_len=1)
    pipeline = tsp.fit(train_df, validation_df=val_df, recipe=recipe,
                       metric="mse")

    scores = pipeline.evaluate(val_df, metrics=["mse", "smape"])
    print(f"holdout: mse={scores['mse']:.4f} smape={scores['smape']:.2f}")

    with tempfile.TemporaryDirectory() as d:
        pipeline.save(f"{d}/pipe")
        reloaded = TimeSequencePipeline.load(f"{d}/pipe")
        preds = reloaded.predict(val_df)
        print(f"reloaded pipeline predicted {len(preds)} steps")


if __name__ == "__main__":
    main()
