"""Long-context attention: flash kernel + ring sequence parallelism.

New TPU-native capability with no reference counterpart (the reference has
no long-context machinery). Demonstrates the three tiers on one example:

1. ``flash_attention`` — O(seq) memory fused attention (pallas kernel on
   TPU, blockwise scan elsewhere) on a sequence too long for a
   materialized score matrix to be comfortable;
2. ``ring_self_attention`` — the same computation sharded over a ``seq``
   mesh axis, where each device holds ``seq/n`` of the tokens and K/V
   shards rotate over the ring (ICI on a real pod);
3. a numerical cross-check of both against the quadratic reference.

On a laptop this runs on the simulated multi-device CPU mesh
(``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``);
on a TPU pod slice the same code runs the pallas kernel per hop.
"""
import argparse
import time

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--seq", type=int, default=16384)
    ap.add_argument("--devices", type=int, default=8,
                    help="virtual CPU devices for the ring (simulation)")
    ap.add_argument("--real", action="store_true",
                    help="use the real attached devices instead")
    args = ap.parse_args()

    import os

    import jax
    if not args.real:  # simulate the seq mesh on virtual CPU devices; must
        # happen before ANY backend initialization
        os.environ["XLA_FLAGS"] = " ".join(
            [f for f in os.environ.get("XLA_FLAGS", "").split()
             if not f.startswith("--xla_force_host_platform_device_count")]
            + [f"--xla_force_host_platform_device_count={args.devices}"])
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from analytics_zoo_tpu.common.context import init_tpu_context
    from analytics_zoo_tpu.ops.attention import (
        dot_product_attention, flash_attention)
    from analytics_zoo_tpu.parallel.ring_attention import (
        SEQ_AXIS, ring_self_attention)

    seq = 512 if args.smoke else args.seq
    b, h, d = 1, 4, 64
    rs = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rs.randn(b, h, seq, d).astype(np.float32))
               for _ in range(3))

    # 1. single-device flash attention
    start = time.perf_counter()
    out = jax.block_until_ready(flash_attention(q, k, v, causal=True))
    print(f"flash_attention seq={seq}: {time.perf_counter() - start:.2f}s "
          f"(includes compile)")

    # 2. ring attention over a seq-sharded mesh (all devices on the ring)
    n_seq = len(jax.devices())
    ctx = init_tpu_context(mesh_shape=(n_seq,), axis_names=(SEQ_AXIS,))
    ring_out = ring_self_attention(ctx.mesh, q, k, v, causal=True)
    print(f"ring over {n_seq} devices: each holds seq/{n_seq} = "
          f"{seq // n_seq} tokens")

    # 3. cross-check (quadratic reference only at smoke sizes)
    if seq <= 2048:
        ref = dot_product_attention(q, k, v, causal=True)
        e1 = float(jnp.max(jnp.abs(out - ref)))
        e2 = float(jnp.max(jnp.abs(ring_out - ref)))
        print(f"max err vs reference: flash {e1:.2e}, ring {e2:.2e}")
    else:
        e = float(jnp.max(jnp.abs(ring_out - out)))
        print(f"max err ring vs flash: {e:.2e}")


if __name__ == "__main__":
    main()
