"""Transfer learning on image classification (north-star #2; reference
``pyzoo/zoo/examples/nnframes/finetune/image_finetuning_example.py``).

Builds a ResNet, freezes the backbone up to the global pool, attaches a new
2-class head, and fine-tunes — only the head receives gradients (XLA
dead-code-eliminates the frozen backward pass). The input pipeline ships
uint8 and normalizes on device (see bench.py: 3.4x transfer win).
"""
import argparse

import numpy as np

from analytics_zoo_tpu.estimator import Estimator
from analytics_zoo_tpu.feature import FeatureSet
from analytics_zoo_tpu.keras import objectives, optimizers
from analytics_zoo_tpu.models.image.imageclassification import resnet


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args()

    depth, size, n = (18, 32, 64) if args.smoke else (50, 224, 2048)
    # preprocess="imagenet_uint8": normalize ON DEVICE so the host ships
    # 1-byte pixels, not 4-byte floats
    model = resnet(depth, num_classes=2, input_shape=(size, size, 3),
                   preprocess="imagenet_uint8")

    # freeze everything up to (and including) the global average pool; the
    # classifier head keeps training
    model.freeze_up_to("avg_pool")
    print(f"trainable after freeze: {model.trainable_param_names()}")

    rs = np.random.RandomState(0)
    raw = rs.randint(0, 255, (n, size, size, 3), dtype=np.uint8)
    labels = (raw.mean(axis=(1, 2, 3)) > 127).astype(np.float32)
    fs = FeatureSet.from_ndarrays(raw, labels)  # stays uint8 end to end

    est = Estimator(model=model,
                    loss_fn=objectives.get("sparse_categorical_crossentropy"),
                    optimizer=optimizers.SGD(0.01, momentum=0.9))
    result = est.train(fs, batch_size=args.batch_size, epochs=args.epochs)
    print(f"fine-tune loss: {result['loss_history'][-1]:.4f} "
          f"({result['iterations']} steps)")


if __name__ == "__main__":
    main()
