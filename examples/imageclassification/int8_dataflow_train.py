"""Quantized-dataflow int8 ResNet training (new TPU-native capability; the
reference's int8 story is OpenVINO inference-only,
``zoo/examples/vnni/openvino/Perf.scala``).

``resnet(dataflow="int8")`` swaps the backbone for the whole-backbone int8
implementation (``ops/int8_dataflow.py``): int8 tensors flow BETWEEN
layers under delayed (FP8-style) scaling, convs run on the int8 MXU path,
and the saved activations are the int8 tensors themselves — the byte-cut
lever past the bf16 step's HBM roofline (see docs/training.md).

Usage:
    python int8_dataflow_train.py                # ResNet-50 at 224px
    python int8_dataflow_train.py --smoke        # ResNet-18 at 32px, CPU-ok
"""
import argparse

import numpy as np

from analytics_zoo_tpu.estimator import Estimator
from analytics_zoo_tpu.feature import FeatureSet
from analytics_zoo_tpu.keras import objectives, optimizers
from analytics_zoo_tpu.models.image.imageclassification import resnet


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=None)
    args = ap.parse_args()

    import jax.numpy as jnp
    depth, size, n = (18, 32, 64) if args.smoke else (50, 224, 2048)
    batch = args.batch_size or (16 if args.smoke else 256)
    model = resnet(depth, num_classes=2, input_shape=(size, size, 3),
                   dataflow="int8")

    rs = np.random.RandomState(0)
    x = rs.rand(n, size, size, 3).astype(np.float32)
    labels = (x.mean(axis=(1, 2, 3)) > 0.5).astype(np.float32)
    x[labels == 1] += 0.3  # separable signal so the loss visibly descends
    fs = FeatureSet.from_ndarrays(x, labels)

    est = Estimator(model=model,
                    loss_fn=objectives.get("sparse_categorical_crossentropy"),
                    optimizer=optimizers.SGD(0.01, momentum=0.9),
                    compute_dtype=jnp.bfloat16)
    result = est.train(fs, batch_size=batch, epochs=args.epochs)
    print(f"int8-dataflow train loss: {result['loss_history'][-1]:.4f} "
          f"({result['iterations']} steps)")
    probs = np.asarray(est.predict(x[:8], batch_size=8))
    print(f"eval-path predictions (running stats): {probs.argmax(1)}")


if __name__ == "__main__":
    main()
