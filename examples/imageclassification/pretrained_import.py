"""Golden-faithful pretrained import + labeled prediction (reference
``ImageClassifier.scala:37`` pretrained-artifact loading + label maps).

Imports a torchvision-format ResNet-18 ``state_dict`` into the native
classifier with torch-exact padding geometry, verifies the probabilities
against torch when torch is importable, attaches a label map, and runs
labeled top-k predictions over an ImageSet.

Usage:
    python pretrained_import.py --weights resnet18.pt --labels labels.json \
        --images ./photos
    python pretrained_import.py --smoke     # synthesizes weights in torch
"""
import argparse
import json
import tempfile

import numpy as np

from analytics_zoo_tpu.feature.image import LocalImageSet
from analytics_zoo_tpu.models import ImageClassifier
from analytics_zoo_tpu.net.torch_import import torchvision_resnet18


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--weights", default=None,
                    help="torchvision resnet18 state_dict (.pt)")
    ap.add_argument("--labels", default=None, help="label map (json/txt)")
    ap.add_argument("--images", default=None, help="directory of images")
    ap.add_argument("--classes", type=int, default=1000)
    args = ap.parse_args()

    size = 64 if args.smoke else 224
    classes = 10 if args.smoke else args.classes
    clf = ImageClassifier("resnet18", num_classes=classes,
                          input_shape=(size, size, 3))

    if args.weights:
        clf.load_pretrained_torch(args.weights)
    else:
        import torch
        torch.manual_seed(0)
        tm = torchvision_resnet18(classes)
        tm.eval()
        clf.load_pretrained_torch(tm)
        # golden check: the imported model must reproduce torch exactly
        rs = np.random.RandomState(0)
        x = rs.rand(2, size, size, 3).astype(np.float32)
        with torch.no_grad():
            want = torch.softmax(
                tm(torch.from_numpy(np.transpose(x, (0, 3, 1, 2)))),
                dim=-1).numpy()
        got = np.asarray(clf.predict(x))
        np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-3)
        print("golden check OK: probabilities match torch within 1e-4")

    if args.labels:
        clf.with_label_map(args.labels)
    else:
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            json.dump([f"class_{i}" for i in range(classes)], f)
        clf.with_label_map(f.name)

    if args.images:
        image_set = LocalImageSet.read(args.images)
    else:
        rs = np.random.RandomState(1)
        image_set = LocalImageSet(
            [rs.randint(0, 255, (size, size, 3)).astype(np.uint8)
             for _ in range(4)])
    for i, preds in enumerate(clf.predict_image_set(image_set, top_k=3)):
        pretty = ", ".join(f"{lbl}={p:.3f}" for lbl, p in preds)
        print(f"image {i}: {pretty}")

    # ship the whole thing as ONE pretrained bundle (weights + config +
    # label map + preprocessing spec) and reload it — works with gs:// URIs
    # through the same call
    from analytics_zoo_tpu.models import ZooModel
    bundle_dir = tempfile.mkdtemp(prefix="zoo_bundle_")
    clf.save_pretrained(bundle_dir)
    reloaded = ZooModel.load_pretrained(bundle_dir)
    assert reloaded.labels == clf.labels
    print(f"bundle round-trip OK: {bundle_dir} "
          f"({len(reloaded.labels)} labels, preproc spec included)")


if __name__ == "__main__":
    main()
