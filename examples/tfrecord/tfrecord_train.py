"""TFRecord ingest → training (reference ``TFDataset.from_tfrecord_file``
flow). Writes a synthetic dataset as tf.train.Example records, reads it back
through the native C++ indexer, and trains a classifier.
"""
import argparse
import os
import tempfile

import numpy as np

from analytics_zoo_tpu.estimator import Estimator
from analytics_zoo_tpu.feature import FeatureSet
from analytics_zoo_tpu.feature.tfrecord import TFRecordWriter, _NativeReader
from analytics_zoo_tpu.keras import Sequential, objectives, optimizers
from analytics_zoo_tpu.keras.layers import Activation, Dense


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--records", type=int, default=10_000)
    ap.add_argument("--path", default=None)
    args = ap.parse_args()
    n = 256 if args.smoke else args.records

    path = args.path or os.path.join(tempfile.mkdtemp(), "train.tfrecord")
    rs = np.random.RandomState(0)
    with TFRecordWriter(path) as w:
        for i in range(n):
            x = rs.randn(8).astype(np.float32)
            w.write_example({"x": x, "y": np.asarray([int(x.sum() > 0)])})
    print(f"wrote {n} examples to {path} "
          f"(native reader: {_NativeReader.lib() is not None})")

    fs = FeatureSet.from_tfrecord(
        path, parser=lambda ex: (ex["x"], ex["y"][0].astype(np.float32)))
    est = Estimator(
        model=Sequential([Dense(16), Activation("relu"), Dense(2)]),
        loss_fn=objectives.get("sparse_categorical_crossentropy"),
        optimizer=optimizers.Adam(1e-2))
    result = est.train(fs, batch_size=64 if not args.smoke else 16, epochs=3)
    print(f"loss: {result['loss_history'][0]:.3f} -> "
          f"{result['loss_history'][-1]:.3f}")


if __name__ == "__main__":
    main()
