"""DataFrame-based training with NNFrames (reference
``pyzoo/zoo/examples/nnframes/basic_text_classification`` and the
NNEstimator/NNClassifier Spark-ML pattern).

Fits an ``NNClassifier`` straight off a pandas DataFrame — the TPU-native
stand-in for the reference's Spark DataFrame — then ``transform``s the same
frame to append a ``prediction`` column.
"""
import argparse

import numpy as np
import pandas as pd

from analytics_zoo_tpu.keras import Sequential
from analytics_zoo_tpu.keras.layers import Dense
from analytics_zoo_tpu.nnframes import NNClassifier


def make_df(n, rs):
    x = rs.rand(n, 4).astype(np.float32)
    label = (x.sum(axis=1) > 2.0).astype(np.float32)
    return pd.DataFrame({"f0": x[:, 0], "f1": x[:, 1], "f2": x[:, 2],
                         "f3": x[:, 3], "label": label})


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--epochs", type=int, default=30)
    args = ap.parse_args()

    rs = np.random.RandomState(0)
    df = make_df(200 if args.smoke else 5000, rs)
    epochs = 5 if args.smoke else args.epochs

    model = Sequential([Dense(16, activation="relu"),
                        Dense(2, activation="softmax")])
    clf = (NNClassifier(model, features_col=["f0", "f1", "f2", "f3"])
           .set_batch_size(32).set_max_epoch(epochs)
           .set_optim_method("adam").set_learning_rate(0.01))
    fitted = clf.fit(df)

    out = fitted.transform(df)
    acc = (out["prediction"].to_numpy() == df["label"].to_numpy()).mean()
    print(f"train accuracy: {acc:.3f} over {len(df)} rows")


if __name__ == "__main__":
    main()
