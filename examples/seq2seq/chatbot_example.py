"""Seq2seq encoder-decoder training + autoregressive inference (reference
``pyzoo/zoo/examples/chatbot`` — the scala chatbot example trains a
Seq2seq on question/answer token sequences).

Task: "echo shifted" — the target sequence is the input sequence shifted by
one learned offset in embedding space. Demonstrates teacher-forced ``fit``
on ``[encoder_in, decoder_in]`` and free-running generation via ``infer``.
"""
import argparse

import numpy as np

from analytics_zoo_tpu.models import Seq2seq


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--epochs", type=int, default=20)
    args = ap.parse_args()

    n, in_seq, out_seq, dim = (32, 6, 5, 4) if args.smoke else \
        (2048, 20, 18, 16)
    epochs = 2 if args.smoke else args.epochs
    rs = np.random.RandomState(0)
    enc = rs.rand(n, in_seq, dim).astype(np.float32)
    # target: previous decoder step plus a constant drift (learnable map)
    dec = rs.rand(n, out_seq, dim).astype(np.float32)
    target = np.roll(dec, -1, axis=1) * 0.5 + 0.25

    m = Seq2seq(rnn_type="lstm", num_layers=2, hidden_size=32,
                bridge="dense", generator_dim=dim)
    m.default_compile()
    m.fit([enc, dec], target.astype(np.float32), batch_size=16,
          nb_epoch=epochs)

    preds = m.predict([enc, dec], batch_size=16)
    mse = float(np.mean((np.asarray(preds) - target) ** 2))
    print(f"teacher-forced MSE: {mse:.4f}")

    gen = m.infer(enc[:2], start_sign=np.zeros(dim, np.float32),
                  max_seq_len=out_seq)
    print(f"free-running generation shape: {gen.shape}")


if __name__ == "__main__":
    main()
