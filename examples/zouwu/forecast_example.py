"""Zouwu time-series forecasting (reference
``pyzoo/zoo/zouwu/use-case/network_traffic`` notebooks).

Fits an LSTM forecaster on a synthetic seasonal series and forecasts the
next step; swap in ``MTNetForecaster``/``Seq2SeqForecaster`` for longer
horizons, or ``zouwu.autots`` to search configs automatically.
"""
import argparse

import numpy as np

from analytics_zoo_tpu.zouwu.model.forecast import LSTMForecaster


def rolling_windows(series, lookback):
    x = np.stack([series[i:i + lookback]
                  for i in range(len(series) - lookback)])
    y = series[lookback:]
    return x[..., None].astype(np.float32), y[:, None].astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--epochs", type=int, default=5)
    args = ap.parse_args()

    n, lookback = (400, 12) if args.smoke else (8000, 48)
    t = np.arange(n)
    series = (np.sin(2 * np.pi * t / 24) + 0.1 * np.sin(2 * np.pi * t / 7)
              + 0.05 * np.random.RandomState(0).randn(n))
    x, y = rolling_windows(series, lookback)
    split = int(0.9 * len(x))

    fc = LSTMForecaster(target_dim=1, feature_dim=1,
                        lstm_1_units=16, lstm_2_units=8)
    fc.fit(x[:split], y[:split], batch_size=64,
           epochs=2 if args.smoke else args.epochs)
    pred = fc.predict(x[split:])
    mse = float(np.mean((pred - y[split:]) ** 2))
    print(f"holdout MSE: {mse:.4f} over {len(pred)} steps")


if __name__ == "__main__":
    main()
