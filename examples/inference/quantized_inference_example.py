"""Pooled, quantized batch inference (reference
``pyzoo/zoo/examples/vnni/openvino`` int8 perf flow + the InferenceModel
``concurrentNum`` pool).

Loads a trained NeuralCF into an ``InferenceModel`` pool (N concurrent
borrowable slots, shape-bucketed compile cache), quantizes it to bf16 —
the TPU analogue of the reference's VNNI int8 path — and compares accuracy
plus wall time of full-precision vs quantized predictions.
"""
import argparse
import time

import numpy as np

from analytics_zoo_tpu.inference import InferenceModel
from analytics_zoo_tpu.models import NeuralCF


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--requests", type=int, default=64)
    args = ap.parse_args()

    users, items = 50, 40
    rs = np.random.RandomState(0)
    x = np.stack([rs.randint(1, users + 1, 512),
                  rs.randint(1, items + 1, 512)], 1).astype(np.float32)
    y = ((x[:, 0] + x[:, 1]) % 2).astype(np.float32)

    ncf = NeuralCF(user_count=users, item_count=items, num_classes=2,
                   user_embed=8, item_embed=8, hidden_layers=[16, 8],
                   mf_embed=4)
    ncf.default_compile()
    ncf.fit(x, y, batch_size=128, nb_epoch=2 if args.smoke else 20)

    pool = InferenceModel(concurrent_num=2).load_keras(ncf.model)
    baseline = np.asarray(pool.predict(x))

    pool.quantize("bf16")
    n_req = 8 if args.smoke else args.requests
    start = time.perf_counter()
    quantized = np.asarray(pool.predict(x))
    for _ in range(n_req - 1):
        pool.predict(x)
    elapsed = time.perf_counter() - start

    drift = np.abs(quantized - baseline).max()
    agree = (quantized.argmax(1) == baseline.argmax(1)).mean()
    print(f"bf16 vs f32: max prob drift {drift:.4f}, "
          f"argmax agreement {agree:.3f}, "
          f"{n_req * len(x) / elapsed:.0f} samples/s quantized")

    # calibrated int8 (the reference's calibrated OpenVINO/VNNI path):
    # activation observers run a calibration set through the model and the
    # Dense/Conv kernels then execute true int8 compute with per-tensor
    # activation scales
    pool8 = InferenceModel(concurrent_num=2).load_keras(ncf.model)
    pool8.quantize("int8", calibration_data=[x[i:i + 128]
                                             for i in range(0, len(x), 128)])
    int8_pred = np.asarray(pool8.predict(x))
    agree8 = (int8_pred.argmax(1) == baseline.argmax(1)).mean()
    print(f"calibrated int8 vs f32: argmax agreement {agree8:.3f} "
          f"(activation scales from a {len(x)}-sample calibration set)")


if __name__ == "__main__":
    main()
