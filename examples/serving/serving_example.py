"""Cluster serving end-to-end (reference
``pyzoo/zoo/examples/serving``): save a zoo model, start the serving engine
on a file-backed queue, push tensors with the client SDK, read predictions.
"""
import argparse
import os
import tempfile

import numpy as np

from analytics_zoo_tpu.models import NeuralCF
from analytics_zoo_tpu.serving import ClusterServing, ServingConfig
from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--requests", type=int, default=8)
    args = ap.parse_args()

    workdir = tempfile.mkdtemp(prefix="zoo_serving_example_")
    model_path = os.path.join(workdir, "model")
    queue_src = f"dir://{workdir}/queue"

    # 1. train briefly and save the model the server will load
    ncf = NeuralCF(50, 40, 2, user_embed=8, item_embed=8,
                   hidden_layers=[16, 8], mf_embed=4)
    ncf.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    rs = np.random.RandomState(0)
    x = np.stack([rs.randint(1, 50, 512), rs.randint(1, 40, 512)], 1) \
        .astype(np.float32)
    ncf.fit(x, (rs.rand(512) > 0.5).astype(np.float32), batch_size=128,
            nb_epoch=1)
    ncf.save_model(model_path)

    # 2. serving engine on a background thread (same engine `zoo-serving`
    # runs as a daemon from config.yaml)
    cfg = ServingConfig(model_path=model_path, model_type="zoo",
                        data_src=queue_src, batch_size=4, filter_top_n=2)
    serving = ClusterServing(cfg).start()

    # 3. client: enqueue tensors (with an answer-by budget), await results
    inq, outq = InputQueue(queue_src), OutputQueue(queue_src)
    for i in range(args.requests):
        inq.enqueue_tensor(f"req-{i}", x[i], deadline_ms=30_000)
    for i in range(args.requests):
        result = outq.query(f"req-{i}", timeout_s=30)
        print(f"req-{i}: {result}")

    # 4. deep health + graceful drain (what a deploy's SIGTERM runs):
    # finish in-flight work, flush results, leave nothing unanswered
    snap = serving.health_snapshot()
    print(f"health: state={snap['state']} served={snap['records_served']} "
          f"p99_ms={snap['latency_ms']['p99']} counters={snap['counters']}")
    serving.drain()


if __name__ == "__main__":
    main()
