"""SSD object detection: train, detect, evaluate mAP (reference
``pyzoo/zoo/examples/objectdetection/predict.py`` + the SSD training
pipeline in ``models/image/objectdetection``).

Builds a MobileNet-SSD300, fits it on a synthetic "bright square on dark
background" detection task, decodes box predictions with NMS, and scores
them with VOC-style MeanAveragePrecision. Swap ``--backbone vgg16`` for the
classic VGG16-SSD300.
"""
import argparse

import numpy as np

from analytics_zoo_tpu.models.image.evaluation import MeanAveragePrecision
from analytics_zoo_tpu.models.image.objectdetection import (
    ObjectDetector, Visualizer, multibox_loss)


def synthetic_boxes(n, size, rs):
    """Images with one bright square each; the box is the ground truth."""
    imgs = rs.rand(n, size, size, 3).astype(np.float32) * 0.2
    boxes, labels = [], []
    for i in range(n):
        w = rs.randint(size // 5, size // 2)
        x0 = rs.randint(0, size - w)
        y0 = rs.randint(0, size - w)
        imgs[i, y0:y0 + w, x0:x0 + w] = 1.0
        boxes.append(np.array([[x0 / size, y0 / size,
                                (x0 + w) / size, (y0 + w) / size]],
                              np.float32))
        labels.append(np.array([1]))
    return imgs, boxes, labels


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--backbone", default="mobilenet",
                    choices=["mobilenet", "vgg16"])
    ap.add_argument("--epochs", type=int, default=10)
    args = ap.parse_args()

    n, epochs = (16, 1) if args.smoke else (256, args.epochs)
    rs = np.random.RandomState(0)
    imgs, gt_boxes, gt_labels = synthetic_boxes(n, 300, rs)

    # SSD train-time augmentation: box-aware flip/expand/crop chain
    from analytics_zoo_tpu.feature.image import (
        ExpandWithBoxes, RandomHFlipWithBoxes, RandomSampleCrop,
        ResizeWithBoxes)
    aug = (RandomHFlipWithBoxes(seed=1) >> ExpandWithBoxes(seed=2)
           >> RandomSampleCrop(seed=3) >> ResizeWithBoxes(300, 300))
    augmented = [aug.apply((imgs[i], gt_boxes[i], gt_labels[i]))
                 for i in range(n)]
    imgs = np.stack([a[0] for a in augmented])
    gt_boxes = [a[1] for a in augmented]
    gt_labels = [a[2] for a in augmented]

    det = ObjectDetector(class_num=2, backbone=args.backbone, resolution=300)
    det.compile("adam", multibox_loss())
    loc_t, cls_t = det.encode_batch(gt_boxes, gt_labels)
    det.fit(imgs, (loc_t, cls_t), batch_size=8, nb_epoch=epochs)

    boxes, scores, classes = det.detect(imgs[:8], batch_size=8,
                                        max_detections=10)
    metric = MeanAveragePrecision(num_classes=2)
    for i in range(8):
        metric.add(boxes[i], scores[i], classes[i], gt_boxes[i], gt_labels[i])
    print(f"mAP over 8 images: {metric.compute()['mAP']:.3f}")

    vis = Visualizer(labels=["bg", "square"])
    drawn = vis.draw(imgs[0], boxes[0], scores[0], classes[0])
    print(f"visualized detections onto image of shape {drawn.shape}")


if __name__ == "__main__":
    main()
