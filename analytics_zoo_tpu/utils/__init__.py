from .tensorboard import SummaryWriter, read_scalars  # noqa: F401
