"""Chrome-trace span recorder over the ``time_it`` micro-profiler.

The reference stops at aggregate wall-time logs (``Utils.timeIt``,
``zoo/.../common/Utils.scala``; BigDL ``Metrics`` phase totals) — SURVEY §5
notes it has "no sampling profiler / chrome-trace". This goes one step
further: while a :func:`trace` session is active, every ``time_it`` span
(train_step, device feed waits, serving phases — anything already
instrumented) is recorded as a complete event and written out in the
Chrome ``chrome://tracing`` / Perfetto JSON array format, so a training or
serving run can be inspected on a timeline per thread.

Usage::

    from analytics_zoo_tpu.utils.trace import trace
    with trace("/tmp/train_trace.json"):
        estimator.train(fs, batch_size=..., epochs=1)
    # open chrome://tracing or https://ui.perfetto.dev and load the file

Spans from any thread are captured (producer threads show as separate
rows). Recording costs one list-append per span; when no session is
active the hook is a no-op.
"""
from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Iterator, List, Optional

from ..common import utils as _utils


class _TraceSession:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self.t0 = time.perf_counter()

    def add(self, name: str, start: float, elapsed: float) -> None:
        with self._lock:
            self._events.append({
                "name": name,
                "ph": "X",  # complete event
                "ts": (start - self.t0) * 1e6,  # microseconds
                "dur": elapsed * 1e6,
                "pid": 0,
                "tid": threading.get_ident(),
                "cat": "analytics_zoo_tpu",
            })

    def dump(self, path: str) -> int:
        with self._lock:
            events = list(self._events)
        names = {}
        for ev in events:  # readable row names per thread
            names.setdefault(ev["tid"], None)
        meta = [{"name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
                 "args": {"name": f"thread-{i}"}}
                for i, tid in enumerate(sorted(names))]
        with open(path, "w") as f:
            json.dump(meta + events, f)
        return len(events)


_active: Optional[_TraceSession] = None


def _record(name: str, start: float, elapsed: float) -> None:
    session = _active
    if session is not None:
        session.add(name, start, elapsed)


_utils.span_hooks.append(_record)  # no-op while no session is active


@contextlib.contextmanager
def trace(path: str) -> Iterator[_TraceSession]:
    """Record every ``time_it`` span until exit, then write Chrome-trace
    JSON to ``path``. Sessions don't nest (the inner one wins)."""
    global _active
    session = _TraceSession()
    prev, _active = _active, session
    try:
        yield session
    finally:
        _active = prev
        count = session.dump(path)
        _utils.logger.info("trace: wrote %d spans to %s", count, path)
