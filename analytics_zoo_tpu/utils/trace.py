"""Chrome-trace span recorder + request-lifecycle flow tracing.

The reference stops at aggregate wall-time logs (``Utils.timeIt``,
``zoo/.../common/Utils.scala``; BigDL ``Metrics`` phase totals) — SURVEY §5
notes it has "no sampling profiler / chrome-trace". While a :func:`trace`
session is active, every ``time_it`` span (train_step, device feed waits,
serving phases, checkpoint writes, forked transform-worker tasks) is
recorded and written out in the Chrome ``chrome://tracing`` / Perfetto JSON
array format, so a training or serving run can be inspected on a timeline
per process and thread.

Three capabilities beyond the original recorder:

- **Sessions nest.** An inner ``trace()`` no longer swallows the outer
  session's spans: every active session records every span, so a broad
  "whole run" trace and a narrow "just this phase" trace can coexist.
- **Forked workers show up, pid-correct.** Spans carry the real
  ``os.getpid()``; a span recorded in a forked child (transform workers)
  is spooled to a crash-tolerant per-pid JSONL part file that the parent
  merges at dump time — worker-pool activity lands on the same timeline as
  the threads that consume it. (``time.perf_counter`` is CLOCK_MONOTONIC
  on Linux, shared across processes, so child timestamps line up.)
- **Flow events.** :func:`flow_point` stamps Chrome flow-phase events
  (``s``/``t``/``f``) so one request's lifecycle — enqueue → claim →
  decode → dispatch → result — draws as a single arrowed chain across
  threads and processes in Perfetto. The serving stack calls it with the
  ``trace_id`` the client stamps at enqueue.

Thread rows are named by ROLE: the recorder uses each thread's live name
(``device-feed``, ``zoo-serving-claim``, ...); :func:`set_thread_label`
renames the current thread for code that runs on an anonymous thread.

Usage::

    from analytics_zoo_tpu.utils.trace import trace
    with trace("/tmp/train_trace.json"):
        estimator.train(fs, batch_size=..., epochs=1)
    # open https://ui.perfetto.dev and load the file

Recording costs one list-append per span; when no session is active the
hook is a no-op.
"""
from __future__ import annotations

import contextlib
import glob
import json
import os
import shutil
import tempfile
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from ..common import utils as _utils

#: flow-chain category — one constant so emitters and Perfetto bind on the
#: same (cat, name, id) triple
FLOW_CAT = "request"


def set_thread_label(label: str) -> None:
    """Name the CURRENT thread's trace row by role (producer / server /
    worker / ...). Threads created with an explicit ``name=`` are already
    labeled; this is for code running on threads it did not create."""
    threading.current_thread().name = label


class _TraceSession:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._names: Dict[Tuple[int, int], str] = {}  # (pid, tid) -> label
        self.t0 = time.perf_counter()
        self.pid = os.getpid()
        # spool for forked children: each foreign pid appends JSONL lines
        # (crash-tolerant — a SIGKILLed worker loses at most a partial
        # final line, which the merge skips)
        self.spool = tempfile.mkdtemp(prefix="zoo_trace_spool_")
        self._part = None        # child-side open part file
        self._part_pid = -1

    # -- recording ------------------------------------------------------------

    def _emit(self, ev: dict) -> None:
        pid = os.getpid()
        ev["pid"] = pid
        tid = ev["tid"]
        if pid == self.pid:
            with self._lock:
                if (pid, tid) not in self._names:
                    self._names[(pid, tid)] = threading.current_thread().name
                self._events.append(ev)
            return
        # forked child: spool to the per-pid part file. The file handle is
        # re-resolved after any further fork (pid changed under us).
        if self._part is None or self._part_pid != pid:
            try:
                self._part = open(
                    os.path.join(self.spool, f"{pid}.jsonl"), "a")
                self._part_pid = pid
                self._part.write(json.dumps(
                    {"name": "process_name", "ph": "M", "pid": pid,
                     "args": {"name": _process_label()}}) + "\n")
            except OSError:
                return  # spool dir gone (session ended in parent)
        try:
            key = (pid, tid)
            if key not in self._names:
                self._names[key] = threading.current_thread().name
                self._part.write(json.dumps(
                    {"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid,
                     "args": {"name": self._names[key]}}) + "\n")
            self._part.write(json.dumps(ev) + "\n")
            self._part.flush()
        except (OSError, ValueError):
            pass

    def add(self, name: str, start: float, elapsed: float) -> None:
        self._emit({
            "name": name,
            "ph": "X",  # complete event
            "ts": (start - self.t0) * 1e6,  # microseconds
            "dur": elapsed * 1e6,
            "tid": threading.get_ident(),
            "cat": "analytics_zoo_tpu",
        })

    def add_flow(self, flow_id: int, stage: str, phase: str,
                 t: float) -> None:
        """One flow-chain point: a 2µs anchor slice named ``stage`` plus
        the flow event Perfetto binds to it (same ts, same track)."""
        ts = (t - self.t0) * 1e6
        tid = threading.get_ident()
        self._emit({"name": stage, "ph": "X", "ts": ts, "dur": 2.0,
                    "tid": tid, "cat": "analytics_zoo_tpu",
                    "args": {"trace_id": flow_id}})
        ev = {"name": FLOW_CAT, "cat": FLOW_CAT, "ph": phase,
              "id": flow_id, "ts": ts + 1.0, "tid": tid}
        if phase == "f":
            ev["bp"] = "e"  # bind the terminus to the enclosing slice
        self._emit(ev)

    # -- output ---------------------------------------------------------------

    def _merge_parts(self) -> List[dict]:
        merged: List[dict] = []
        for part in sorted(glob.glob(os.path.join(self.spool, "*.jsonl"))):
            try:
                with open(part) as f:
                    for line in f:
                        line = line.strip()
                        if not line:
                            continue
                        try:
                            merged.append(json.loads(line))
                        except ValueError:
                            pass  # torn final line of a killed worker
            except OSError:
                pass
        return merged

    def dump(self, path: str) -> int:
        with self._lock:
            events = list(self._events)
            names = dict(self._names)
        events.extend(self._merge_parts())
        meta = [{"name": "process_name", "ph": "M", "pid": self.pid,
                 "args": {"name": _process_label()}}]
        meta += [{"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                  "args": {"name": label}}
                 for (pid, tid), label in sorted(names.items())
                 if pid == self.pid]
        with open(path, "w") as f:
            json.dump(meta + events, f)
        shutil.rmtree(self.spool, ignore_errors=True)
        return len([e for e in events if e.get("ph") != "M"])


def _process_label() -> str:
    import multiprocessing
    name = multiprocessing.current_process().name
    return "main" if name == "MainProcess" else name


#: stack of active sessions — EVERY active session records every span, so
#: nested trace() calls merge instead of the inner silently dropping the
#: outer's spans
_sessions: List[_TraceSession] = []


def tracing() -> bool:
    """Cheap hot-path check: is any trace session active?"""
    return bool(_sessions)


def _record(name: str, start: float, elapsed: float) -> None:
    for session in tuple(_sessions):
        session.add(name, start, elapsed)


_utils.span_hooks.append(_record)  # no-op while no session is active


def flow_point(flow_id: Optional[int], stage: str, phase: str) -> None:
    """Stamp one point of a request-lifecycle flow chain in every active
    session. ``phase``: ``"s"`` starts the chain (enqueue), ``"t"`` marks
    an intermediate step (claim / decode / dispatch), ``"f"`` ends it
    (result post). A ``None``/missing ``flow_id`` (request from a client
    that predates trace ids) is skipped silently."""
    if flow_id is None or not _sessions:
        return
    t = time.perf_counter()
    for session in tuple(_sessions):
        session.add_flow(int(flow_id), stage, phase, t)


def new_trace_id() -> int:
    """A fresh flow-chain id (31-bit, collision-unlikely): stamped onto
    serving requests at enqueue so every pipeline stage can tag its spans."""
    return int.from_bytes(os.urandom(4), "big") & 0x7FFFFFFF


@contextlib.contextmanager
def trace(path: str) -> Iterator[_TraceSession]:
    """Record every ``time_it`` span and :func:`flow_point` until exit,
    then write Chrome-trace JSON to ``path``. Sessions NEST by merging:
    spans recorded during an inner session land in both traces."""
    session = _TraceSession()
    _sessions.append(session)
    try:
        yield session
    finally:
        try:
            _sessions.remove(session)
        except ValueError:  # pragma: no cover - double-exit safety
            pass
        count = session.dump(path)
        _utils.logger.info("trace: wrote %d spans to %s", count, path)
