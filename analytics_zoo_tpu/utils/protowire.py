"""Schema-driven protobuf wire-format decoder (no protobuf dependency).

Shared by the ONNX importer (``net/onnx_wire.py``) and the TFRecord
``tf.train.Example`` parser (``feature/tfrecord.py``). Only decoding of
the handful of field kinds those schemas need — not a general protobuf
implementation.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# wire types
_VARINT, _I64, _LEN, _I32 = 0, 1, 2, 5


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long (corrupt protobuf)")


def _skip(buf: bytes, pos: int, wire_type: int) -> int:
    if wire_type == _VARINT:
        _, pos = _read_varint(buf, pos)
        return pos
    if wire_type == _I64:
        return pos + 8
    if wire_type == _LEN:
        n, pos = _read_varint(buf, pos)
        return pos + n
    if wire_type == _I32:
        return pos + 4
    raise ValueError(f"unsupported wire type {wire_type}")


def _zigzag(v: int) -> int:
    # onnx uses plain int64 (not sint64); negative ints arrive as 2^64-|v|
    return v - (1 << 64) if v >= (1 << 63) else v


class Field:
    """One schema entry: how to decode a field number."""

    def __init__(self, name: str, kind: str, repeated: bool = False,
                 schema: Optional[Dict[int, "Field"]] = None):
        self.name = name
        self.kind = kind  # int | float32 | string | bytes | message | packed_int | packed_float
        self.repeated = repeated
        self.schema = schema


def parse(buf: bytes, schema: Dict[int, Field]) -> Dict[str, Any]:
    """Decode one message with the given schema; unknown fields are skipped."""
    out: Dict[str, Any] = {}
    for fno, f in schema.items():
        if f.repeated:
            out[f.name] = []
    pos, end = 0, len(buf)
    while pos < end:
        key, pos = _read_varint(buf, pos)
        fno, wt = key >> 3, key & 7
        f = schema.get(fno)
        if f is None:
            pos = _skip(buf, pos, wt)
            continue
        val: Any
        if f.kind == "int":
            if wt == _VARINT:
                v, pos = _read_varint(buf, pos)
                val = _zigzag(v)
            elif wt == _LEN:  # packed repeated ints
                n, pos = _read_varint(buf, pos)
                sub_end = pos + n
                vals = []
                while pos < sub_end:
                    v, pos = _read_varint(buf, pos)
                    vals.append(_zigzag(v))
                out[f.name].extend(vals)
                continue
            else:
                pos = _skip(buf, pos, wt)
                continue
        elif f.kind == "float32":
            if wt == _I32:
                val = struct.unpack_from("<f", buf, pos)[0]
                pos += 4
            elif wt == _LEN:  # packed floats
                n, pos = _read_varint(buf, pos)
                out[f.name].extend(
                    np.frombuffer(buf, dtype="<f4", count=n // 4, offset=pos))
                pos += n
                continue
            else:
                pos = _skip(buf, pos, wt)
                continue
        elif f.kind == "float64":
            if wt == _I64:
                val = struct.unpack_from("<d", buf, pos)[0]
                pos += 8
            elif wt == _LEN:
                n, pos = _read_varint(buf, pos)
                out[f.name].extend(
                    np.frombuffer(buf, dtype="<f8", count=n // 8, offset=pos))
                pos += n
                continue
            else:
                pos = _skip(buf, pos, wt)
                continue
        elif f.kind in ("string", "bytes", "message"):
            if wt != _LEN:
                pos = _skip(buf, pos, wt)
                continue
            n, pos = _read_varint(buf, pos)
            raw = buf[pos:pos + n]
            pos += n
            if f.kind == "string":
                val = raw.decode("utf-8", errors="replace")
            elif f.kind == "bytes":
                val = raw
            else:
                val = parse(raw, f.schema)
        else:
            raise ValueError(f"unknown schema kind {f.kind}")
        if f.repeated:
            out[f.name].append(val)
        else:
            out[f.name] = val
    return out




# --------------------------------------------------------------------------
# Wire ENCODING primitives (the writer-side twin of the decoder above) —
# shared by the TensorBoard event writer and the TFRecord Example encoder.
# --------------------------------------------------------------------------


def encode_varint(v: int) -> bytes:
    out = bytearray()
    v &= 0xFFFFFFFFFFFFFFFF
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def encode_tag(field_no: int, wire_type: int) -> bytes:
    return encode_varint((field_no << 3) | wire_type)


def encode_len_field(field_no: int, payload: bytes) -> bytes:
    return encode_tag(field_no, 2) + encode_varint(len(payload)) + payload
