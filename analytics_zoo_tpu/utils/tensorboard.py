"""Self-contained TensorBoard event-file writer/reader.

The reference implements its own TF-event stack on the JVM —
``tensorboard/EventWriter.scala``, ``RecordWriter.scala`` (CRC-masked TFRecord
framing), ``FileWriter.scala``, ``Summary.scala``, and ``FileReader.scala`` for
read-back (~553 LoC total). This is the same capability without a TensorFlow
dependency: a minimal protobuf wire-format encoder for ``Event``/``Summary``
scalar messages, masked-CRC32C TFRecord framing, an async file writer, and a
reader used by ``get_train_summary`` equivalents and tests.

TFRecord frame layout:
  uint64 length | uint32 masked_crc32c(length) | bytes data | uint32 masked_crc32c(data)
"""
from __future__ import annotations

import os
import queue
import socket
import struct
import threading
from typing import Dict, Iterator, List, Optional, Tuple

from ..common import file_io
from ..common.utils import wall_clock

# ---------------------------------------------------------------------------
# CRC32C (Castagnoli), table-driven, pure python.
# ---------------------------------------------------------------------------

_CRC_TABLE: List[int] = []


def _make_table() -> None:
    poly = 0x82F63B78
    for n in range(256):
        crc = n
        for _ in range(8):
            crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
        _CRC_TABLE.append(crc)


_make_table()


def crc32c(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    crc = crc32c(data)
    return ((((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF)


# ---------------------------------------------------------------------------
# Minimal protobuf wire-format encoding for tensorboard Event messages.
#
# Event     { double wall_time = 1; int64 step = 2; string file_version = 3;
#             Summary summary = 5; }
# Summary   { repeated Value value = 1; }
# Value     { string tag = 1; float simple_value = 2; }
# ---------------------------------------------------------------------------


from .protowire import encode_tag as _tag, encode_varint as _varint  # noqa: E402


def _f64(field: int, value: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", value)


def _f32(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", value)


def _i64(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(value)


def _bytes_field(field: int, value: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(value)) + value


def encode_scalar_event(tag: str, value: float, step: int,
                        wall_time: Optional[float] = None) -> bytes:
    if wall_time is None:
        wall_time = wall_clock()
    value_msg = _bytes_field(1, tag.encode("utf-8")) + _f32(2, float(value))
    summary_msg = _bytes_field(1, value_msg)
    return _f64(1, wall_time) + _i64(2, step) + _bytes_field(5, summary_msg)


def encode_file_version_event(wall_time: Optional[float] = None) -> bytes:
    if wall_time is None:
        wall_time = wall_clock()
    return _f64(1, wall_time) + _bytes_field(3, b"brain.Event:2")


def frame_record(data: bytes) -> bytes:
    header = struct.pack("<Q", len(data))
    return (header + struct.pack("<I", masked_crc32c(header))
            + data + struct.pack("<I", masked_crc32c(data)))


# ---------------------------------------------------------------------------
# Decoding (FileReader.scala equivalent) — enough to read scalars back.
# ---------------------------------------------------------------------------


def _read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_fields(data: bytes) -> Iterator[Tuple[int, int, object]]:
    pos = 0
    while pos < len(data):
        key, pos = _read_varint(data, pos)
        field, wire = key >> 3, key & 0x7
        if wire == 0:
            val, pos = _read_varint(data, pos)
        elif wire == 1:
            val = struct.unpack("<d", data[pos:pos + 8])[0]
            pos += 8
        elif wire == 2:
            ln, pos = _read_varint(data, pos)
            val = data[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = struct.unpack("<f", data[pos:pos + 4])[0]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def decode_event(data: bytes) -> Dict[str, object]:
    event: Dict[str, object] = {"scalars": []}
    for field, wire, val in _iter_fields(data):
        if field == 1 and wire == 1:
            event["wall_time"] = val
        elif field == 2 and wire == 0:
            event["step"] = val
        elif field == 3 and wire == 2:
            event["file_version"] = val.decode("utf-8")
        elif field == 5 and wire == 2:
            for f2, w2, v2 in _iter_fields(val):
                if f2 == 1 and w2 == 2:
                    tag, simple = None, None
                    for f3, w3, v3 in _iter_fields(v2):
                        if f3 == 1 and w3 == 2:
                            tag = v3.decode("utf-8")
                        elif f3 == 2 and w3 == 5:
                            simple = v3
                    if tag is not None:
                        event["scalars"].append((tag, simple))
    return event


def read_events(path: str) -> List[Dict[str, object]]:
    events = []
    with file_io.fopen(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                break
            (length,) = struct.unpack("<Q", header)
            hcrc = f.read(4)
            data = f.read(length)
            dcrc = f.read(4)
            if len(hcrc) < 4 or len(data) < length or len(dcrc) < 4:
                break  # truncated tail of a file still being written = EOF
            if struct.unpack("<I", hcrc)[0] != masked_crc32c(header):
                raise ValueError("corrupt tfrecord header crc")
            if struct.unpack("<I", dcrc)[0] != masked_crc32c(data):
                raise ValueError("corrupt tfrecord data crc")
            events.append(decode_event(data))
    return events


# ---------------------------------------------------------------------------
# FileWriter — async, the EventWriter.scala queue-and-thread design.
# ---------------------------------------------------------------------------


class SummaryWriter:
    """Writes TensorBoard scalar summaries to ``logdir``.

    Equivalent of the reference's ``FileWriter``+``EventWriter`` pair: events
    are queued and flushed by a daemon thread, files are named
    ``events.out.tfevents.<ts>.<hostname>``.
    """

    def __init__(self, logdir: str, flush_secs: float = 2.0):
        file_io.makedirs(logdir, exist_ok=True)
        self.logdir = logdir
        # pid suffix: two writers on one host in the same second (crash-loop
        # restarts) must not collide — remote fopen refuses to append to an
        # existing object
        fname = (f"events.out.tfevents.{int(wall_clock())}."
                 f"{socket.gethostname()}.{os.getpid()}")
        self.path = file_io.join(logdir, fname)
        self._queue: "queue.Queue[Optional[bytes]]" = queue.Queue()
        self._file = file_io.fopen(self.path, "ab")
        self._file.write(frame_record(encode_file_version_event()))
        self._file.flush()
        self._flush_secs = flush_secs
        self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def add_scalar(self, tag: str, value: float, step: int) -> None:
        if self._closed:
            raise RuntimeError("writer closed")
        self._queue.put(frame_record(encode_scalar_event(tag, value, step)))

    def _run(self) -> None:
        while True:
            try:
                item = self._queue.get(timeout=self._flush_secs)
            except queue.Empty:
                self._file.flush()
                continue
            try:
                if item is None:
                    self._file.flush()
                    return
                self._file.write(item)
            finally:
                self._queue.task_done()

    def flush(self) -> None:
        self._queue.join()
        self._file.flush()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue.put(None)
        self._thread.join(timeout=5)
        self._file.close()

    def __enter__(self) -> "SummaryWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_scalars(logdir: str, tag: str) -> List[Tuple[int, float]]:
    """Read back all (step, value) pairs for ``tag`` — ``getTrainSummary``."""
    out: List[Tuple[int, float]] = []
    for fname in sorted(file_io.listdir(logdir)):
        if not fname.startswith("events.out.tfevents"):
            continue
        for event in read_events(file_io.join(logdir, fname)):
            for t, v in event.get("scalars", []):
                if t == tag:
                    out.append((int(event.get("step", 0)), v))
    return sorted(out)
