"""Forecasters (reference ``zouwu/model/forecast.py``: ``Forecaster`` base
over TFPark KerasModel, ``LSTMForecaster:49``, ``MTNetForecaster:108``) —
thin user-facing wrappers over the AutoML trainables with fixed configs."""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

from ...automl.model import MTNet, TimeSeq2Seq, VanillaLSTM


class Forecaster:
    """fit(x, y) / evaluate / predict over rolled windows."""

    def __init__(self):
        self.internal = None
        self.config: Dict[str, Any] = {}

    def fit(self, x, y, validation_data=None, batch_size: int = 32,
            epochs: int = 1, metric: str = "mse", **kwargs) -> float:
        config = dict(self.config, batch_size=batch_size, epochs=epochs,
                      **kwargs)
        return self.internal.fit_eval(
            (np.asarray(x, np.float32), np.asarray(y, np.float32)),
            validation_data=validation_data, metric=metric, **config)

    def evaluate(self, x, y, metrics: Sequence[str] = ("mse",)):
        return self.internal.evaluate(x, y, metrics=metrics)

    def predict(self, x) -> np.ndarray:
        return self.internal.predict(x)

    def save(self, path: str) -> None:
        self.internal.save(path)

    def restore(self, path: str, **config) -> None:
        self.internal.restore(path, **{**self.config, **config})


class LSTMForecaster(Forecaster):
    def __init__(self, target_dim: int = 1, feature_dim: int = 1,
                 lstm_1_units: int = 16, dropout_1: float = 0.2,
                 lstm_2_units: int = 8, dropout_2: float = 0.2,
                 lr: float = 0.001):
        super().__init__()
        self.internal = VanillaLSTM()
        self.config = {
            "lstm_1_units": lstm_1_units, "dropout_1": dropout_1,
            "lstm_2_units": lstm_2_units, "dropout_2": dropout_2,
            "lr": lr, "future_seq_len": target_dim,
            "input_dim": feature_dim,
        }


class MTNetForecaster(Forecaster):
    def __init__(self, target_dim: int = 1, feature_dim: int = 1,
                 long_series_num: int = 1, series_length: int = 1,
                 ar_window_size: int = 1, cnn_height: int = 1,
                 cnn_hid_size: int = 32, lr: float = 0.001):
        super().__init__()
        self.internal = MTNet()
        self.config = {
            "long_num": long_series_num, "time_step": series_length,
            "ar_window": ar_window_size, "cnn_height": cnn_height,
            "cnn_hid_size": cnn_hid_size, "lr": lr,
            "future_seq_len": target_dim, "input_dim": feature_dim,
        }

    def preprocess_input(self, x: np.ndarray) -> np.ndarray:
        """Check/trim the rolled window to (long_num+1)*time_step rows
        (reference ``MTNetForecaster.preprocess_input``)."""
        need = self.internal.required_past_seq_len(self.config)
        x = np.asarray(x, np.float32)
        if x.shape[1] < need:
            raise ValueError(f"need past_seq_len >= {need}, got {x.shape[1]}")
        return x[:, -need:]


class Seq2SeqForecaster(Forecaster):
    def __init__(self, future_seq_len: int = 1, feature_dim: int = 1,
                 latent_dim: int = 32, num_layers: int = 1,
                 lr: float = 0.001):
        super().__init__()
        self.internal = TimeSeq2Seq()
        self.config = {
            "latent_dim": latent_dim, "num_layers": num_layers, "lr": lr,
            "future_seq_len": future_seq_len, "input_dim": feature_dim,
        }
