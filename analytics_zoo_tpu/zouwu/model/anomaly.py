"""Anomaly detection on forecasts (reference ``zouwu/model/anomaly.py``:
``ThresholdEstimator.fit`` picks a distance threshold from a target anomaly
ratio; ``ThresholdDetector.detect`` flags forecast-vs-actual deviations or
absolute-range violations)."""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def _distance(y: np.ndarray, yhat: np.ndarray) -> np.ndarray:
    y = np.asarray(y, np.float64).reshape(len(y), -1)
    yhat = np.asarray(yhat, np.float64).reshape(len(yhat), -1)
    return np.sqrt(((y - yhat) ** 2).sum(axis=1))


class ThresholdEstimator:
    """Pick the distance threshold matching a target anomaly ratio."""

    def fit(self, y, yhat, mode: str = "default", ratio: float = 0.01
            ) -> float:
        dist = _distance(y, yhat)
        k = max(1, int(round(len(dist) * ratio)))
        self.th = float(np.sort(dist)[-k])
        return self.th


class ThresholdDetector:
    """Flag anomalies by forecast distance or absolute range."""

    def __init__(self):
        self.threshold = None

    def detect(self, y, yhat: Optional[np.ndarray] = None,
               threshold=None) -> np.ndarray:
        """Returns indices of anomalous records.

        - with ``yhat``: distance(y, yhat) > threshold (scalar).
        - without: range check; ``threshold`` = (min, max) bounds.
        """
        threshold = threshold if threshold is not None else self.threshold
        if threshold is None:
            raise ValueError("no threshold given or fitted")
        y = np.asarray(y)
        if yhat is not None:
            # >= so a ThresholdEstimator-fitted threshold (the k-th largest
            # distance) flags exactly its target ratio of records
            dist = _distance(y, yhat)
            return np.nonzero(dist >= float(threshold))[0]
        lo, hi = threshold
        flat = y.reshape(len(y), -1)
        bad = (flat < lo) | (flat > hi)
        return np.nonzero(bad.any(axis=1))[0]
