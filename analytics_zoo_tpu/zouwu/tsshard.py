"""Zouwu × XShard: rolling/lag feature windows computed IN the ETL
engine, lowered zero-copy into a sequence-model FeatureSet.

The reference's Zouwu rolls time-series windows in the driver (pandas
``shift`` over the whole frame) before handing numpy arrays to a
forecaster. Here the roll runs as an :meth:`XShard.map` wave — one
partition per series (the natural Zouwu sharding: windows never cross a
series boundary) — and :func:`rolled_featureset` lowers the lag columns
straight into FeatureSet staging memory with ``feature_shape=(lookback,
n_features)``, so ``Estimator.train`` reads sequence batches out of the
slabs the ETL workers wrote. No window tensor is ever materialized in
the driver.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple


def lag_feature_cols(value_cols: Sequence[str], lookback: int
                     ) -> List[str]:
    """Time-major lag column order — oldest step first, value columns
    within a step — so the flat ``[N, lookback * F]`` feature matrix
    reshapes to ``(N, lookback, F)`` as a free view."""
    return [f"{c}_lag{lookback - 1 - t}"
            for t in range(lookback) for c in value_cols]


def roll_windows(xs, value_cols: Sequence[str], lookback: int,
                 horizon: int = 1, target_col: Optional[str] = None):
    """Roll lag windows per partition (= per series): each output row
    holds ``lookback`` trailing steps of every value column plus the
    ``horizon``-step-ahead target. Returns ``(rolled_shard,
    feature_cols)``; rows without a full window or future target are
    dropped within their partition, so windows never leak across series
    boundaries."""
    value_cols = list(value_cols)
    target_col = target_col or value_cols[0]
    lookback = int(lookback)
    horizon = int(horizon)
    if lookback < 1 or horizon < 1:
        raise ValueError("lookback and horizon must be >= 1")

    def _roll(df):
        import pandas as pd
        out = {}
        for t in range(lookback):  # lag count, not rows — shifts vectorize
            shift = lookback - 1 - t
            for c in value_cols:
                out[f"{c}_lag{shift}"] = df[c].shift(shift)
        out["target"] = df[target_col].shift(-horizon)
        rolled = pd.DataFrame(out)
        lo, hi = lookback - 1, len(df) - horizon
        return rolled.iloc[lo:hi].reset_index(drop=True)

    return xs.map(_roll), lag_feature_cols(value_cols, lookback)


def rolled_featureset(xs, value_cols: Sequence[str], lookback: int,
                      horizon: int = 1,
                      target_col: Optional[str] = None, **kwargs
                      ) -> Tuple[object, object]:
    """Roll windows in the engine and lower them zero-copy: returns
    ``(featureset, rolled_shard)`` where the FeatureSet's features are
    ``(N, lookback, F)`` float32 views into worker-written slabs — ready
    for a recurrent model under ``Estimator.train``."""
    rolled, feature_cols = roll_windows(xs, value_cols, lookback,
                                        horizon, target_col)
    fs = rolled.to_featureset(
        feature_cols, "target",
        feature_shape=(lookback, len(list(value_cols))), **kwargs)
    return fs, rolled
