"""Zouwu — time-series productization of AutoML (reference
``pyzoo/zoo/zouwu/**``): Forecasters, anomaly detectors, AutoTS."""
from .model.forecast import (  # noqa: F401
    Forecaster, LSTMForecaster, MTNetForecaster, Seq2SeqForecaster)
from .model.anomaly import ThresholdDetector, ThresholdEstimator  # noqa: F401
from .autots.forecast import AutoTSTrainer, TSPipeline  # noqa: F401
from .tsshard import (  # noqa: F401
    lag_feature_cols, roll_windows, rolled_featureset)
