"""AutoTS (reference ``zouwu/autots/forecast.py:22,81``): AutoTSTrainer
drives the AutoML TimeSequencePredictor; TSPipeline wraps the fitted
pipeline."""
from __future__ import annotations

from typing import Optional, Sequence

from ...automl.config.recipe import Recipe, SmokeRecipe
from ...automl.pipeline.time_sequence import TimeSequencePipeline
from ...automl.regression.time_sequence_predictor import TimeSequencePredictor


class TSPipeline:
    def __init__(self, internal: TimeSequencePipeline):
        self.internal = internal

    def predict(self, input_df):
        return self.internal.predict(input_df)

    def evaluate(self, input_df, metrics: Sequence[str] = ("mse",)):
        return self.internal.evaluate(input_df, metrics)

    def fit(self, input_df, validation_df=None, epoch_num: int = 1):
        return self.internal.fit(input_df, validation_df, epoch_num)

    def save(self, path: str) -> None:
        self.internal.save(path)

    @staticmethod
    def load(path: str) -> "TSPipeline":
        return TSPipeline(TimeSequencePipeline.load(path))


class AutoTSTrainer:
    def __init__(self, dt_col: str = "datetime", target_col: str = "value",
                 horizon: int = 1,
                 extra_features_col: Optional[Sequence[str]] = None):
        self.internal = TimeSequencePredictor(
            dt_col=dt_col, target_col=target_col, future_seq_len=horizon,
            extra_features_col=extra_features_col)

    def fit(self, train_df, validation_df=None,
            recipe: Optional[Recipe] = None, metric: str = "mse"
            ) -> TSPipeline:
        pipeline = self.internal.fit(train_df, validation_df,
                                     recipe or SmokeRecipe(), metric)
        return TSPipeline(pipeline)
