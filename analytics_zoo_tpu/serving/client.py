"""Serving client SDK (reference ``pyzoo/zoo/serving/client.py``:
``InputQueue.enqueue_image:87``, ``OutputQueue.dequeue:135`` / ``query``).

SLO contract: every enqueue stamps ``enqueue_t`` (client wall clock — the
only clock two processes share) and, when the caller passes
``deadline_ms``, the request's latency budget. The server checks the
deadline at claim, after decode, and before dispatch, and answers expired
requests with ``{"error": "deadline exceeded"}`` instead of burning device
time on work nobody is waiting for.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from ..common.utils import wall_clock
from ..utils import trace as _trace
from .queues import FileQueue, QueueBackend, encode_image, make_queue


class _API:
    def __init__(self, src: str = "dir:///tmp/zoo_serving"):
        self.queue: QueueBackend = make_queue(src)


class InputQueue(_API):
    @staticmethod
    def _stamp(payload: Dict[str, Any],
               deadline_ms: Optional[int]) -> Dict[str, Any]:
        # wall clock on purpose: enqueue_t crosses a process boundary, and
        # monotonic clocks do not compare across processes
        payload["enqueue_t"] = wall_clock()
        # every request carries a flow-chain id from birth: when a trace
        # session is active (here or on the server), the Perfetto timeline
        # draws enqueue→claim→decode→dispatch→result as one arrowed chain
        flow_id = _trace.new_trace_id()
        payload["trace_id"] = flow_id
        _trace.flow_point(flow_id, "serving.enqueue", "s")
        if deadline_ms is not None:
            payload["deadline_ms"] = int(deadline_ms)
        return payload

    def enqueue_image(self, uri: str, img,
                      deadline_ms: Optional[int] = None) -> None:
        """``img``: ndarray (HWC), encoded bytes, or a path string.
        ``deadline_ms``: answer-by budget from now; past it the server
        posts a deadline error instead of a prediction."""
        if isinstance(img, str):
            import cv2
            data = cv2.imread(img)
            if data is None:
                raise ValueError(f"unreadable image path {img}")
            img = data
        self.queue.enqueue(uri, self._stamp({"image": encode_image(img)},
                                            deadline_ms))

    def enqueue_tensor(self, uri: str, tensor,
                       deadline_ms: Optional[int] = None) -> None:
        self.queue.enqueue(
            uri, self._stamp({"tensor": np.asarray(tensor).tolist()},
                             deadline_ms))

    def enqueue_prompt(self, uri: str, tokens,
                       deadline_ms: Optional[int] = None,
                       max_new_tokens: Optional[int] = None,
                       seed: Optional[int] = None,
                       prefix=None) -> None:
        """Generative request: ``tokens`` is the int prompt sequence.
        ``max_new_tokens`` caps this stream (else the server's config
        budget applies); ``seed`` makes sampled decoding reproducible
        per-request. With a ``deadline_ms``, the budget is enforced PER
        TOKEN — an expired stream is evicted mid-flight with a deadline
        error as its one terminal result.

        ``prefix`` resumes a stream that already decoded some tokens
        elsewhere: the server re-prefills ``prompt + prefix`` and
        continues token-identically (the fleet router uses this for
        continuation-on-failover — docs/fleet.md; with ``prefix`` a
        sampled stream must also pass its original ``seed``).

        Routed fleets change NOTHING here: point the client at the fleet
        FRONT spool and the router places the request on an instance
        whose results land back in the same front ``results/`` this
        client polls (``serving.fleet.instance_queue``)."""
        payload: Dict[str, Any] = {
            "prompt": [int(t) for t in np.asarray(tokens).reshape(-1)]}
        if max_new_tokens is not None:
            payload["max_new_tokens"] = int(max_new_tokens)
        if seed is not None:
            payload["seed"] = int(seed)
        if prefix is not None:
            payload["prefix"] = [int(t) for t in
                                 np.asarray(prefix).reshape(-1)]
        self.queue.enqueue(uri, self._stamp(payload, deadline_ms))


class OutputQueue(_API):
    def query(self, uri: str, timeout_s: float = 0.0
              ) -> Optional[Dict[str, Any]]:
        """Result for one uri; optionally poll up to ``timeout_s``.
        The wait is on the monotonic clock (a wall-clock step must not
        stretch or collapse the timeout) with exponential poll backoff —
        a long-poll client must not busy-hammer the result store."""
        deadline = time.monotonic() + timeout_s
        sleep_s = 0.005
        while True:
            res = self.queue.get_result(uri)
            remaining = deadline - time.monotonic()
            if res is not None or remaining <= 0:
                return res
            time.sleep(min(sleep_s, remaining))
            sleep_s = min(sleep_s * 2, 0.25)

    def dequeue(self) -> Dict[str, Dict[str, Any]]:
        """All available results keyed by uri (reference HGETALL sweep)."""
        if isinstance(self.queue, FileQueue):
            return self.queue.all_results()
        raise NotImplementedError(
            "dequeue-all needs the file queue; use query(uri) with redis")

    def stream(self, uri: str, timeout_s: float = 30.0):
        """Yield a generative stream's tokens as the server posts them.

        The scheduler overwrites ``uri``'s result with growing partials
        (``{"stream": [...], "done": false}``) and finally the terminal
        (``{"value": [...], "done": true}`` or ``{"error": ...}``); this
        generator polls that single idempotent record and yields each NEW
        token exactly once, in order. Raises ``RuntimeError`` on an error
        terminal (shed / deadline / step failure) and ``TimeoutError``
        after ``timeout_s`` with no progress — progress resets the clock,
        so a long stream only has to keep moving, not finish fast."""
        seen = 0
        deadline = time.monotonic() + timeout_s
        sleep_s = 0.005
        while True:
            res = self.queue.get_result(uri)
            if res is not None:
                if "error" in res:
                    raise RuntimeError(f"stream {uri!r}: {res['error']}")
                done = bool(res.get("done", True))
                tokens = res.get("value" if done else "stream") or []
                if len(tokens) > seen:
                    for t in tokens[seen:]:
                        yield t
                    seen = len(tokens)
                    deadline = time.monotonic() + timeout_s
                    sleep_s = 0.005
                if done:
                    return
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"stream {uri!r}: no progress in {timeout_s}s "
                    f"({seen} tokens received)")
            time.sleep(min(sleep_s, remaining))
            sleep_s = min(sleep_s * 2, 0.25)
