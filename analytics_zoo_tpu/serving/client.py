"""Serving client SDK (reference ``pyzoo/zoo/serving/client.py``:
``InputQueue.enqueue_image:87``, ``OutputQueue.dequeue:135`` / ``query``).

SLO contract: every enqueue stamps ``enqueue_t`` (client wall clock — the
only clock two processes share) and, when the caller passes
``deadline_ms``, the request's latency budget. The server checks the
deadline at claim, after decode, and before dispatch, and answers expired
requests with ``{"error": "deadline exceeded"}`` instead of burning device
time on work nobody is waiting for.

Overload survival (docs/serving.md#overload-survival): requests carry a
``criticality`` class (``critical`` / ``default`` / ``sheddable``) that the
queue backends turn into priority lanes, terminal error results carry a
``retriable`` flag (shed → yes; deadline/validation/shutdown → no), and
:class:`ResilientClient` layers a token-bucket retry *budget*, full-jitter
exponential backoff, and hedged queries on top — a client retry loop that
cannot become a retry storm by construction.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..common.utils import wall_clock
from ..utils import trace as _trace
from .queues import FileQueue, QueueBackend, encode_image, make_queue


def _io_retry_policy():
    """The same bounded-retry knobs remote ``file_io`` uses — a transient
    result-store error during a poll is the same class of failure as a
    flaky object store during a read."""
    try:
        from ..common.config import global_config
        cfg = global_config()
        return (int(cfg.get("failure.io_retries") or 0),
                float(cfg.get("failure.io_backoff_s") or 0.0))
    except Exception:
        return 0, 0.0


def _transient(e: BaseException) -> bool:
    """Errors worth retrying a result-store read through: generic
    ``OSError``/timeouts and redis connection failures; shaped-path errors
    (missing dir, permission) stay fatal, mirroring ``file_io``."""
    if isinstance(e, (FileNotFoundError, FileExistsError, IsADirectoryError,
                      NotADirectoryError, PermissionError)):
        return False
    if isinstance(e, (OSError, TimeoutError)):
        return True
    return type(e).__module__.split(".")[0] == "redis"


class _API:
    def __init__(self, src: str = "dir:///tmp/zoo_serving"):
        self.queue: QueueBackend = make_queue(src)

    def _get_result_guarded(self, uri: str, state: Dict[str, int]
                            ) -> Optional[Dict[str, Any]]:
        """``get_result`` with the ``file_io`` bounded-retry stance: a
        transient backend error (flaky NFS, a redis connection reset) is
        absorbed up to ``failure.io_retries`` consecutive times with
        exponential backoff instead of killing the poll loop; anything
        else — or an exhausted budget — raises. ``state`` carries the
        consecutive-failure count across poll iterations."""
        retries, backoff = _io_retry_policy()
        try:
            res = self.queue.get_result(uri)
        except BaseException as e:
            failures = state.get("failures", 0)
            if not _transient(e) or failures >= retries:
                raise
            state["failures"] = failures + 1
            time.sleep(backoff * (2 ** failures))
            return None
        state["failures"] = 0
        return res


class InputQueue(_API):
    @staticmethod
    def _stamp(payload: Dict[str, Any],
               deadline_ms: Optional[int],
               criticality: Optional[str] = None) -> Dict[str, Any]:
        # wall clock on purpose: enqueue_t crosses a process boundary, and
        # monotonic clocks do not compare across processes
        payload["enqueue_t"] = wall_clock()
        # every request carries a flow-chain id from birth: when a trace
        # session is active (here or on the server), the Perfetto timeline
        # draws enqueue→claim→decode→dispatch→result as one arrowed chain
        flow_id = _trace.new_trace_id()
        payload["trace_id"] = flow_id
        _trace.flow_point(flow_id, "serving.enqueue", "s")
        if deadline_ms is not None:
            payload["deadline_ms"] = int(deadline_ms)
        if criticality is not None:
            payload["criticality"] = str(criticality)
        return payload

    def enqueue_image(self, uri: str, img,
                      deadline_ms: Optional[int] = None,
                      criticality: Optional[str] = None) -> None:
        """``img``: ndarray (HWC), encoded bytes, or a path string.
        ``deadline_ms``: answer-by budget from now; past it the server
        posts a deadline error instead of a prediction. ``criticality``
        (``critical``/``default``/``sheddable``) picks the admission
        lane — under overload, sheddable lanes are dropped first."""
        if isinstance(img, str):
            import cv2
            data = cv2.imread(img)
            if data is None:
                raise ValueError(f"unreadable image path {img}")
            img = data
        self.queue.enqueue(uri, self._stamp({"image": encode_image(img)},
                                            deadline_ms, criticality))

    def enqueue_tensor(self, uri: str, tensor,
                       deadline_ms: Optional[int] = None,
                       criticality: Optional[str] = None) -> None:
        self.queue.enqueue(
            uri, self._stamp({"tensor": np.asarray(tensor).tolist()},
                             deadline_ms, criticality))

    def enqueue_prompt(self, uri: str, tokens,
                       deadline_ms: Optional[int] = None,
                       max_new_tokens: Optional[int] = None,
                       seed: Optional[int] = None,
                       prefix=None,
                       criticality: Optional[str] = None) -> None:
        """Generative request: ``tokens`` is the int prompt sequence.
        ``max_new_tokens`` caps this stream (else the server's config
        budget applies); ``seed`` makes sampled decoding reproducible
        per-request. With a ``deadline_ms``, the budget is enforced PER
        TOKEN — an expired stream is evicted mid-flight with a deadline
        error as its one terminal result.

        ``prefix`` resumes a stream that already decoded some tokens
        elsewhere: the server re-prefills ``prompt + prefix`` and
        continues token-identically (the fleet router uses this for
        continuation-on-failover — docs/fleet.md; with ``prefix`` a
        sampled stream must also pass its original ``seed``).

        Routed fleets change NOTHING here: point the client at the fleet
        FRONT spool and the router places the request on an instance
        whose results land back in the same front ``results/`` this
        client polls (``serving.fleet.instance_queue``)."""
        payload: Dict[str, Any] = {
            "prompt": [int(t) for t in np.asarray(tokens).reshape(-1)]}
        if max_new_tokens is not None:
            payload["max_new_tokens"] = int(max_new_tokens)
        if seed is not None:
            payload["seed"] = int(seed)
        if prefix is not None:
            payload["prefix"] = [int(t) for t in
                                 np.asarray(prefix).reshape(-1)]
        self.queue.enqueue(uri, self._stamp(payload, deadline_ms,
                                            criticality))


class OutputQueue(_API):
    def query(self, uri: str, timeout_s: float = 0.0
              ) -> Optional[Dict[str, Any]]:
        """Result for one uri; optionally poll up to ``timeout_s``.
        The wait is on the monotonic clock (a wall-clock step must not
        stretch or collapse the timeout) with exponential poll backoff —
        a long-poll client must not busy-hammer the result store.
        Transient backend errors (a redis connection reset, a flaky
        shared filesystem) are absorbed with the bounded ``file_io``
        retry policy instead of being treated as fatal."""
        deadline = time.monotonic() + timeout_s
        sleep_s = 0.005
        state: Dict[str, int] = {}
        while True:
            res = self._get_result_guarded(uri, state)
            remaining = deadline - time.monotonic()
            if res is not None or remaining <= 0:
                return res
            time.sleep(min(sleep_s, remaining))
            sleep_s = min(sleep_s * 2, 0.25)

    def dequeue(self) -> Dict[str, Dict[str, Any]]:
        """All available results keyed by uri (reference HGETALL sweep)."""
        if isinstance(self.queue, FileQueue):
            return self.queue.all_results()
        raise NotImplementedError(
            "dequeue-all needs the file queue; use query(uri) with redis")

    def stream(self, uri: str, timeout_s: float = 30.0):
        """Yield a generative stream's tokens as the server posts them.

        The scheduler overwrites ``uri``'s result with growing partials
        (``{"stream": [...], "done": false}``) and finally the terminal
        (``{"value": [...], "done": true}`` or ``{"error": ...}``); this
        generator polls that single idempotent record and yields each NEW
        token exactly once, in order. Raises ``RuntimeError`` on an error
        terminal (shed / deadline / step failure) and ``TimeoutError``
        after ``timeout_s`` with no progress — progress resets the clock,
        so a long stream only has to keep moving, not finish fast."""
        seen = 0
        deadline = time.monotonic() + timeout_s
        sleep_s = 0.005
        state: Dict[str, int] = {}
        while True:
            res = self._get_result_guarded(uri, state)
            if res is not None:
                if "error" in res:
                    raise RuntimeError(f"stream {uri!r}: {res['error']}")
                done = bool(res.get("done", True))
                tokens = res.get("value" if done else "stream") or []
                if len(tokens) > seen:
                    for t in tokens[seen:]:
                        yield t
                    seen = len(tokens)
                    deadline = time.monotonic() + timeout_s
                    sleep_s = 0.005
                if done:
                    return
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"stream {uri!r}: no progress in {timeout_s}s "
                    f"({seen} tokens received)")
            time.sleep(min(sleep_s, remaining))
            sleep_s = min(sleep_s * 2, 0.25)


class RetryBudget:
    """Token-bucket retry budget: every first-attempt request deposits
    ``ratio`` tokens (capped at ``burst``); every retry or hedge withdraws
    one whole token. Retry amplification therefore cannot exceed
    ``ratio`` of offered load by construction — against a fleet that sheds
    100% of traffic, a budgeted client converges to ``1 + ratio`` attempts
    per request instead of a retry storm."""

    def __init__(self, ratio: float = 0.1, burst: float = 10.0):
        self.ratio = float(ratio)
        self.burst = max(1.0, float(burst))
        self._tokens = min(1.0, self.burst)  # one early retry allowed
        self._lock = threading.Lock()

    def deposit(self) -> None:
        with self._lock:
            self._tokens = min(self.burst, self._tokens + self.ratio)

    def try_spend(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    @property
    def tokens(self) -> float:
        return self._tokens


class ResilientClient:
    """Retry-budgeted, hedging client wrapper over one queue ``src``.

    ``call()`` enqueues a request, polls its terminal, and — only when the
    terminal error carries ``retriable: true`` (shed / fleet-shed; never
    deadline, validation or shutdown errors), the attempt cap allows it,
    AND the shared :class:`RetryBudget` grants a token — re-enqueues under
    a fresh attempt uri after a full-jitter exponential backoff
    (``uniform(0, base * 2^attempt)``: the jitter decorrelates a thundering
    herd of shed clients). ``query_any()`` hedges tail latency instead: a
    second copy races the first after a p99-derived delay, the first
    terminal wins and the loser is reaped via ``discard_result`` — never
    surfaced. Every attempt uses its own uri, so the server-side
    exactly-one-terminal invariant is untouched.

    Amplification accounting for SLO audits: ``attempts_sent /
    requests_sent`` is the measured retry amplification, bounded by
    ``1 + client.retry_budget_ratio`` by construction."""

    def __init__(self, src: str,
                 budget_ratio: Optional[float] = None,
                 attempts: Optional[int] = None,
                 backoff_s: Optional[float] = None,
                 hedge_delay_ms: Optional[float] = None,
                 rng: Optional[random.Random] = None):
        from ..common.config import global_config
        cfg = global_config()
        if budget_ratio is None:
            budget_ratio = float(cfg.get("client.retry_budget_ratio"))
        self.inputs = InputQueue(src)
        self.outputs = OutputQueue(src)
        self.budget = RetryBudget(budget_ratio)
        self.attempts = int(attempts if attempts is not None
                            else cfg.get("client.retry_attempts"))
        self.backoff_s = float(backoff_s if backoff_s is not None
                               else cfg.get("client.retry_backoff_s"))
        self.hedge_delay_s = float(
            hedge_delay_ms if hedge_delay_ms is not None
            else cfg.get("client.hedge_delay_ms")) / 1000.0
        self._rng = rng if rng is not None else random.Random()
        self._lat: List[float] = []  # recent terminal latencies (monotonic)
        self._pending_reaps: List[str] = []
        self._lock = threading.Lock()
        self.requests_sent = 0   # logical requests (first attempts)
        self.attempts_sent = 0   # every enqueue: first + retries + hedges

    # -- bookkeeping ----------------------------------------------------------

    def _note_latency(self, seconds: float) -> None:
        with self._lock:
            self._lat.append(seconds)
            if len(self._lat) > 512:
                del self._lat[:256]

    def _p99_delay(self) -> float:
        """Hedge trigger: observed p99 latency once enough history exists,
        else the configured ``client.hedge_delay_ms`` floor."""
        with self._lock:
            lat = sorted(self._lat)
        if len(lat) >= 20:
            return lat[min(len(lat) - 1, int(0.99 * len(lat)))]
        return self.hedge_delay_s

    def _jitter(self, attempt: int) -> float:
        # full jitter: anywhere in [0, base * 2^attempt) — retries from a
        # synchronized shed wave land spread out, not in lockstep
        return self._rng.uniform(0.0, self.backoff_s * (2 ** attempt))

    def reap_pending(self) -> int:
        """Discard any landed results of past hedge losers (lazy reaping:
        a loser still in flight when its race ended is reaped on a later
        call). Returns how many were removed this pass."""
        with self._lock:
            pending, self._pending_reaps = self._pending_reaps, []
        reaped = 0
        for uri in pending:
            if self.outputs.queue.discard_result(uri):
                reaped += 1
            else:
                with self._lock:
                    self._pending_reaps.append(uri)
        return reaped

    # -- request paths --------------------------------------------------------

    def call(self, uri: str, enqueue: Callable[[str], None],
             timeout_s: float = 30.0) -> Optional[Dict[str, Any]]:
        """One logical request with budgeted retries. ``enqueue`` is called
        with the attempt uri (``uri``, then ``uri~r1``, ...) and must
        enqueue exactly one copy of the request under that uri."""
        self.reap_pending()
        deadline = time.monotonic() + timeout_s
        self.requests_sent += 1
        self.budget.deposit()
        attempt = 0
        attempt_uri = uri
        while True:
            t0 = time.monotonic()
            self.attempts_sent += 1
            enqueue(attempt_uri)
            res = self.outputs.query(
                attempt_uri, timeout_s=max(0.0, deadline - time.monotonic()))
            if res is None:
                return None  # timed out: nothing terminal to retry on
            if "error" not in res:
                self._note_latency(time.monotonic() - t0)
                return res
            remaining = deadline - time.monotonic()
            if (not res.get("retriable") or attempt >= self.attempts
                    or remaining <= 0 or not self.budget.try_spend()):
                return res
            time.sleep(min(self._jitter(attempt), max(0.0, remaining)))
            attempt += 1
            attempt_uri = f"{uri}~r{attempt}"

    def query_any(self, uri: str, enqueue: Callable[[str], None],
                  timeout_s: float = 30.0,
                  hedge_delay_s: Optional[float] = None
                  ) -> Optional[Dict[str, Any]]:
        """Hedged request: enqueue ``uri``, wait a p99-derived delay, and
        if no terminal landed, race a second copy (``uri~h``) — subject to
        the same retry budget. The first terminal to land wins; the
        loser's result is reaped, never surfaced."""
        self.reap_pending()
        deadline = time.monotonic() + timeout_s
        self.requests_sent += 1
        self.budget.deposit()
        self.attempts_sent += 1
        t0 = time.monotonic()
        enqueue(uri)
        delay = hedge_delay_s if hedge_delay_s is not None \
            else self._p99_delay()
        res = self.outputs.query(
            uri, timeout_s=min(delay, max(0.0, deadline - time.monotonic())))
        if res is not None:
            self._note_latency(time.monotonic() - t0)
            return res
        hedge_uri = f"{uri}~h"
        hedged = self.budget.try_spend()
        if hedged:
            self.attempts_sent += 1
            enqueue(hedge_uri)
        sleep_s = 0.005
        state: Dict[str, int] = {}
        hstate: Dict[str, int] = {}
        while True:
            res = self.outputs._get_result_guarded(uri, state)
            if res is not None:
                winner, loser = uri, hedge_uri if hedged else None
                break
            if hedged:
                res = self.outputs._get_result_guarded(hedge_uri, hstate)
                if res is not None:
                    winner, loser = hedge_uri, uri
                    break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            time.sleep(min(sleep_s, remaining))
            sleep_s = min(sleep_s * 2, 0.25)
        if loser is not None:
            with self._lock:
                self._pending_reaps.append(loser)
            self.reap_pending()
        self._note_latency(time.monotonic() - t0)
        return res
