"""Serving client SDK (reference ``pyzoo/zoo/serving/client.py``:
``InputQueue.enqueue_image:87``, ``OutputQueue.dequeue:135`` / ``query``)."""
from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from .queues import FileQueue, QueueBackend, encode_image, make_queue


class _API:
    def __init__(self, src: str = "dir:///tmp/zoo_serving"):
        self.queue: QueueBackend = make_queue(src)


class InputQueue(_API):
    def enqueue_image(self, uri: str, img) -> None:
        """``img``: ndarray (HWC), encoded bytes, or a path string."""
        if isinstance(img, str):
            import cv2
            data = cv2.imread(img)
            if data is None:
                raise ValueError(f"unreadable image path {img}")
            img = data
        self.queue.enqueue(uri, {"image": encode_image(img)})

    def enqueue_tensor(self, uri: str, tensor) -> None:
        self.queue.enqueue(uri, {"tensor": np.asarray(tensor).tolist()})


class OutputQueue(_API):
    def query(self, uri: str, timeout_s: float = 0.0
              ) -> Optional[Dict[str, Any]]:
        """Result for one uri; optionally poll up to ``timeout_s``."""
        deadline = time.time() + timeout_s
        while True:
            res = self.queue.get_result(uri)
            if res is not None or time.time() >= deadline:
                return res
            time.sleep(0.01)

    def dequeue(self) -> Dict[str, Dict[str, Any]]:
        """All available results keyed by uri (reference HGETALL sweep)."""
        if isinstance(self.queue, FileQueue):
            return self.queue.all_results()
        raise NotImplementedError(
            "dequeue-all needs the file queue; use query(uri) with redis")
