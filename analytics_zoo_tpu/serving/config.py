"""Serving config (reference ``scripts/cluster-serving/config.yaml`` schema
parsed by ``ClusterServingHelper.scala``: model path, data src, image shape,
topN filter, batch size, memory cap)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence


@dataclass
class ServingConfig:
    model_path: str = ""
    model_type: str = "zoo"  # zoo | savedmodel | torch | onnx | caffe
    model_weight_path: str = ""  # caffe: path to the .caffemodel
    data_src: str = "dir:///tmp/zoo_serving"
    image_shape: Sequence[int] = (224, 224, 3)
    input_dtype: str = "float32"  # "uint8" halves x4 the host->device bytes
    #   (pair with a model that normalizes on device, e.g.
    #   resnet(preprocess="imagenet_uint8"))
    filter_top_n: Optional[int] = None
    batch_size: int = 4
    batch_wait_ms: int = 20  # micro-batch window
    max_pending: int = 10000  # erroring load-shed depth threshold
    concurrent_num: int = 1
    decode_threads: int = 4  # host threads decoding while the device runs
    quantize: Optional[str] = None  # bf16 | int8
    log_dir: Optional[str] = None  # TensorBoard serving summaries
    # -- SLO layer ------------------------------------------------------------
    default_deadline_ms: Optional[int] = None  # server-side deadline for
    #   records that carry none (clients stamp per-request deadline_ms)
    shed_wait_ms: Optional[int] = None  # estimated-wait admission: shed the
    #   queue down to what the smoothed service rate can answer within this
    #   wait (None = depth-only shedding via max_pending)
    claim_retries: int = 20  # consecutive transient claim failures the loop
    #   absorbs before surfacing the backend as dead
    health_path: Optional[str] = None  # periodic + terminal health.json
    health_interval_s: float = 1.0  # min seconds between health writes
    # -- generative serving (continuous batching) -----------------------------
    slots: int = 8  # resident decode slots (device batch of the step loop)
    max_new_tokens: int = 64  # per-stream budget when the request omits one
    eos_id: Optional[int] = None  # stop token; None = run out the budget
    stream_interval: int = 1  # post a partial result every N tokens
    temperature: Optional[float] = None  # sampling knobs: any set => the
    top_k: Optional[int] = None          # scheduler samples through the
    top_p: Optional[float] = None        # shared make_logit_filter; all
    #   None => greedy argmax decoding
    # -- paged KV engine -------------------------------------------------------
    kv_pages: Optional[int] = None  # pool size in pages; None = contiguous
    #   per-slot rectangles (the PR 8 engine). Page 0 is the null page, so
    #   kv_pages - 1 pages are allocatable.
    kv_page_len: int = 16  # tokens per page; must divide the LM's max_len
    #   and be a power of two <= 16 (so it divides every prefill bucket)
    kv_int8: bool = False  # int8 KV pool (delayed-scaling quantization)
    kv_shard: int = 1  # devices the pool's PAGE axis shards over (a model
    #   whose KV exceeds one device's HBM spreads pages across the mesh;
    #   decode gathers each stream's pages to the compute device, so
    #   sharded output is token-identical to kv_shard=1). Must divide
    #   kv_pages and be <= the local device count.
    spec_k: int = 0  # speculative decoding: draft tokens per verify round;
    #   0 = disabled. Requires kv_pages and a draft_lm, greedy-only.

    @staticmethod
    def from_yaml(path: str) -> "ServingConfig":
        import yaml
        with open(path) as f:
            raw = yaml.safe_load(f) or {}
        model = raw.get("model", {}) or {}
        data = raw.get("data", {}) or {}
        params = raw.get("params", {}) or {}
        cfg = ServingConfig()
        cfg.model_path = model.get("path", cfg.model_path)
        cfg.model_type = model.get("type", cfg.model_type)
        cfg.model_weight_path = model.get("weight_path",
                                          cfg.model_weight_path)
        cfg.data_src = data.get("src") or cfg.data_src
        cfg.input_dtype = data.get("input_dtype", cfg.input_dtype)
        if cfg.input_dtype not in ("float32", "uint8"):
            raise ValueError(f"input_dtype must be float32 or uint8, got "
                             f"{cfg.input_dtype!r}")
        if data.get("image_shape"):
            shape = data["image_shape"]
            if isinstance(shape, str):
                shape = [int(s) for s in shape.split(",")]
            cfg.image_shape = tuple(shape)
        if data.get("filter"):  # "topN(5)" like the reference
            s = str(data["filter"])
            if s.lower().startswith("topn"):
                cfg.filter_top_n = int(s[s.index("(") + 1:s.index(")")])
        cfg.batch_size = int(params.get("batch_size", cfg.batch_size))
        cfg.batch_wait_ms = int(params.get("batch_wait_ms", cfg.batch_wait_ms))
        cfg.max_pending = int(params.get("max_pending", cfg.max_pending))
        cfg.concurrent_num = int(params.get("concurrent_num",
                                            cfg.concurrent_num))
        cfg.decode_threads = int(params.get("decode_threads",
                                            cfg.decode_threads))
        cfg.quantize = params.get("quantize", cfg.quantize)
        if params.get("deadline_ms") is not None:
            cfg.default_deadline_ms = int(params["deadline_ms"])
        if params.get("shed_wait_ms") is not None:
            cfg.shed_wait_ms = int(params["shed_wait_ms"])
        cfg.claim_retries = int(params.get("claim_retries",
                                           cfg.claim_retries))
        cfg.slots = int(params.get("slots", cfg.slots))
        cfg.max_new_tokens = int(params.get("max_new_tokens",
                                            cfg.max_new_tokens))
        if params.get("eos_id") is not None:
            cfg.eos_id = int(params["eos_id"])
        cfg.stream_interval = int(params.get("stream_interval",
                                             cfg.stream_interval))
        if params.get("temperature") is not None:
            cfg.temperature = float(params["temperature"])
        if params.get("top_k") is not None:
            cfg.top_k = int(params["top_k"])
        if params.get("top_p") is not None:
            cfg.top_p = float(params["top_p"])
        if params.get("kv_pages") is not None:
            cfg.kv_pages = int(params["kv_pages"])
        cfg.kv_page_len = int(params.get("kv_page_len", cfg.kv_page_len))
        cfg.kv_int8 = bool(params.get("kv_int8", cfg.kv_int8))
        cfg.kv_shard = int(params.get("kv_shard", cfg.kv_shard))
        cfg.spec_k = int(params.get("spec_k", cfg.spec_k))
        cfg.log_dir = raw.get("log_dir", cfg.log_dir)
        cfg.health_path = raw.get("health_path", cfg.health_path)
        if raw.get("health_interval_s") is not None:
            cfg.health_interval_s = float(raw["health_interval_s"])
        return cfg
