"""Queue backends for serving.

The reference's data plane is a Redis stream (``image_stream`` XADD /
consumer-group reads, results in a ``result:<uri>`` hash —
``ClusterServing.scala:106-140,276-307``; client ``client.py:62,131``).
Here the backend is pluggable:

- :class:`FileQueue` (default): a spool directory — zero extra
  dependencies, works single-host and on a shared filesystem across hosts
  (results as per-uri JSON files). Requests are claimed by atomic rename
  locally, and by exclusive-create claim markers on ``scheme://`` spools
  (remote renames are copy+delete, not atomic); exactly-once on remote
  spools is as strong as the backend's exclusive-create (see
  ``file_io.create_exclusive``) — use RedisQueue for a hard guarantee.
- :class:`RedisQueue`: the reference's wire contract (stream + hash), gated
  on the ``redis`` package being installed.
"""
from __future__ import annotations

import base64
import hashlib
import json
import os

from ..common import file_io
from ..common.utils import wall_clock
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: admission classes, in CLAIM priority order — critical requests are
#: claimed first; shed/trim consume the lanes in the REVERSE order, so
#: sheddable traffic absorbs overload before default, and default before
#: critical (docs/serving.md#overload-survival)
CRITICALITY_LANES = ("critical", "default", "sheddable")
_CLAIM_RANK = {lane: i for i, lane in enumerate(CRITICALITY_LANES)}
_SHED_ORDER = tuple(reversed(CRITICALITY_LANES))
_SHED_RANK = {lane: i for i, lane in enumerate(_SHED_ORDER)}
#: FileQueue filename lane tag ("{ts}-{uuid}.{tag}.json")
_LANE_TAG = {"critical": "c", "default": "d", "sheddable": "s"}
_TAG_LANE = {v: k for k, v in _LANE_TAG.items()}


def criticality_of(payload: Dict[str, Any]) -> str:
    """The request's admission class; unknown/absent values degrade to
    ``default`` (never an error — a foreign producer must not crash
    admission control)."""
    lane = payload.get("criticality")
    return lane if lane in _CLAIM_RANK else "default"


class QueueBackend:
    """enqueue/claim requests; put/get results."""

    def enqueue(self, uri: str, payload: Dict[str, Any]) -> None:
        raise NotImplementedError

    def enqueue_many(self, items: Sequence[Tuple[str, Dict[str, Any]]]
                     ) -> None:
        """Enqueue a batch of ``(uri, payload)`` records. Backends override
        this with an amortized publish (one rename / one pipeline round-trip
        per batch); the default is the per-record loop."""
        for uri, payload in items:
            self.enqueue(uri, payload)

    def claim_batch(self, max_items: int) -> List[Tuple[str, Dict[str, Any]]]:
        """Atomically claim up to ``max_items`` pending requests."""
        raise NotImplementedError

    def put_result(self, uri: str, value: Dict[str, Any]) -> None:
        raise NotImplementedError

    def get_result(self, uri: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def pending_count(self) -> int:
        raise NotImplementedError

    def trim(self, max_pending: int) -> int:
        """Drop oldest requests beyond ``max_pending`` (the redis maxmem
        xtrim guard, ClusterServing.scala:134-140). Returns dropped count.
        SILENT — the dropped clients poll to their timeout. Kept for
        direct queue administration; the serve loop uses :meth:`shed`."""
        raise NotImplementedError

    def shed(self, max_pending: int,
             reason: str = "shed: queue overloaded") -> List[str]:
        """Erroring admission control: atomically remove requests beyond
        ``max_pending`` and post a terminal ``{"error": reason,
        "retriable": True}`` result for each, so every dropped client
        gets an explicit answer instead of polling to its timeout.
        Victims are consumed criticality-lane-first (sheddable, then
        default, then critical; oldest first within a lane), so under
        overload the critical class is the last to lose work.
        Returns the shed uris. Claims are exclusive — on a shared spool N
        servers shedding concurrently drop each request at most once."""
        raise NotImplementedError

    def discard_result(self, uri: str) -> bool:
        """Drop ``uri``'s terminal result from the result store, if any.
        Used by the client's hedged query to reap the losing copy so it
        is never surfaced and never leaks storage. Returns True when a
        result record was removed."""
        return False


class FileQueue(QueueBackend):
    # a remote claim marker older than this is considered abandoned (the
    # claiming consumer died between claim and cleanup) and is reaped so
    # the record becomes claimable again — at-least-once past a crash, the
    # same recovery stance as redis XAUTOCLAIM
    CLAIM_LEASE_S = 300.0

    def __init__(self, root: str, claim_lease_s: Optional[float] = None,
                 results_root: Optional[str] = None):
        """``results_root`` detaches the result store from the request
        spool: the fleet tier gives every server its OWN request spool
        (``<root>/inst/<name>``) while all of them post results into the
        FRONT spool's ``results/`` — clients poll one place no matter
        which instance answered, and the router's re-routing stays
        invisible to them."""
        self.root = root
        self.req_dir = file_io.join(root, "requests")
        self.claim_dir = file_io.join(root, "claimed")
        self.res_dir = file_io.join(results_root if results_root else root,
                                    "results")
        self.claim_lease_s = (claim_lease_s if claim_lease_s is not None
                              else self.CLAIM_LEASE_S)
        for d in (self.req_dir, self.claim_dir, self.res_dir):
            file_io.makedirs(d, exist_ok=True)

    @staticmethod
    def _record_name(payload: Dict[str, Any]) -> str:
        """Spool filename: wall-clock stamp (FIFO within a lane under
        ``sorted()``) + uniquifier + criticality lane tag, so claim/shed
        ordering never has to open the record to learn its class."""
        tag = _LANE_TAG[criticality_of(payload)]
        return (f"{int(wall_clock() * 1e9):020d}-"
                f"{uuid.uuid4().hex[:8]}.{tag}.json")

    @staticmethod
    def _lane_of_name(name: str) -> str:
        parts = name.split(".")
        if len(parts) >= 3 and parts[-2] in _TAG_LANE:
            return _TAG_LANE[parts[-2]]
        return "default"  # pre-lane spool files keep working

    def enqueue(self, uri: str, payload: Dict[str, Any]) -> None:
        name = self._record_name(payload)
        tmp = file_io.join(self.req_dir, "." + name)
        with file_io.fopen(tmp, "w") as f:
            f.write(json.dumps({"uri": uri, **payload}))
        file_io.replace(tmp, file_io.join(self.req_dir, name))  # atomic publish

    def enqueue_many(self, items: Sequence[Tuple[str, Dict[str, Any]]]
                     ) -> None:
        """Batched publish: all records are written into a hidden staging
        dir and made visible with ONE directory rename — a streaming
        producer pays one atomic publish per batch instead of one
        tmp-write + rename per record. Consumers flatten published batch
        dirs back into the spool lazily (see :meth:`_flatten_batches`).
        Remote spools rename by copy+delete (not atomic), so they fall
        back to the per-record loop."""
        items = list(items)
        if not items:
            return
        if file_io.is_remote(self.req_dir):
            for uri, payload in items:
                self.enqueue(uri, payload)
            return
        stage = file_io.join(self.req_dir, f".stage-{uuid.uuid4().hex[:8]}")
        file_io.makedirs(stage, exist_ok=True)
        for uri, payload in items:
            name = self._record_name(payload)
            with file_io.fopen(file_io.join(stage, name), "w") as f:
                f.write(json.dumps({"uri": uri, **payload}))
        batch = file_io.join(
            self.req_dir,
            f"batch-{int(wall_clock() * 1e9):020d}-{uuid.uuid4().hex[:8]}")
        file_io.replace(stage, batch)  # one rename publishes the batch

    def _flatten_batches(self, names: List[str]) -> List[str]:
        """Expand ``batch-*`` dirs published by :meth:`enqueue_many` into
        top-level record files and return the claimable names. Each member
        move is an atomic rename, so a consumer crashing mid-flatten
        leaves the rest claimable by the next lister; concurrent
        flatteners race per file and the loser skips (same stance as
        claims)."""
        out = [n for n in names if not n.startswith("batch-")]
        for bname in names:
            if not bname.startswith("batch-"):
                continue
            bdir = file_io.join(self.req_dir, bname)
            try:
                members = file_io.listdir(bdir, refresh=True)
            except (FileNotFoundError, NotADirectoryError, OSError):
                continue
            for m in members:
                try:
                    file_io.replace(file_io.join(bdir, m),
                                    file_io.join(self.req_dir, m))
                    out.append(m)
                except (OSError, FileNotFoundError):
                    pass  # another consumer moved it first
            try:
                # drop the dir only once it is verifiably empty — a move
                # that failed for any reason other than losing a race
                # must leave its record claimable on the next pass
                if not file_io.listdir(bdir, refresh=True):
                    file_io.rmtree(bdir)
            except (OSError, FileNotFoundError):
                pass
        return out

    def _claim_one(self, name: str) -> Optional[str]:
        """Claim one request; returns the path to read it from, or None if
        another consumer won. Local spools claim by atomic rename
        (os.replace — the loser raises). Remote spools claim by an
        EXCLUSIVE-CREATE marker in claimed/: a remote ``replace`` is
        copy+delete, so two consumers could both 'win' a rename — the
        marker makes the winner explicit (see file_io.create_exclusive for
        the per-backend atomicity story)."""
        src = file_io.join(self.req_dir, name)
        if not file_io.is_remote(src):
            dst = file_io.join(self.claim_dir, name)
            try:
                file_io.replace(src, dst)  # atomic claim; loser raises
            except (OSError, FileNotFoundError):
                return None
            return dst
        marker = file_io.join(self.claim_dir, name + ".claim")
        try:
            file_io.create_exclusive(
                marker, repr(wall_clock()).encode())
        except (FileExistsError, OSError):
            # marker held by another consumer — unless it's an expired
            # lease from a consumer that died between claim and cleanup.
            # Reaping (remove + recreate) is NOT atomic, so two reapers
            # interleaving could both "win" their create_exclusive (B
            # creates fresh, C removes B's fresh marker and creates its
            # own); a reap LOCK serializes them: only the exclusive-create
            # winner of ``<marker>.reap`` may remove and recreate the
            # claim marker.
            def _read_raw(path):
                try:
                    with file_io.fopen(path, "rb") as f:
                        return f.read().decode()
                except (OSError, FileNotFoundError, ValueError):
                    # ValueError covers UnicodeDecodeError from a corrupt
                    # or foreign marker: treat as unreadable, not fatal —
                    # the poll loop must survive junk in the spool
                    return None

            def _read_stamp(path):
                raw = _read_raw(path)
                if raw is None:  # vanished = claim completed, NOT stale
                    return None
                try:
                    # claim markers hold a bare stamp; reap locks hold
                    # "stamp:token" — the first field is the stamp either way
                    return float(raw.split(":")[0] or 0)
                except ValueError:
                    return None

            stamp = _read_stamp(marker)
            if stamp is None or wall_clock() - stamp < self.claim_lease_s:
                return None
            reap_lock = marker + ".reap"
            # unique stamp doubles as an ownership token: the finally
            # below must not delete a lock some other consumer re-acquired
            # after OUR tenure was (legitimately) declared stale
            lock_token = f"{wall_clock()!r}:{uuid.uuid4().hex}"
            try:
                file_io.create_exclusive(reap_lock, lock_token.encode())
            except (FileExistsError, OSError):
                # another consumer is reaping; if the LOCK itself is stale
                # (its holder died mid-reap), clear it so a later pass can
                # retry. The 2x-lease margin is the standard lease-system
                # stall bound: deleting a LIVE lock here would need the
                # reader to stall >1 full lease between read and remove.
                lock_stamp = _read_stamp(reap_lock)
                if (lock_stamp is not None
                        and wall_clock() - lock_stamp
                        >= 2 * self.claim_lease_s):
                    try:
                        file_io.remove(reap_lock)
                    except (OSError, FileNotFoundError):
                        pass
                return None
            try:
                # RE-VALIDATE under the lock: a previous reaper may have
                # already reclaimed this marker between our staleness read
                # and the lock acquisition — its fresh claim must survive
                stamp = _read_stamp(marker)
                if stamp is None or \
                        wall_clock() - stamp < self.claim_lease_s:
                    return None
                try:
                    file_io.remove(marker)
                except (OSError, FileNotFoundError):
                    pass
                # a fresh (non-reaping) consumer may slip in between the
                # remove and this create — then IT owns the claim and this
                # create fails: exactly one winner either way
                try:
                    file_io.create_exclusive(
                        marker, repr(wall_clock()).encode())
                except (FileExistsError, OSError):
                    return None
            finally:
                # release ONLY if we still own it: a reaper that stalled
                # past the 2x-lease margin may find its lock legitimately
                # cleared and re-acquired by another consumer — deleting
                # that live lock would re-open the two-reaper race
                if _read_raw(reap_lock) == lock_token:
                    try:
                        file_io.remove(reap_lock)
                    except (OSError, FileNotFoundError):
                        pass
        return src

    def _remove_claimed(self, name: str, path: str) -> None:
        """Clean up a fully-consumed claim: request file(s) first, marker
        LAST — a marker removed while the request still exists would let a
        second consumer re-claim the record."""
        cleanup = list({path, file_io.join(self.req_dir, name)})
        if file_io.is_remote(path):
            # the marker must not outlive the request either:
            # remote spools would leak one object per record
            cleanup.append(file_io.join(self.claim_dir, name + ".claim"))
        for p in cleanup:
            try:
                file_io.remove(p)
            except (OSError, FileNotFoundError):
                pass

    def claim_batch(self, max_items: int) -> List[Tuple[str, Dict[str, Any]]]:
        out = []
        try:
            # refresh: another process's enqueues must be visible despite
            # fsspec listing caches (remote spools). Claim order is
            # priority-lane first (critical → default → sheddable), FIFO
            # within a lane — under overload the deadline enforcement at
            # claim time therefore expires sheddable work last-admitted.
            names = sorted(self._flatten_batches(
                file_io.listdir(self.req_dir, refresh=True)),
                key=lambda n: (_CLAIM_RANK[self._lane_of_name(n)], n))
        except FileNotFoundError:
            return out
        for name in names:
            if name.startswith(".") or len(out) >= max_items:
                continue
            path = self._claim_one(name)
            if path is None:
                continue
            try:
                with file_io.fopen(path) as f:
                    rec = json.loads(f.read())
                out.append((rec["uri"], rec))
            except (ValueError, KeyError, OSError):
                # malformed request file (partial write / foreign producer):
                # skip it, keep the batch and the serve loop alive
                import logging
                logging.getLogger("analytics_zoo_tpu.serving").warning(
                    "dropping malformed request file %s", name)
            finally:
                self._remove_claimed(name, path)
        return out

    def shed(self, max_pending: int,
             reason: str = "shed: queue overloaded") -> List[str]:
        try:
            # victim order is the REVERSE of claim priority: sheddable
            # lanes absorb the overload first, critical requests are the
            # last to be dropped (oldest first within a lane)
            names = sorted((n for n in self._flatten_batches(
                file_io.listdir(self.req_dir, refresh=True))
                            if not n.startswith(".")),
                           key=lambda n: (_SHED_RANK[self._lane_of_name(n)],
                                          n))
        except FileNotFoundError:
            return []
        dropped: List[str] = []
        for name in names[:max(0, len(names) - max_pending)]:
            path = self._claim_one(name)  # exclusive: N shedders, one winner
            if path is None:
                continue
            try:
                with file_io.fopen(path) as f:
                    rec = json.loads(f.read())
                self.put_result(rec["uri"],
                                {"error": reason, "retriable": True})
                dropped.append(rec["uri"])
            except (ValueError, KeyError, OSError):
                # malformed request: no uri to answer — drop it outright
                import logging
                logging.getLogger("analytics_zoo_tpu.serving").warning(
                    "dropping malformed request file %s during shed", name)
            finally:
                self._remove_claimed(name, path)
        return dropped

    def put_result(self, uri: str, value: Dict[str, Any]) -> None:
        key = hashlib.md5(uri.encode()).hexdigest()
        tmp = file_io.join(self.res_dir, "." + key)
        with file_io.fopen(tmp, "w") as f:
            f.write(json.dumps({"uri": uri, **value}))
        file_io.replace(tmp, file_io.join(self.res_dir, key + ".json"))

    def get_result(self, uri: str) -> Optional[Dict[str, Any]]:
        key = hashlib.md5(uri.encode()).hexdigest()
        path = file_io.join(self.res_dir, key + ".json")
        if not file_io.exists(path):
            return None
        with file_io.fopen(path) as f:
            return json.loads(f.read())

    def discard_result(self, uri: str) -> bool:
        key = hashlib.md5(uri.encode()).hexdigest()
        path = file_io.join(self.res_dir, key + ".json")
        try:
            file_io.remove(path)
            return True
        except (OSError, FileNotFoundError):
            return False

    def all_results(self) -> Dict[str, Dict[str, Any]]:
        out = {}
        for name in file_io.listdir(self.res_dir):
            if name.startswith("."):
                continue
            with file_io.fopen(file_io.join(self.res_dir, name)) as f:
                rec = json.loads(f.read())
            out[rec["uri"]] = rec
        return out

    def pending_count(self) -> int:
        """Backlog depth, counting INTO published-but-unflattened batch
        dirs (read-only — depth accounting must not mutate the spool)."""
        try:
            count = 0
            for n in file_io.listdir(self.req_dir, refresh=True):
                if n.startswith("."):
                    continue
                if n.startswith("batch-"):
                    try:
                        count += sum(
                            1 for m in file_io.listdir(
                                file_io.join(self.req_dir, n), refresh=True)
                            if not m.startswith("."))
                    except (FileNotFoundError, NotADirectoryError, OSError):
                        pass
                else:
                    count += 1
            return count
        except FileNotFoundError:
            return 0

    def trim(self, max_pending: int) -> int:
        names = sorted((n for n in self._flatten_batches(
            file_io.listdir(self.req_dir, refresh=True))
                        if not n.startswith(".")),
                       key=lambda n: (_SHED_RANK[self._lane_of_name(n)], n))
        dropped = 0
        for name in names[:max(0, len(names) - max_pending)]:
            try:
                file_io.remove(file_io.join(self.req_dir, name))
                dropped += 1
            except OSError:
                pass
        return dropped


class RedisQueue(QueueBackend):
    """The reference wire contract: XADD to ``image_stream``, consumer-group
    reads, results HSET at ``result:<uri>``. Needs the redis package.

    Delivery is AT-LEAST-ONCE past a crash: a claimed entry is XACKed only
    after its result lands in :meth:`put_result` — a server that dies
    between claim and result leaves the entry in the group's PEL, and
    :meth:`claim_batch` XAUTOCLAIMs entries idle past ``claim_lease_s``
    back onto a live consumer (the FileQueue claim-marker reaping stance,
    in redis' native vocabulary)."""

    STREAM = "image_stream"
    GROUP = "serving"
    #: a pending entry idle this long belongs to a consumer presumed dead
    CLAIM_LEASE_S = 60.0

    def __init__(self, host: str = "localhost", port: int = 6379,
                 claim_lease_s: Optional[float] = None, client=None,
                 stream: Optional[str] = None, group: Optional[str] = None):
        if client is None:
            import redis  # gated dependency
            client = redis.StrictRedis(host=host, port=port, db=0)
        self.db = client
        if stream:
            self.STREAM = stream  # instance shadow of the class default —
        if group:                 # lets benches/tests run isolated streams
            self.GROUP = group    # on one shared server
        # unique consumer identity per server instance: XREADGROUP '>'
        # delivers each entry to exactly one consumer in the group, which
        # is what makes N serving servers on one stream exactly-once
        # (ClusterServing.scala's multi-executor contract)
        self.consumer = f"consumer-{uuid.uuid4().hex[:12]}"
        self.claim_lease_s = (claim_lease_s if claim_lease_s is not None
                              else self.CLAIM_LEASE_S)
        # criticality lanes are sibling streams sharing one group name:
        # default traffic rides the base stream (the reference wire
        # contract is unchanged), critical/sheddable get their own streams
        # so claim order and shed order can differ per class without
        # opening any payload
        self._lane_streams = {
            "critical": f"{self.STREAM}:crit",
            "default": self.STREAM,
            "sheddable": f"{self.STREAM}:shed",
        }
        # uri -> (stream, entry id), claimed but not yet answered; the ack
        # in put_result closes the loop (plain dict ops are GIL-atomic, and
        # claim/result run on different serve-loop threads)
        self._unacked: Dict[str, Tuple[str, Any]] = {}
        for lane in CRITICALITY_LANES:
            try:
                self.db.xgroup_create(self._lane_streams[lane], self.GROUP,
                                      mkstream=True)
            except Exception:
                pass  # group exists

    def enqueue(self, uri: str, payload: Dict[str, Any]) -> None:
        self.db.xadd(self._lane_streams[criticality_of(payload)],
                     {"uri": uri, "data": json.dumps(payload)})

    def enqueue_many(self, items: Sequence[Tuple[str, Dict[str, Any]]]
                     ) -> None:
        """Pipelined XADD: one round-trip per batch instead of one per
        record (order within the batch is preserved — a pipeline executes
        commands in submission order)."""
        items = list(items)
        if not items:
            return
        pipe = self.db.pipeline()
        for uri, payload in items:
            pipe.xadd(self._lane_streams[criticality_of(payload)],
                      {"uri": uri, "data": json.dumps(payload)})
        pipe.execute()

    def _reclaim_stale(self, stream: str, max_items: int) -> List:
        """XAUTOCLAIM entries whose claiming consumer died before acking
        (idle past the lease). Absent on old servers/fakes: no reclaim."""
        try:
            resp = self.db.xautoclaim(
                stream, self.GROUP, self.consumer,
                min_idle_time=int(self.claim_lease_s * 1000.0),
                count=max_items)
        except Exception:
            return []
        # redis-py returns (next_id, entries[, deleted]) depending on
        # server version; the entry list is always the second field
        if isinstance(resp, (list, tuple)) and len(resp) >= 2:
            return list(resp[1] or [])
        return []

    def claim_batch(self, max_items: int) -> List[Tuple[str, Dict[str, Any]]]:
        out: List[Tuple[str, Dict[str, Any]]] = []
        # priority lanes: drain the critical stream before default before
        # sheddable, FIFO within each
        for lane in CRITICALITY_LANES:
            room = max_items - len(out)
            if room <= 0:
                break
            stream = self._lane_streams[lane]
            entries = self._reclaim_stale(stream, room)
            if len(entries) < room:
                resp = self.db.xreadgroup(self.GROUP, self.consumer,
                                          {stream: ">"},
                                          count=room - len(entries),
                                          block=10)
                for _, fresh in resp or []:
                    entries.extend(fresh)
            for eid, fields in entries:
                uri = fields[b"uri"].decode()
                payload = json.loads(fields[b"data"].decode())
                out.append((uri, {"uri": uri, **payload}))
                # at-most-once fix: NO xack here — the ack waits for the
                # result (put_result), so a crash mid-batch redelivers via
                # _reclaim_stale instead of dropping the request forever
                self._unacked[uri] = (stream, eid)
        return out

    def put_result(self, uri: str, value: Dict[str, Any]) -> None:
        self.db.hset(f"result:{uri}", mapping={
            k: json.dumps(v) for k, v in value.items()})
        claim = self._unacked.pop(uri, None)
        if claim is not None:
            # result durable → the claim is settled; ack AFTER the hset so
            # a crash between the two redelivers (result overwrite is
            # idempotent) rather than losing the request
            stream, eid = claim
            self.db.xack(stream, self.GROUP, eid)

    def get_result(self, uri: str) -> Optional[Dict[str, Any]]:
        raw = self.db.hgetall(f"result:{uri}")
        if not raw:
            return None
        return {k.decode(): json.loads(v.decode()) for k, v in raw.items()}

    def discard_result(self, uri: str) -> bool:
        try:
            return bool(self.db.delete(f"result:{uri}"))
        except Exception:
            return False

    def _stream_pending(self, stream: str) -> int:
        # undelivered backlog (group lag) when the server exposes it —
        # XLEN counts already-served entries that linger until an XTRIM
        # and would make admission control shed phantom load
        try:
            for g in self.db.xinfo_groups(stream):
                name = g.get("name")
                if name in (self.GROUP, self.GROUP.encode()):
                    lag = g.get("lag")
                    if lag is not None:
                        return int(lag)
        except Exception:
            pass
        try:
            return int(self.db.xlen(stream))
        except Exception:
            return 0

    def pending_count(self) -> int:
        return sum(self._stream_pending(self._lane_streams[lane])
                   for lane in CRITICALITY_LANES)

    def consumer_pending(self) -> Dict[str, int]:
        """Per-consumer pending (claimed-not-yet-acked) counts, via XINFO
        CONSUMERS, summed across the lane streams. Group lag
        (:meth:`pending_count`) is the UNDELIVERED backlog; this is the
        in-flight side — what each server instance has claimed and not yet
        answered. The fleet router reads it as the true per-instance queue
        depth a placement decision adds to. Returns ``{}`` when the
        server/fake doesn't support the call."""
        out: Dict[str, int] = {}
        ok = False
        for lane in CRITICALITY_LANES:
            try:
                consumers = self.db.xinfo_consumers(
                    self._lane_streams[lane], self.GROUP)
            except Exception:
                continue
            ok = True
            for c in consumers:
                name = c.get("name")
                if isinstance(name, bytes):
                    name = name.decode()
                if name is None:
                    continue
                out[str(name)] = (out.get(str(name), 0)
                                  + int(c.get("pending") or 0))
        return out if ok else {}

    def trim(self, max_pending: int) -> int:
        before = self.pending_count()
        excess = before - max_pending
        for lane in _SHED_ORDER:  # sheddable lanes absorb the cut first
            if excess <= 0:
                break
            stream = self._lane_streams[lane]
            depth = self._stream_pending(stream)
            cut = min(excess, depth)
            if cut > 0:
                self.db.xtrim(stream, maxlen=depth - cut)
                excess -= cut
        return max(0, before - self.pending_count())

    def shed(self, max_pending: int,
             reason: str = "shed: queue overloaded") -> List[str]:
        dropped: List[str] = []
        excess = self.pending_count() - max_pending
        for lane in _SHED_ORDER:  # sheddable victims first, critical last
            while excess > 0:
                stream = self._lane_streams[lane]
                resp = self.db.xreadgroup(self.GROUP, self.consumer,
                                          {stream: ">"}, count=excess,
                                          block=10)
                entries = [e for _, es in resp or [] for e in es]
                if not entries:
                    break
                for eid, fields in entries:
                    uri = fields[b"uri"].decode()
                    self.put_result(uri,
                                    {"error": reason, "retriable": True})
                    self.db.xack(stream, self.GROUP, eid)
                    dropped.append(uri)
                excess -= len(entries)
        return dropped


def make_queue(src: str) -> QueueBackend:
    """``dir:///path``, a path, or a ``scheme://`` URI → FileQueue;
    ``host:port`` → RedisQueue."""
    if src.startswith("dir://"):
        return FileQueue(src[len("dir://"):])
    if file_io.scheme_of(src) is not None:
        return FileQueue(src)
    if ":" in src and not os.sep in src.split(":")[0]:
        host, port = src.rsplit(":", 1)
        try:
            return RedisQueue(host, int(port))
        except ImportError as e:
            raise RuntimeError(
                f"queue src '{src}' needs the redis package; use a "
                f"dir:///path file queue instead") from e
    return FileQueue(src)


def encode_image(img) -> str:
    """ndarray/bytes → base64 jpg string (client-side payload encoding)."""
    import numpy as np
    if isinstance(img, (bytes, bytearray)):
        return base64.b64encode(bytes(img)).decode()
    import cv2
    ok, buf = cv2.imencode(".jpg", np.asarray(img))
    if not ok:
        raise ValueError("image encode failed")
    return base64.b64encode(buf.tobytes()).decode()


def decode_image(b64: str):
    import cv2
    import numpy as np
    buf = np.frombuffer(base64.b64decode(b64), np.uint8)
    img = cv2.imdecode(buf, cv2.IMREAD_COLOR)
    if img is None:
        raise ValueError("image decode failed")
    return img
