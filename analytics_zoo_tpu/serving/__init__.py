"""Cluster Serving (reference ``serving/ClusterServing.scala:45`` +
``pyzoo/zoo/serving/client.py``): pub/sub queue → host preprocessing →
batched TPU inference → result write-back with backpressure."""
from .client import (InputQueue, OutputQueue,  # noqa: F401
                     ResilientClient, RetryBudget)
from .config import ServingConfig  # noqa: F401
from .fleet import (FLEET_SHED_ERROR, FleetInstance,  # noqa: F401
                    FleetRouter, instance_queue, read_health)
from .queues import (CRITICALITY_LANES, FileQueue,  # noqa: F401
                     QueueBackend, RedisQueue, criticality_of, make_queue)
from .server import (ClusterServing, GenerativeServing,  # noqa: F401
                     ModelReloadError)
