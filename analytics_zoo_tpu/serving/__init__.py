"""Cluster Serving (reference ``serving/ClusterServing.scala:45`` +
``pyzoo/zoo/serving/client.py``): pub/sub queue → host preprocessing →
batched TPU inference → result write-back with backpressure."""
from .client import InputQueue, OutputQueue  # noqa: F401
from .config import ServingConfig  # noqa: F401
from .fleet import (FLEET_SHED_ERROR, FleetInstance,  # noqa: F401
                    FleetRouter, instance_queue, read_health)
from .queues import FileQueue, QueueBackend, RedisQueue, make_queue  # noqa: F401
from .server import (ClusterServing, GenerativeServing,  # noqa: F401
                     ModelReloadError)
