"""Serving engine (reference ``serving/ClusterServing.scala:45``): the loop
is claim micro-batch → decode base64 images → preprocess to the model shape
→ batched ``InferenceModel.doPredict`` → top-N postprocess → result
write-back, with throughput summaries (``:312-331``). One process per host;
the TPU executes the batched forward, threads only move bytes.

Request-lifecycle SLO layer (the Tail-at-Scale/Clipper machinery the
reference leaves to the operator): the invariant is that **every claimed
request receives exactly one terminal result — a value or an explicit
error — no matter what fails**. Deadlines are checked at claim, after
decode, and before dispatch (expired work answers ``deadline exceeded``
instead of burning device time); overload sheds the oldest requests with
explicit shed errors instead of silent trims; SIGTERM drains (finish
in-flight, flush, terminal ``health.json``) instead of dropping; and
``reload_model`` hot-swaps the model off the serve path with a canary
predict and rollback. ``health_snapshot()`` is the deep-health surface
(queue depth, claim age, in-flight, p50/p99, shed/expired/error counters)
supervisors consume as a dict or as the periodically-written
``config.health_path`` file."""
from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..common import faults, file_io
from ..common import metrics as _metrics
from ..common import profiler as _profiler
from ..common.utils import time_it
from ..inference.inference_model import InferenceModel
from ..utils import trace as _trace
from .config import ServingConfig
from .queues import QueueBackend, decode_image, make_queue

logger = logging.getLogger("analytics_zoo_tpu.serving")

#: canonical terminal error texts (clients match on these)
SHED_ERROR = "shed: queue overloaded"
DEADLINE_ERROR = "deadline exceeded"
SHUTDOWN_ERROR = "serving shut down before this request completed"

#: SLO telemetry in the shared registry (common/metrics.py). Every family
#: is labeled by server instance so two servers in one process (tests, the
#: multi-server spool) keep separate series; ``health_snapshot()`` is a
#: per-instance view of these.
_M_COUNTERS = {
    "shed": _metrics.counter(
        "serving.shed_total", "Requests shed by admission control.",
        labels=("server",)),
    "expired": _metrics.counter(
        "serving.expired_total", "Requests answered with deadline errors.",
        labels=("server",)),
    "errors": _metrics.counter(
        "serving.error_total",
        "Requests answered with non-deadline error results.",
        labels=("server",)),
    "claim_faults": _metrics.counter(
        "serving.claim_fault_total", "Transient claim-stage failures.",
        labels=("server",)),
    "reloads": _metrics.counter(
        "serving.reload_total", "Successful hot model reloads.",
        labels=("server",)),
    "reload_failures": _metrics.counter(
        "serving.reload_failure_total",
        "Model reloads that failed and rolled back.", labels=("server",)),
}
_M_RECORDS = _metrics.counter(
    "serving.records_total", "Records answered with prediction values.",
    labels=("server",))
_M_LATENCY = _metrics.histogram(
    "serving.request_latency_seconds",
    "Enqueue-to-terminal-result latency (client-stamped enqueue_t).",
    labels=("server",))
_M_QUEUE_DEPTH = _metrics.gauge(
    "serving.queue_depth", "Pending requests in the claim queue.",
    labels=("server",))
_M_IN_FLIGHT = _metrics.gauge(
    "serving.in_flight", "Claimed requests without a terminal result yet.",
    labels=("server",))
_M_CLAIM_AGE = _metrics.gauge(
    "serving.claim_age_seconds", "Seconds since the last successful claim.",
    labels=("server",))

_instance_ids = itertools.count()


class ModelReloadError(RuntimeError):
    """``reload_model`` failed; the PREVIOUS model is still serving."""


def top_n(probs: np.ndarray, n: int) -> List[Dict[str, float]]:
    """Per-record topN (class, prob) filter (reference
    ``PostProcessing.scala``)."""
    idx = np.argsort(-probs)[:n]
    return [{"class": int(i), "prob": float(probs[i])} for i in idx]


class ClusterServing:
    #: min seconds between shed passes — a shed scans the backlog, and
    #: re-scanning every 5ms claim poll would double the spool listings
    #: (expensive on remote spools) for no added protection
    SHED_INTERVAL_S = 0.05

    def __init__(self, config: ServingConfig,
                 model: Optional[InferenceModel] = None,
                 queue: Optional[QueueBackend] = None):
        self.config = config
        self.queue = queue if queue is not None else make_queue(config.data_src)
        self.model = model if model is not None else self._load_model()
        # compile warmth before traffic: the first claimed micro-batch must
        # hit an already-compiled program, not eat a multi-second XLA
        # compile while clients poll (InferenceModel.compile_counts proves
        # it — tests assert no NEW compile on the first request)
        self.prewarmed = self._prewarm_model()
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pool = None
        self.records_served = 0
        self.device_seconds = 0.0  # dispatch→fetch time across batches
        # -- SLO bookkeeping --------------------------------------------------
        # counters/latency/gauges live in the process-global metrics
        # registry, one label per server instance (health_snapshot() and
        # the .counters property are views of it)
        self.metrics_label = f"srv{next(_instance_ids)}"
        self._m = {key: fam.labels(server=self.metrics_label)
                   for key, fam in _M_COUNTERS.items()}
        self._m_records = _M_RECORDS.labels(server=self.metrics_label)
        self._m_latency = _M_LATENCY.labels(server=self.metrics_label)
        self._m_depth = _M_QUEUE_DEPTH.labels(server=self.metrics_label)
        self._m_in_flight = _M_IN_FLIGHT.labels(server=self.metrics_label)
        self._m_claim_age = _M_CLAIM_AGE.labels(server=self.metrics_label)
        self._counter_lock = threading.Lock()
        self._in_flight = 0  # claimed, no terminal result yet
        #: uri -> (enqueue_t, trace_id) — latency base + flow-chain id
        self._meta: Dict[str, Tuple[float, Optional[int]]] = {}
        self._ewma_record_s = 0.0  # smoothed device seconds per record
        self._last_claim_m: Optional[float] = None  # monotonic
        self._last_health_m = -1e18
        self._last_shed_m = -1e18
        self._claim_fail_streak = 0
        self._loop_running = False
        self._terminal_state: Optional[str] = None
        self._reload_lock = threading.Lock()
        self._writer = None
        if config.log_dir:
            from ..utils.tensorboard import SummaryWriter
            self._writer = SummaryWriter(
                os.path.join(config.log_dir, "serving"))

    def _load_model(self, cfg: Optional[ServingConfig] = None
                    ) -> InferenceModel:
        cfg = cfg if cfg is not None else self.config
        im = InferenceModel(concurrent_num=cfg.concurrent_num)
        if cfg.model_type == "zoo":
            im.load_zoo(cfg.model_path)
        elif cfg.model_type == "savedmodel":
            im.load_savedmodel(cfg.model_path)
        elif cfg.model_type == "torch":
            im.load_torch(cfg.model_path)
        elif cfg.model_type == "onnx":
            im.load_onnx(cfg.model_path)
        elif cfg.model_type == "caffe":
            h, w, c = cfg.image_shape
            im.load_caffe(cfg.model_path, cfg.model_weight_path or None,
                          input_shape=(c, h, w))
        else:
            raise ValueError(f"unknown model_type {cfg.model_type}")
        if cfg.quantize:
            im.quantize(cfg.quantize)
        return im

    def _example_batch(self) -> np.ndarray:
        """A zeros batch shaped like ``_prepare``'s output: image records
        decode to ``image_shape`` arrays (uint8 or float32 per
        ``input_dtype``), tensor records are always float32."""
        cfg = self.config
        dtype = np.uint8 if cfg.input_dtype == "uint8" else np.float32
        return np.zeros((cfg.batch_size,) + tuple(cfg.image_shape), dtype)

    def _prewarm_model(self, model: Optional[InferenceModel] = None) -> bool:
        """AOT-compile the configured ``batch_size`` bucket at startup.
        A model whose forward rejects a zeros batch just logs and compiles
        lazily."""
        model = model if model is not None else self.model
        if not getattr(model, "prewarm", None):
            return False
        try:
            model.prewarm(self._example_batch(),
                          buckets=(self.config.batch_size,))
            return True
        except Exception:
            logger.exception(
                "startup prewarm failed; the first request at each shape "
                "bucket will pay the compile instead")
            return False

    # -- record prep ----------------------------------------------------------

    def _prepare(self, record: Dict[str, Any]) -> np.ndarray:
        # chaos site: a faulty decode must become THIS record's error
        # result (the _decode future handler), never kill the claim loop
        faults.inject("serving.decode")
        cfg = self.config
        if "image" in record:  # base64-encoded image bytes
            img = decode_image(record["image"])
            h, w = cfg.image_shape[0], cfg.image_shape[1]
            if img.shape[:2] != (h, w):
                import cv2
                img = cv2.resize(img, (w, h))
            # uint8 wire applies to IMAGES only (pixels are uint8 by nature)
            dtype = np.uint8 if cfg.input_dtype == "uint8" else np.float32
            return np.asarray(img, dtype)
        if "tensor" in record:  # raw numeric payload: always float32 — a
            # uint8 cast would silently truncate/wrap client floats
            return np.asarray(record["tensor"], np.float32)
        raise ValueError(f"record has neither image nor tensor: "
                         f"{sorted(record)}")

    def _decode_pool(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=self.config.decode_threads,
                thread_name_prefix="zoo-serving-decode")
        return self._pool

    def _shutdown_pool(self) -> None:
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)

    # -- SLO bookkeeping ------------------------------------------------------

    @property
    def counters(self) -> Dict[str, int]:
        """Instance view of the registry-backed SLO counters (same keys the
        old hand-rolled dict had, so supervisors/tests read it unchanged)."""
        return {key: int(c.value()) for key, c in self._m.items()}

    def _count(self, key: str, n: int = 1) -> None:
        self._m[key].inc(n)
        if key in ("shed", "expired"):
            # first SLO breach can arm a jax.profiler capture window
            # (profile.capture_on_breach) — cheap no-op otherwise
            _profiler.on_slo_breach(key)

    def _flow_uris(self, uris: List[str], stage: str) -> None:
        """Stamp one flow-chain point per uri (no-op unless a trace
        session is active — the lookup cost stays off the hot path)."""
        if not _trace.tracing():
            return
        with self._counter_lock:
            ids = [self._meta.get(u, (0.0, None))[1] for u in uris]
        for flow_id in ids:
            _trace.flow_point(flow_id, stage, "t")

    def _expiry(self, rec: Dict[str, Any]) -> Optional[float]:
        """Absolute wall-clock expiry for a record, or None when it has no
        deadline. Wall clock is deliberate: ``enqueue_t`` is stamped by the
        CLIENT process and the wall is the only clock two processes share;
        every purely-local interval in this file uses ``time.monotonic()``."""
        deadline_ms = rec.get("deadline_ms") or self.config.default_deadline_ms
        if not deadline_ms:
            return None
        t0 = rec.get("enqueue_t")
        base = float(t0) if t0 is not None else time.time()
        return base + float(deadline_ms) / 1000.0

    def _post_terminal(self, uri: str, value: Dict[str, Any]) -> None:
        """Every claimed request funnels its ONE terminal result (value or
        error) through here — latency and in-flight accounting included."""
        try:
            self.queue.put_result(uri, value)
        except Exception:
            logger.exception("posting result for %s failed", uri)
        with self._counter_lock:
            self._in_flight = max(0, self._in_flight - 1)
            in_flight = self._in_flight
            meta = self._meta.pop(uri, None)
        self._m_in_flight.set(in_flight)
        if meta is not None:
            t0, flow_id = meta
            self._m_latency.observe(max(time.time() - t0, 0.0))
            # flow terminus: the request's lifecycle chain ends here
            _trace.flow_point(flow_id, "serving.result", "f")

    def _error_batch(self, uris: List[str], message: str,
                     counter: str = "errors") -> None:
        for uri in uris:
            self._post_terminal(uri, {"error": message})
        if uris:
            self._count(counter, len(uris))

    # -- pipeline stages ------------------------------------------------------

    def _shed(self) -> None:
        """Erroring admission control (replaces the silent trim): every
        dropped request gets an explicit shed error result. Two knobs:
        ``max_pending`` caps absolute depth; ``shed_wait_ms`` caps the
        ESTIMATED WAIT of the queue tail (depth x smoothed per-record
        service time) so a slow model sheds earlier than a fast one."""
        now = time.monotonic()
        if now - self._last_shed_m < self.SHED_INTERVAL_S:
            return
        self._last_shed_m = now
        cfg = self.config
        allowed = cfg.max_pending
        if cfg.shed_wait_ms:
            with self._counter_lock:
                per_record_s = self._ewma_record_s
            if per_record_s > 0:
                allowed = min(allowed, max(
                    cfg.batch_size,
                    int(cfg.shed_wait_ms / 1000.0 / per_record_s)))
        try:
            dropped = self.queue.shed(allowed, reason=SHED_ERROR)
        except OSError as e:
            logger.warning("shed pass failed (transient): %r", e)
            return
        if dropped:
            self._count("shed", len(dropped))
            logger.warning(
                "overload: shed %d oldest requests with error results "
                "(allowed depth %d)", len(dropped), allowed)

    def _claim(self) -> List[Tuple[str, Dict[str, Any]]]:
        """Claim up to one micro-batch: shed first, then fill the batch
        within the ``batch_wait_ms`` window on the MONOTONIC clock (a
        wall-clock step must not warp the batch window). A transient
        claim failure (flaky backend, injected ``serving.claim`` fault) is
        absorbed and retried; ``claim_retries`` consecutive failures
        surface the backend as dead."""
        cfg = self.config
        self._shed()
        deadline = time.monotonic() + cfg.batch_wait_ms / 1000.0
        batch: List[Tuple[str, Dict[str, Any]]] = []
        while len(batch) < cfg.batch_size and time.monotonic() < deadline:
            try:
                # chaos site: a flaky queue backend must be retried, not
                # kill the serve loop
                faults.inject("serving.claim")
                got = self.queue.claim_batch(cfg.batch_size - len(batch))
                self._claim_fail_streak = 0
            except OSError as e:
                self._count("claim_faults")
                self._claim_fail_streak += 1
                if self._claim_fail_streak > cfg.claim_retries:
                    raise  # dead backend, not a flaky one: surface it
                logger.warning("transient claim failure (%d/%d): %r",
                               self._claim_fail_streak, cfg.claim_retries, e)
                time.sleep(0.002)
                continue
            if got:
                self._last_claim_m = time.monotonic()
                batch.extend(got)
            elif not batch:
                break  # nothing pending at all
            else:
                time.sleep(0.001)
        if batch:
            now = time.time()
            with self._counter_lock:
                self._in_flight += len(batch)
                in_flight = self._in_flight
                for uri, rec in batch:
                    self._meta[uri] = (float(rec.get("enqueue_t") or now),
                                       rec.get("trace_id"))
            self._m_in_flight.set(in_flight)
            if _trace.tracing():
                for uri, rec in batch:
                    _trace.flow_point(rec.get("trace_id"),
                                      "serving.claim", "t")
        return batch

    def _filter_expired(self, batch: List[Tuple[str, Dict[str, Any]]]
                        ) -> List[Tuple[str, Dict[str, Any]]]:
        """Deadline check at claim: already-expired records answer the
        deadline error immediately — no decode, no device time."""
        if not batch:
            return batch
        now = time.time()
        live, expired = [], []
        for uri, rec in batch:
            exp = self._expiry(rec)
            (expired if exp is not None and now >= exp
             else live).append((uri, rec))
        if expired:
            self._error_batch([u for u, _ in expired], DEADLINE_ERROR,
                              counter="expired")
        return live

    def _decode(self, batch: List):
        """Decode a claimed batch on the thread pool (cv2 releases the GIL);
        undecodable records become error results immediately, and records
        whose deadline expired DURING decode answer the deadline error
        instead of riding to the device."""
        uris, arrays, expiries = [], [], []
        errors, expired = [], []
        tracing = _trace.tracing()
        t_dec = time.perf_counter()
        with time_it("serving.decode_batch"):
            futures = [(uri, rec,
                        self._decode_pool().submit(self._prepare, rec))
                       for uri, rec in batch]
            for uri, rec, fut in futures:
                try:
                    arr = fut.result()
                except Exception as e:  # undecodable record → error result
                    errors.append((uri, str(e)))
                    continue
                if tracing:
                    _trace.flow_point(rec.get("trace_id"),
                                      "serving.decode", "t")
                exp = self._expiry(rec)
                if exp is not None and time.time() >= exp:
                    expired.append(uri)
                    continue
                uris.append(uri)
                arrays.append(arr)
                expiries.append(exp)
        _profiler.record_phase("serving", "host_input",
                               time.perf_counter() - t_dec, start=t_dec)
        for uri, msg in errors:
            self._post_terminal(uri, {"error": msg})
        if errors:
            self._count("errors", len(errors))
        self._error_batch(expired, DEADLINE_ERROR, counter="expired")
        return uris, arrays, expiries

    def _expire_before_dispatch(self, uris: List[str], x: np.ndarray,
                                expiries: List[Optional[float]]):
        """Last deadline check, right before device dispatch — queueing
        inside the pipeline must not launder expired work onto the chip."""
        now = time.time()
        keep = [i for i, e in enumerate(expiries) if e is None or now < e]
        if len(keep) == len(uris):
            return uris, x
        kept = set(keep)
        self._error_batch([u for i, u in enumerate(uris) if i not in kept],
                          DEADLINE_ERROR, counter="expired")
        if not keep:
            return [], x[:0]
        return [uris[i] for i in keep], x[keep]

    def _dispatch(self, x: np.ndarray):
        """Async device dispatch for one decoded batch. Single choke point
        for the ``serving.predict`` chaos site: callers catch any failure
        and post per-uri error results so one bad batch cannot take the
        loop (or its batch's clients) down with it."""
        faults.inject("serving.predict")
        t_d = time.perf_counter()
        with time_it("serving.dispatch_batch"):
            handle = self.model.predict_async(x)
        _profiler.record_phase("serving", "dispatch",
                               time.perf_counter() - t_d, start=t_d)
        return handle

    def _writeback(self, uris: List[str], probs: np.ndarray,
                   device_elapsed: float) -> None:
        # chaos site: a failed writeback must error its batch and keep the
        # server draining (the writeback thread's per-batch catch)
        faults.inject("serving.writeback")
        cfg = self.config
        with time_it("serving.writeback_batch"):
            for uri, p in zip(uris, probs):
                p = np.asarray(p).reshape(-1)
                if cfg.filter_top_n:
                    self._post_terminal(uri,
                                        {"topN": top_n(p, cfg.filter_top_n)})
                else:
                    self._post_terminal(uri, {"value": p.tolist()})
        self._m_records.inc(len(uris))
        self.records_served += len(uris)
        self.device_seconds += device_elapsed
        if uris:
            per = device_elapsed / len(uris)
            with self._counter_lock:
                self._ewma_record_s = (
                    per if self._ewma_record_s == 0.0
                    else 0.8 * self._ewma_record_s + 0.2 * per)
        if self._writer is not None:
            self._writer.add_scalar("Serving Throughput",
                                    len(uris) / max(device_elapsed, 1e-9),
                                    self.records_served)
            self._writer.add_scalar("Total Records Number",
                                    self.records_served, self.records_served)

    def _force_sentinel(self, q) -> None:
        """Land a ``None`` sentinel on a possibly-full queue. Any real
        in-flight item displaced to make room was already CLAIMED from the
        spool — its requests get error results rather than vanishing (the
        client would otherwise poll to its timeout)."""
        import queue as pyqueue
        while True:
            try:
                q.put(None, timeout=0.2)
                return
            except pyqueue.Full:
                try:
                    item = q.get_nowait()
                except pyqueue.Empty:
                    continue
                if item is None:
                    continue
                self._error_batch(list(item[0]), SHUTDOWN_ERROR)

    # -- deep health ----------------------------------------------------------

    def health_snapshot(self) -> Dict[str, Any]:
        """Structured deep-health snapshot: lifecycle state, queue depth,
        last-claim age, in-flight count, p50/p99 terminal latency, and the
        shed/expired/error counters. Supervisors consume the same dict as
        the periodically-written ``config.health_path`` file; tests consume
        it directly. (``check_health()`` remains the narrow liveness probe
        that re-raises a crashed background loop.)

        This is a per-instance VIEW of the shared metrics registry
        (``common.metrics.metrics_snapshot()``): the counters and the
        latency histogram live there, scrapable as Prometheus text via the
        ``metrics.prom`` file written next to ``health.json``. On an empty
        latency window ``p50``/``p99`` are ``null`` — never a fake
        ``0.0`` (see docs/observability.md)."""
        with self._counter_lock:
            in_flight = self._in_flight
        counters = self.counters

        def _pct(p: float) -> Optional[float]:
            v = self._m_latency.percentile(p)
            return None if v is None else round(v * 1e3, 3)

        err = getattr(self, "_background_error", None)
        if self._terminal_state is not None:
            state = self._terminal_state
        elif err is not None:
            state = "crashed"
        elif self._draining.is_set():
            state = "draining"
        elif self._loop_running or (self._thread is not None
                                    and self._thread.is_alive()):
            state = "running"
        else:
            state = "idle"
        try:
            pending = self.queue.pending_count()
        except Exception:
            pending = None
        now_m = time.monotonic()
        claim_age = (round(now_m - self._last_claim_m, 3)
                     if self._last_claim_m is not None else None)
        # refresh the point-in-time gauges on the same cadence the
        # snapshot is taken (scrapers read them from metrics.prom)
        if pending is not None:
            self._m_depth.set(pending)
        self._m_in_flight.set(in_flight)
        if claim_age is not None:
            self._m_claim_age.set(claim_age)
        return {
            "state": state,
            "time": time.time(),
            "queue_pending": pending,
            "in_flight": in_flight,
            "records_served": self.records_served,
            "device_seconds": round(self.device_seconds, 4),
            "last_claim_age_s": claim_age,
            "latency_ms": {"p50": _pct(0.50), "p99": _pct(0.99),
                           "window": self._m_latency.count()},
            "counters": counters,
            "prewarmed": self.prewarmed,
            "error": repr(err) if err is not None else None,
        }

    def _write_health(self) -> None:
        path = self.config.health_path
        if not path:
            return
        # health cadence doubles as the profiler's slow tick: refresh the
        # HBM/RSS/build-info gauges so they land in THIS metrics.prom, and
        # close any elapsed time-bounded capture window (a quiet queue sees
        # no step boundaries)
        try:
            _profiler.sample_memory()
            _profiler.maybe_stop_capture()
        except Exception:
            logger.debug("profiler health tick failed", exc_info=True)
        tmp = path + ".tmp"
        try:
            with file_io.fopen(tmp, "w") as f:
                f.write(json.dumps(self.health_snapshot()))
            file_io.replace(tmp, path)  # atomic: readers never see a tear
        except OSError:
            logger.warning("health write to %s failed", path)
        # Prometheus exposition rides the same cadence: metrics.prom next
        # to health.json, for a node-exporter textfile collector / sidecar
        sep = "/" if "/" in path or "://" in path else os.sep
        prom = path.rsplit(sep, 1)[0] + sep + "metrics.prom" \
            if sep in path else "metrics.prom"
        tmp = prom + ".tmp"
        try:
            with file_io.fopen(tmp, "w") as f:
                f.write(_metrics.expose_text())
            file_io.replace(tmp, prom)
        except OSError:
            logger.warning("metrics write to %s failed", prom)

    def _maybe_write_health(self) -> None:
        if not self.config.health_path:
            return
        now = time.monotonic()
        if now - self._last_health_m >= self.config.health_interval_s:
            self._last_health_m = now
            self._write_health()

    # -- hot model reload -----------------------------------------------------

    def reload_model(self, model_path: Optional[str] = None, *,
                     model: Optional[InferenceModel] = None,
                     model_type: Optional[str] = None) -> InferenceModel:
        """Hot-swap the serving model with canary + rollback. The candidate
        loads and prewarms OFF the serve path (the old model keeps serving
        the whole time), canary-predicts one synthetic batch, and only then
        swaps in — a single attribute store, atomic under the GIL, so no
        request is ever dropped or misrouted: in-flight batches hold a
        reference to whichever model dispatched them. ANY failure (load,
        prewarm, canary, injected ``serving.reload`` chaos) leaves the old
        model serving and raises :class:`ModelReloadError`."""
        with self._reload_lock:
            old = self.model
            cfg = self.config
            try:
                # chaos site: a reload that dies anywhere must roll back
                faults.inject("serving.reload")
                if model is None:
                    if model_path is None:
                        raise ValueError(
                            "reload_model needs model_path= or model=")
                    import dataclasses
                    model = self._load_model(dataclasses.replace(
                        cfg, model_path=model_path,
                        model_type=model_type or cfg.model_type))
                # prewarm + canary off the serve path: the swap only
                # happens once the candidate has proven it can answer
                self._prewarm_model(model)
                example = self._example_batch()
                canary = model.predict(example)
                import jax
                leaves = jax.tree_util.tree_leaves(canary)
                if not leaves:
                    raise ValueError("canary predict returned no outputs")
                for leaf in leaves:
                    a = np.asarray(leaf)
                    if a.shape[0] != cfg.batch_size:
                        raise ValueError(
                            f"canary predict returned leading dim "
                            f"{a.shape[0]} for a batch of {cfg.batch_size}")
                    if np.issubdtype(a.dtype, np.floating) \
                            and not np.isfinite(a).all():
                        raise ValueError(
                            "canary predict produced non-finite values")
                self.model = model  # atomic swap: next dispatch uses it
                if model_path is not None:
                    cfg.model_path = model_path
                    if model_type:
                        cfg.model_type = model_type
                self._count("reloads")
                logger.info("model reloaded%s",
                            f" from {model_path}" if model_path else "")
                return model
            except Exception as e:
                self.model = old  # rollback (no-op unless a partial swap)
                self._count("reload_failures")
                logger.exception(
                    "model reload failed; previous model still serving")
                raise ModelReloadError(
                    f"model reload failed ({e!r}); previous model still "
                    f"serving") from e

    # -- the serve loop -------------------------------------------------------

    def serve_once(self) -> int:
        """One synchronous micro-batch (claim → decode → predict →
        writeback); returns the number of records claimed — every one of
        them receives a terminal result (value, deadline error, decode
        error, or predict error) before this returns. ``run()`` pipelines
        these stages — this method is the single-step form for tests and
        manual driving."""
        batch = self._claim()
        self._maybe_write_health()
        if not batch:
            return 0
        claimed = len(batch)
        uris, arrays, expiries = self._decode(self._filter_expired(batch))
        if arrays:
            x = np.stack(arrays)
            uris, x = self._expire_before_dispatch(uris, x, expiries)
            if uris:
                start = time.perf_counter()
                try:
                    self._flow_uris(uris, "serving.dispatch")
                    fetch = self._dispatch(x)
                    probs = np.asarray(fetch())
                    self._writeback(uris, probs,
                                    time.perf_counter() - start)
                except Exception as e:
                    logger.exception("predict/writeback failed for %d "
                                     "records", len(uris))
                    self._error_batch(uris, repr(e))
        return claimed

    def run(self, poll_interval_s: float = 0.005) -> None:
        """Pipelined serve loop: a claim+decode thread feeds the dispatch
        stage, and a writeback thread drains device results — batch N+1
        decodes on host threads while batch N runs on the device and batch
        N-1's results upload (the reference runs decode serially inside the
        structured-streaming micro-batch, ``ClusterServing.scala:160-259``;
        overlapping the stages is what keeps a fast chip fed)."""
        import queue as pyqueue

        logger.info("serving started (src=%s batch=%d)",
                    self.config.data_src, self.config.batch_size)
        self._terminal_state = None
        self._loop_running = True
        # a fresh loop gets an immediate admission pass: a backlog that
        # piled up while the server was down must shed BEFORE it is
        # claimed, not ride through because the previous loop's shed
        # stamp is still inside the interval gate
        self._last_shed_m = -1e18
        decoded_q: "pyqueue.Queue" = pyqueue.Queue(maxsize=2)
        fetch_q: "pyqueue.Queue" = pyqueue.Queue(maxsize=2)
        errors: List[BaseException] = []
        dead = threading.Event()  # any stage died — unblock everyone

        def _put(q: "pyqueue.Queue", item) -> bool:
            """Bounded put that can never wedge the pipeline: gives up when
            the loop is stopping or a peer stage has died. Monotonic-clock
            stall accounting — wall steps must not mask a wedged stage."""
            start = time.monotonic()
            while not dead.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except pyqueue.Full:
                    if time.monotonic() - start > 30:
                        logger.warning(
                            "pipeline stage blocked handing off a batch "
                            "for %.0fs", time.monotonic() - start)
                        start = time.monotonic()
                    continue
            return False

        def decoder() -> None:
            try:
                while not self._stop.is_set() and not dead.is_set():
                    if self._draining.is_set():
                        return  # drain: stop CLAIMING; sentinel flushes
                    self._maybe_write_health()
                    batch = self._filter_expired(self._claim())
                    if not batch:
                        time.sleep(poll_interval_s)
                        continue
                    uris, arrays, expiries = self._decode(batch)
                    if arrays and not _put(decoded_q,
                                           (uris, np.stack(arrays),
                                            expiries)):
                        self._error_batch(uris, SHUTDOWN_ERROR)
                        return
            except BaseException as e:  # pragma: no cover - surfaced below
                errors.append(e)
                dead.set()
            finally:
                self._force_sentinel(decoded_q)

        def writeback() -> None:
            while True:
                item = fetch_q.get()
                if item is None:
                    return
                uris, fetch = item
                try:
                    t0 = time.perf_counter()
                    probs = fetch()  # blocks on the device fetch only
                    elapsed = time.perf_counter() - t0
                    # device execute + transfer both resolve inside fetch()
                    # on the async path; attribute the blocked time there
                    _profiler.record_phase("serving", "fetch", elapsed,
                                           start=t0)
                    self._writeback(uris, np.asarray(probs), elapsed)
                except BaseException as e:
                    # one failed batch must not wedge the server: record
                    # error results and keep draining
                    logger.exception("writeback failed for %d records",
                                     len(uris))
                    self._error_batch(list(uris), repr(e))

        threads = [threading.Thread(target=decoder, daemon=True,
                                    name="zoo-serving-claim"),
                   threading.Thread(target=writeback, daemon=True,
                                    name="zoo-serving-writeback")]
        for t in threads:
            t.start()
        try:
            while True:
                item = decoded_q.get()
                if item is None:
                    break
                uris, x, expiries = item
                uris, x = self._expire_before_dispatch(uris, x, expiries)
                if not uris:
                    continue
                # async dispatch: the device computes while the NEXT batch
                # decodes and the PREVIOUS batch's fetch+writeback runs
                try:
                    self._flow_uris(uris, "serving.dispatch")
                    fetch = self._dispatch(x)
                except Exception as e:
                    logger.exception("dispatch failed for %d records",
                                     len(uris))
                    self._error_batch(uris, repr(e))
                    continue
                if not _put(fetch_q, (uris, fetch)):
                    self._error_batch(uris, SHUTDOWN_ERROR)
                    break
        finally:
            drained = (self._draining.is_set() and not dead.is_set()
                       and not errors)
            self._stop.set()
            dead.set()
            self._force_sentinel(fetch_q)
            for t in threads:
                t.join(timeout=10)
            self._shutdown_pool()
            self._loop_running = False
            self._terminal_state = ("crashed" if errors
                                    else "drained" if drained else "stopped")
            self._write_health()
        if errors:
            raise errors[0]
        if self._writer is not None:
            self._writer.flush()

    def start(self) -> "ClusterServing":
        """Run the loop in a background thread (the spark-submit long-running
        job role). A crash in the loop is captured and re-raised from
        :meth:`stop` / :meth:`check_health` — a dead queue backend must not
        kill the server silently."""
        self._stop.clear()
        self._draining.clear()
        self._terminal_state = None
        self._background_error: Optional[BaseException] = None

        def _run() -> None:
            try:
                self.run()
            except BaseException as e:
                logger.exception("serving loop died")
                self._background_error = e

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()
        return self

    def check_health(self) -> None:
        """Raise the background loop's failure, if any (liveness probe for
        supervisors driving :meth:`start`; :meth:`health_snapshot` is the
        rich readiness/depth surface)."""
        err = getattr(self, "_background_error", None)
        if err is not None:
            raise RuntimeError("serving loop died in the background") from err

    def drain(self, timeout_s: float = 30.0) -> None:
        """Graceful shutdown, distinct from the hard :meth:`stop`: stop
        CLAIMING new requests, finish every in-flight batch, flush all
        results, then write the terminal ``health.json`` state. A drained
        server has answered everything it ever claimed — zero shutdown
        errors. Called on a foreground :meth:`run` (e.g. from the SIGTERM
        handler) it just flags the loop, which unwinds and finalizes
        itself."""
        self._draining.set()
        if self._loop_running and self._thread is None:
            return  # foreground run(): the loop finalizes itself
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
            if t.is_alive():
                raise RuntimeError(
                    f"drain did not complete within {timeout_s}s "
                    f"({self._in_flight} requests still in flight)")
            self._thread = None
        self._shutdown_pool()
        if self._terminal_state is None:
            self._terminal_state = "drained"
        self._write_health()
        self.check_health()

    def stop(self) -> None:
        """Hard stop: the loop exits as fast as it can; displaced in-flight
        work is answered with explicit shutdown errors (never silently
        dropped). Use :meth:`drain` for deploys."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            if self._thread.is_alive():
                # a wedged backend (claim blocked on a dead connection) is as
                # dead as a crashed one — don't report a clean shutdown
                self._thread = None
                raise RuntimeError(
                    "serving loop did not shut down within 10s (queue "
                    "backend wedged?); thread leaked")
            self._thread = None
        self._shutdown_pool()
        if self._terminal_state is None:
            self._terminal_state = "stopped"
        self._write_health()
        self.check_health()


def main() -> None:
    """CLI entry (the ``cluster-serving-start`` role, packaged as
    ``zoo-serving``): read a YAML config, write a pidfile, serve. SIGTERM
    drains (deploy-friendly: finish in-flight, flush, terminal health);
    SIGINT stops hard."""
    import signal
    import sys

    cfg_path = sys.argv[1] if len(sys.argv) > 1 else "config.yaml"
    cfg = ServingConfig.from_yaml(cfg_path)
    # construct (model load, queue init) BEFORE writing the pidfile so a
    # startup failure can't leave a stale pidfile for a supervisor to kill
    # an unrelated reused pid with
    serving = ClusterServing(cfg)
    signal.signal(signal.SIGTERM, lambda *_: serving.drain())
    signal.signal(signal.SIGINT, lambda *_: serving.stop())
    pidfile = os.environ.get("ZOO_SERVING_PIDFILE", "/tmp/zoo_serving.pid")
    try:
        with open(pidfile, "w") as f:
            f.write(str(os.getpid()))
        serving.run()
    finally:
        try:
            with open(pidfile) as f:
                if f.read().strip() == str(os.getpid()):
                    os.remove(pidfile)
        except OSError:
            pass
