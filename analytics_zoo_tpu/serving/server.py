"""Serving engine (reference ``serving/ClusterServing.scala:45``): the loop
is claim micro-batch → decode base64 images → preprocess to the model shape
→ batched ``InferenceModel.doPredict`` → top-N postprocess → result
write-back, with a pending-queue trim guard and throughput summaries
(``:312-331``). One process per host; the TPU executes the batched forward,
threads only move bytes."""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..inference.inference_model import InferenceModel
from .config import ServingConfig
from .queues import QueueBackend, decode_image, make_queue

logger = logging.getLogger("analytics_zoo_tpu.serving")


def top_n(probs: np.ndarray, n: int) -> List[Dict[str, float]]:
    """Per-record topN (class, prob) filter (reference
    ``PostProcessing.scala``)."""
    idx = np.argsort(-probs)[:n]
    return [{"class": int(i), "prob": float(probs[i])} for i in idx]


class ClusterServing:
    def __init__(self, config: ServingConfig,
                 model: Optional[InferenceModel] = None,
                 queue: Optional[QueueBackend] = None):
        self.config = config
        self.queue = queue if queue is not None else make_queue(config.data_src)
        self.model = model if model is not None else self._load_model()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.records_served = 0
        self._writer = None
        if config.log_dir:
            from ..utils.tensorboard import SummaryWriter
            self._writer = SummaryWriter(
                os.path.join(config.log_dir, "serving"))

    def _load_model(self) -> InferenceModel:
        cfg = self.config
        im = InferenceModel(concurrent_num=cfg.concurrent_num)
        if cfg.model_type == "zoo":
            im.load_zoo(cfg.model_path)
        elif cfg.model_type == "savedmodel":
            im.load_savedmodel(cfg.model_path)
        elif cfg.model_type == "torch":
            im.load_torch(cfg.model_path)
        elif cfg.model_type == "onnx":
            im.load_onnx(cfg.model_path)
        elif cfg.model_type == "caffe":
            h, w, c = cfg.image_shape
            im.load_caffe(cfg.model_path, cfg.model_weight_path or None,
                          input_shape=(c, h, w))
        else:
            raise ValueError(f"unknown model_type {cfg.model_type}")
        if cfg.quantize:
            im.quantize(cfg.quantize)
        return im

    # -- record prep ----------------------------------------------------------

    def _prepare(self, record: Dict[str, Any]) -> np.ndarray:
        cfg = self.config
        if "image" in record:  # base64-encoded image bytes
            img = decode_image(record["image"])
            h, w = cfg.image_shape[0], cfg.image_shape[1]
            if img.shape[:2] != (h, w):
                import cv2
                img = cv2.resize(img, (w, h))
            # uint8 wire applies to IMAGES only (pixels are uint8 by nature)
            dtype = np.uint8 if cfg.input_dtype == "uint8" else np.float32
            return np.asarray(img, dtype)
        if "tensor" in record:  # raw numeric payload: always float32 — a
            # uint8 cast would silently truncate/wrap client floats
            return np.asarray(record["tensor"], np.float32)
        raise ValueError(f"record has neither image nor tensor: "
                         f"{sorted(record)}")

    # -- the serve loop -------------------------------------------------------

    def serve_once(self) -> int:
        """One micro-batch; returns number of records served."""
        cfg = self.config
        dropped = self.queue.trim(cfg.max_pending)
        if dropped:
            logger.warning("backpressure: dropped %d oldest requests", dropped)
        deadline = time.time() + cfg.batch_wait_ms / 1000.0
        batch: List = []
        while len(batch) < cfg.batch_size and time.time() < deadline:
            got = self.queue.claim_batch(cfg.batch_size - len(batch))
            if got:
                batch.extend(got)
            elif not batch:
                return 0  # nothing pending at all
            else:
                time.sleep(0.001)
        if not batch:
            return 0
        uris, arrays, errors = [], [], []
        for uri, rec in batch:
            try:
                arrays.append(self._prepare(rec))
                uris.append(uri)
            except Exception as e:  # undecodable record → error result
                errors.append((uri, str(e)))
        for uri, msg in errors:
            self.queue.put_result(uri, {"error": msg})
        if arrays:
            x = np.stack(arrays)
            start = time.perf_counter()
            probs = np.asarray(self.model.predict(x))
            elapsed = time.perf_counter() - start
            for uri, p in zip(uris, probs):
                p = np.asarray(p).reshape(-1)
                if cfg.filter_top_n:
                    self.queue.put_result(uri, {"topN": top_n(
                        p, cfg.filter_top_n)})
                else:
                    self.queue.put_result(uri, {"value": p.tolist()})
            self.records_served += len(uris)
            if self._writer is not None:
                self._writer.add_scalar("Serving Throughput",
                                        len(uris) / max(elapsed, 1e-9),
                                        self.records_served)
                self._writer.add_scalar("Total Records Number",
                                        self.records_served,
                                        self.records_served)
        return len(batch)

    def run(self, poll_interval_s: float = 0.005) -> None:
        logger.info("serving started (src=%s batch=%d)",
                    self.config.data_src, self.config.batch_size)
        while not self._stop.is_set():
            if self.serve_once() == 0:
                time.sleep(poll_interval_s)
        if self._writer is not None:
            self._writer.flush()

    def start(self) -> "ClusterServing":
        """Run the loop in a background thread (the spark-submit long-running
        job role)."""
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


def main() -> None:
    """CLI entry (the ``cluster-serving-start`` role, packaged as
    ``zoo-serving``): read a YAML config, write a pidfile, serve."""
    import signal
    import sys

    cfg_path = sys.argv[1] if len(sys.argv) > 1 else "config.yaml"
    cfg = ServingConfig.from_yaml(cfg_path)
    # construct (model load, queue init) BEFORE writing the pidfile so a
    # startup failure can't leave a stale pidfile for a supervisor to kill
    # an unrelated reused pid with
    serving = ClusterServing(cfg)
    signal.signal(signal.SIGTERM, lambda *_: serving.stop())
    signal.signal(signal.SIGINT, lambda *_: serving.stop())
    pidfile = os.environ.get("ZOO_SERVING_PIDFILE", "/tmp/zoo_serving.pid")
    try:
        with open(pidfile, "w") as f:
            f.write(str(os.getpid()))
        serving.run()
    finally:
        try:
            with open(pidfile) as f:
                if f.read().strip() == str(os.getpid()):
                    os.remove(pidfile)
        except OSError:
            pass
