"""Serving engine (reference ``serving/ClusterServing.scala:45``): the loop
is claim micro-batch → decode base64 images → preprocess to the model shape
→ batched ``InferenceModel.doPredict`` → top-N postprocess → result
write-back, with throughput summaries (``:312-331``). One process per host;
the TPU executes the batched forward, threads only move bytes.

Request-lifecycle SLO layer (the Tail-at-Scale/Clipper machinery the
reference leaves to the operator): the invariant is that **every claimed
request receives exactly one terminal result — a value or an explicit
error — no matter what fails**. Deadlines are checked at claim, after
decode, and before dispatch (expired work answers ``deadline exceeded``
instead of burning device time); overload sheds the oldest requests with
explicit shed errors instead of silent trims; SIGTERM drains (finish
in-flight, flush, terminal ``health.json``) instead of dropping; and
``reload_model`` hot-swaps the model off the serve path with a canary
predict and rollback. ``health_snapshot()`` is the deep-health surface
(queue depth, claim age, in-flight, p50/p99, shed/expired/error counters)
supervisors consume as a dict or as the periodically-written
``config.health_path`` file."""
from __future__ import annotations

import itertools
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..common import faults, file_io
from ..common import metrics as _metrics
from ..common import profiler as _profiler
from ..common.config import global_config
from ..common.utils import time_it, wall_clock
from ..inference.inference_model import InferenceModel
from ..ops import alerts as ops_alerts
from ..ops import events as ops_events
from ..ops import incident as ops_incident
from ..utils import trace as _trace
from .config import ServingConfig
from .queues import QueueBackend, decode_image, make_queue

logger = logging.getLogger("analytics_zoo_tpu.serving")

#: canonical terminal error texts (clients match on these)
SHED_ERROR = "shed: queue overloaded"
PAGE_SHED_ERROR = "shed: kv page pool exhausted"
DEADLINE_ERROR = "deadline exceeded"
SHUTDOWN_ERROR = "serving shut down before this request completed"

#: SLO telemetry in the shared registry (common/metrics.py). Every family
#: is labeled by server instance so two servers in one process (tests, the
#: multi-server spool) keep separate series; ``health_snapshot()`` is a
#: per-instance view of these.
_M_COUNTERS = {
    "shed": _metrics.counter(
        "serving.shed_total", "Requests shed by admission control.",
        labels=("server",)),
    "expired": _metrics.counter(
        "serving.expired_total", "Requests answered with deadline errors.",
        labels=("server",)),
    "errors": _metrics.counter(
        "serving.error_total",
        "Requests answered with non-deadline error results.",
        labels=("server",)),
    "claim_faults": _metrics.counter(
        "serving.claim_fault_total", "Transient claim-stage failures.",
        labels=("server",)),
    "reloads": _metrics.counter(
        "serving.reload_total", "Successful hot model reloads.",
        labels=("server",)),
    "reload_failures": _metrics.counter(
        "serving.reload_failure_total",
        "Model reloads that failed and rolled back.", labels=("server",)),
}
_M_RECORDS = _metrics.counter(
    "serving.records_total", "Records answered with prediction values.",
    labels=("server",))
_M_LATENCY = _metrics.histogram(
    "serving.request_latency_seconds",
    "Enqueue-to-terminal-result latency (client-stamped enqueue_t).",
    labels=("server",))
_M_QUEUE_DEPTH = _metrics.gauge(
    "serving.queue_depth", "Pending requests in the claim queue.",
    labels=("server",))
_M_IN_FLIGHT = _metrics.gauge(
    "serving.in_flight", "Claimed requests without a terminal result yet.",
    labels=("server",))
_M_CLAIM_AGE = _metrics.gauge(
    "serving.claim_age_seconds", "Seconds since the last successful claim.",
    labels=("server",))
#: generative (continuous-batching) serving telemetry
_M_TTFT = _metrics.histogram(
    "serving.ttft_seconds",
    "Enqueue-to-first-token latency of generative streams.",
    labels=("server",))
_M_TOKENS = _metrics.counter(
    "serving.tokens_total",
    "Tokens decoded across all generative streams.", labels=("server",))
_M_SLOTS = _metrics.gauge(
    "serving.slots_occupied",
    "Decode slots currently holding an active stream.", labels=("server",))
#: paged KV engine + speculative decoding telemetry
_M_PAGES_FREE = _metrics.gauge(
    "serving.kv_pages_free",
    "Allocatable pages remaining in the paged KV pool (0 = joins shed).",
    labels=("server",))
_M_PAGE_EVICT = _metrics.counter(
    "serving.kv_page_evictions_total",
    "KV pages returned to the pool by stream retirement.",
    labels=("server",))
_M_SPEC_ACCEPT = _metrics.gauge(
    "serving.spec_accept_ratio",
    "Mean fraction of draft tokens accepted in the last verify round.",
    labels=("server",))
_M_BROWNOUT = _metrics.gauge(
    "serving.brownout_level",
    "Current brownout degradation rung: 0=normal, 1=coarse streaming/wide "
    "batch window, 2=half token budget, 3=quarter token budget "
    "(docs/serving.md 'Overload survival').", labels=("server",))

_instance_ids = itertools.count()

#: ops-plane event types (docs/observability.md "Ops plane") — one event
#: per state transition, replayed by the incident correlator
_E_BROWNOUT = ops_events.event_type(
    "serving.brownout_rung",
    "Brownout ladder rung change (level_from/level_to, pressure).")
_E_SHED = ops_events.event_type(
    "serving.shed",
    "Admission control shed the oldest requests (count, allowed depth).")
_E_RELOAD = ops_events.event_type(
    "serving.reload",
    "Hot model reload landed (ok=true, version) or rolled back "
    "(ok=false).")
_E_LIFECYCLE = ops_events.event_type(
    "serving.lifecycle",
    "Server reached a terminal lifecycle state "
    "(state=drained|stopped|crashed).")


class _Brownout:
    """Hysteretic brownout ladder (docs/serving.md "Overload survival").

    A feedback loop over the server's own pressure signal — queue fill
    against the shed-allowed depth, and KV-page scarcity for paged
    generative servers. ``tick(pressure)`` steps DOWN one rung whenever
    pressure exceeds ``serving.brownout_high`` and back UP one rung only
    after ``serving.brownout_hold_ticks`` consecutive ticks below
    ``serving.brownout_low`` — asymmetric on purpose: degrade fast,
    recover cautiously, never oscillate across a noisy boundary.

    The rungs trade answer *quality* for answer *existence*:

    - **L1** coarsens stream partials (4x ``stream_interval``) and widens
      the one-shot micro-batch window (2x ``batch_wait_ms``) — fewer
      queue writes and fuller batches at a small latency cost.
    - **L2** additionally caps new streams' ``max_new_tokens`` at
      2 x ``serving.brownout_token_frac`` of the configured budget and
      widens the batch window to 4x.
    - **L3** tightens the cap to ``serving.brownout_token_frac``.

    Speculative depth and int8 paged KV are BUILD-TIME levers (the step
    program and pool dtype are compiled/allocated at ``__init__``): an
    operator browning out a fleet applies them via config + rolling
    ``reload_model``, not live (see the docs table)."""

    MAX_LEVEL = 3
    #: batch-window multiplier per rung (one-shot micro-batching)
    _WINDOW = (1, 2, 4, 4)
    #: stream-partial stride multiplier per rung (generative)
    _STRIDE = (1, 4, 4, 4)

    def __init__(self, label: str = ""):
        cfg = global_config()
        self.high = float(cfg.get("serving.brownout_high"))
        self.low = float(cfg.get("serving.brownout_low"))
        self.hold_ticks = int(cfg.get("serving.brownout_hold_ticks"))
        self.token_frac = float(cfg.get("serving.brownout_token_frac"))
        self.label = label
        self.level = 0
        self._calm = 0

    def tick(self, pressure: float) -> int:
        prev = self.level
        if pressure > self.high:
            self._calm = 0
            if self.level < self.MAX_LEVEL:
                self.level += 1
        elif pressure < self.low:
            self._calm += 1
            if self._calm >= self.hold_ticks and self.level > 0:
                self.level -= 1
                self._calm = 0
        else:
            self._calm = 0
        if self.level != prev:
            _E_BROWNOUT.emit(label=self.label, level_from=prev,
                             level_to=self.level,
                             pressure=round(float(pressure), 4))
        return self.level

    def token_cap(self, budget: int) -> int:
        """Effective per-stream token budget at the current rung."""
        if self.level < 2:
            return budget
        frac = self.token_frac * (2.0 if self.level == 2 else 1.0)
        return max(1, min(budget, int(round(budget * frac))))

    def batch_window_ms(self, base_ms: float) -> float:
        return base_ms * self._WINDOW[self.level]

    def stream_stride(self, base: int) -> int:
        return base * self._STRIDE[self.level] if base > 0 else base


def _model_version_of(path: Optional[str]) -> str:
    """Version label for a servable path: its basename (snapshot export
    dirs are named by version), or ``inline-0`` for models handed over
    as live objects with no path to name them by."""
    base = os.path.basename(str(path or "").rstrip("/"))
    return base or "inline-0"


class ModelReloadError(RuntimeError):
    """``reload_model`` failed; the PREVIOUS model is still serving."""


def top_n(probs: np.ndarray, n: int) -> List[Dict[str, float]]:
    """Per-record topN (class, prob) filter (reference
    ``PostProcessing.scala``)."""
    idx = np.argsort(-probs)[:n]
    return [{"class": int(i), "prob": float(probs[i])} for i in idx]


class ClusterServing:
    #: min seconds between shed passes — a shed scans the backlog, and
    #: re-scanning every 5ms claim poll would double the spool listings
    #: (expensive on remote spools) for no added protection
    SHED_INTERVAL_S = 0.05

    def __init__(self, config: ServingConfig,
                 model: Optional[InferenceModel] = None,
                 queue: Optional[QueueBackend] = None):
        self.config = config
        self.queue = queue if queue is not None else make_queue(config.data_src)
        self.model = model if model is not None else self._load_model()
        # which snapshot is live: stamped here and on every successful
        # reload_model — the promotion canary verifies it via health
        self.model_version = _model_version_of(
            config.model_path if (model is None or config.model_path)
            else None)
        self._inline_versions = itertools.count(1)
        # compile warmth before traffic: the first claimed micro-batch must
        # hit an already-compiled program, not eat a multi-second XLA
        # compile while clients poll (InferenceModel.compile_counts proves
        # it — tests assert no NEW compile on the first request)
        self.prewarmed = self._prewarm_model()
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pool = None
        self.records_served = 0
        self.device_seconds = 0.0  # dispatch→fetch time across batches
        # -- SLO bookkeeping --------------------------------------------------
        # counters/latency/gauges live in the process-global metrics
        # registry, one label per server instance (health_snapshot() and
        # the .counters property are views of it)
        self.metrics_label = f"srv{next(_instance_ids)}"
        self._m = {key: fam.labels(server=self.metrics_label)
                   for key, fam in _M_COUNTERS.items()}
        self._m_records = _M_RECORDS.labels(server=self.metrics_label)
        self._m_latency = _M_LATENCY.labels(server=self.metrics_label)
        self._m_depth = _M_QUEUE_DEPTH.labels(server=self.metrics_label)
        self._m_in_flight = _M_IN_FLIGHT.labels(server=self.metrics_label)
        self._m_claim_age = _M_CLAIM_AGE.labels(server=self.metrics_label)
        self._m_brownout = _M_BROWNOUT.labels(server=self.metrics_label)
        self._brownout = _Brownout(self.metrics_label)
        self._counter_lock = threading.Lock()
        self._in_flight = 0  # claimed, no terminal result yet
        #: uri -> (enqueue_t, trace_id) — latency base + flow-chain id
        self._meta: Dict[str, Tuple[float, Optional[int]]] = {}
        self._ewma_record_s = 0.0  # smoothed device seconds per record
        self._last_claim_m: Optional[float] = None  # monotonic
        self._last_health_m = -1e18
        self._last_shed_m = -1e18
        self._claim_fail_streak = 0
        self._loop_running = False
        self._terminal_state: Optional[str] = None
        self._reload_lock = threading.Lock()
        self._writer = None
        if config.log_dir:
            from ..utils.tensorboard import SummaryWriter
            self._writer = SummaryWriter(
                os.path.join(config.log_dir, "serving"))

    def _load_model(self, cfg: Optional[ServingConfig] = None
                    ) -> InferenceModel:
        cfg = cfg if cfg is not None else self.config
        im = InferenceModel(concurrent_num=cfg.concurrent_num)
        if cfg.model_type == "zoo":
            im.load_zoo(cfg.model_path)
        elif cfg.model_type == "savedmodel":
            im.load_savedmodel(cfg.model_path)
        elif cfg.model_type == "torch":
            im.load_torch(cfg.model_path)
        elif cfg.model_type == "onnx":
            im.load_onnx(cfg.model_path)
        elif cfg.model_type == "caffe":
            h, w, c = cfg.image_shape
            im.load_caffe(cfg.model_path, cfg.model_weight_path or None,
                          input_shape=(c, h, w))
        else:
            raise ValueError(f"unknown model_type {cfg.model_type}")
        if cfg.quantize:
            im.quantize(cfg.quantize)
        return im

    def _example_batch(self) -> np.ndarray:
        """A zeros batch shaped like ``_prepare``'s output: image records
        decode to ``image_shape`` arrays (uint8 or float32 per
        ``input_dtype``), tensor records are always float32."""
        cfg = self.config
        dtype = np.uint8 if cfg.input_dtype == "uint8" else np.float32
        return np.zeros((cfg.batch_size,) + tuple(cfg.image_shape), dtype)

    def _prewarm_model(self, model: Optional[InferenceModel] = None) -> bool:
        """AOT-compile the configured ``batch_size`` bucket at startup.
        A model whose forward rejects a zeros batch just logs and compiles
        lazily."""
        model = model if model is not None else self.model
        if not getattr(model, "prewarm", None):
            return False
        try:
            model.prewarm(self._example_batch(),
                          buckets=(self.config.batch_size,))
            return True
        except Exception:
            logger.exception(
                "startup prewarm failed; the first request at each shape "
                "bucket will pay the compile instead")
            return False

    # -- record prep ----------------------------------------------------------

    def _prepare(self, record: Dict[str, Any]) -> np.ndarray:
        # chaos site: a faulty decode must become THIS record's error
        # result (the _decode future handler), never kill the claim loop
        faults.inject("serving.decode")
        cfg = self.config
        if "image" in record:  # base64-encoded image bytes
            img = decode_image(record["image"])
            h, w = cfg.image_shape[0], cfg.image_shape[1]
            if img.shape[:2] != (h, w):
                import cv2
                img = cv2.resize(img, (w, h))
            # uint8 wire applies to IMAGES only (pixels are uint8 by nature)
            dtype = np.uint8 if cfg.input_dtype == "uint8" else np.float32
            return np.asarray(img, dtype)
        if "tensor" in record:  # raw numeric payload: always float32 — a
            # uint8 cast would silently truncate/wrap client floats
            return np.asarray(record["tensor"], np.float32)
        raise ValueError(f"record has neither image nor tensor: "
                         f"{sorted(record)}")

    def _decode_pool(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=self.config.decode_threads,
                thread_name_prefix="zoo-serving-decode")
        return self._pool

    def _shutdown_pool(self) -> None:
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=True)

    # -- SLO bookkeeping ------------------------------------------------------

    @property
    def counters(self) -> Dict[str, int]:
        """Instance view of the registry-backed SLO counters (same keys the
        old hand-rolled dict had, so supervisors/tests read it unchanged)."""
        return {key: int(c.value()) for key, c in self._m.items()}

    def _count(self, key: str, n: int = 1) -> None:
        self._m[key].inc(n)
        if key in ("shed", "expired"):
            # first SLO breach can arm a jax.profiler capture window
            # (profile.capture_on_breach) — cheap no-op otherwise
            _profiler.on_slo_breach(key)

    def _flow_uris(self, uris: List[str], stage: str) -> None:
        """Stamp one flow-chain point per uri (no-op unless a trace
        session is active — the lookup cost stays off the hot path)."""
        if not _trace.tracing():
            return
        with self._counter_lock:
            ids = [self._meta.get(u, (0.0, None))[1] for u in uris]
        for flow_id in ids:
            _trace.flow_point(flow_id, stage, "t")

    def _expiry(self, rec: Dict[str, Any]) -> Optional[float]:
        """Absolute wall-clock expiry for a record, or None when it has no
        deadline. Wall clock is deliberate: ``enqueue_t`` is stamped by the
        CLIENT process and the wall is the only clock two processes share;
        every purely-local interval in this file uses ``time.monotonic()``."""
        deadline_ms = rec.get("deadline_ms") or self.config.default_deadline_ms
        if not deadline_ms:
            return None
        t0 = rec.get("enqueue_t")
        base = float(t0) if t0 is not None else wall_clock()
        return base + float(deadline_ms) / 1000.0

    def _post_terminal(self, uri: str, value: Dict[str, Any]) -> None:
        """Every claimed request funnels its ONE terminal result (value or
        error) through here — latency and in-flight accounting included.
        Error terminals are stamped ``retriable``: shed errors are (the
        overload may clear), deadline/validation/shutdown are not — a
        retry would burn the fleet's retry budget on a certain failure."""
        if "error" in value and "retriable" not in value:
            value = dict(value)
            value["retriable"] = value["error"] in (SHED_ERROR,
                                                    PAGE_SHED_ERROR)
        try:
            self.queue.put_result(uri, value)
        except Exception:
            logger.exception("posting result for %s failed", uri)
        with self._counter_lock:
            self._in_flight = max(0, self._in_flight - 1)
            in_flight = self._in_flight
            meta = self._meta.pop(uri, None)
        self._m_in_flight.set(in_flight)
        if meta is not None:
            t0, flow_id = meta
            self._m_latency.observe(max(wall_clock() - t0, 0.0))
            # flow terminus: the request's lifecycle chain ends here
            _trace.flow_point(flow_id, "serving.result", "f")

    def _error_batch(self, uris: List[str], message: str,
                     counter: str = "errors") -> None:
        for uri in uris:
            self._post_terminal(uri, {"error": message})
        if uris:
            self._count(counter, len(uris))

    # -- pipeline stages ------------------------------------------------------

    def _shed(self) -> None:
        """Erroring admission control (replaces the silent trim): every
        dropped request gets an explicit shed error result. Two knobs:
        ``max_pending`` caps absolute depth; ``shed_wait_ms`` caps the
        ESTIMATED WAIT of the queue tail (depth x smoothed per-record
        service time) so a slow model sheds earlier than a fast one."""
        now = time.monotonic()
        if now - self._last_shed_m < self.SHED_INTERVAL_S:
            return
        self._last_shed_m = now
        cfg = self.config
        allowed = cfg.max_pending
        if cfg.shed_wait_ms:
            with self._counter_lock:
                per_record_s = self._ewma_record_s
            if per_record_s > 0:
                allowed = min(allowed, max(
                    cfg.batch_size,
                    int(cfg.shed_wait_ms / 1000.0 / per_record_s)))
        try:
            dropped = self.queue.shed(allowed, reason=SHED_ERROR)
        except OSError as e:
            logger.warning("shed pass failed (transient): %r", e)
            return
        # brownout feedback rides the shed cadence: queue fill against the
        # shed-allowed depth is the pressure signal (docs/serving.md)
        try:
            pending = self.queue.pending_count()
        except Exception:
            pending = None
        fill = (pending / float(max(allowed, 1))
                if pending is not None else 0.0)
        self._m_brownout.set(self._brownout.tick(fill))
        if dropped:
            self._count("shed", len(dropped))
            _E_SHED.emit(label=self.metrics_label, count=len(dropped),
                         allowed=allowed)
            logger.warning(
                "overload: shed %d oldest requests with error results "
                "(allowed depth %d)", len(dropped), allowed)

    def _claim(self) -> List[Tuple[str, Dict[str, Any]]]:
        """Claim up to one micro-batch: shed first, then fill the batch
        within the ``batch_wait_ms`` window on the MONOTONIC clock (a
        wall-clock step must not warp the batch window). A transient
        claim failure (flaky backend, injected ``serving.claim`` fault) is
        absorbed and retried; ``claim_retries`` consecutive failures
        surface the backend as dead."""
        cfg = self.config
        self._shed()
        # brownout L1+: widen the micro-batch window — fuller batches
        # amortize dispatch overhead exactly when the queue is deepest
        wait_ms = self._brownout.batch_window_ms(cfg.batch_wait_ms)
        deadline = time.monotonic() + wait_ms / 1000.0
        batch: List[Tuple[str, Dict[str, Any]]] = []
        while len(batch) < cfg.batch_size and time.monotonic() < deadline:
            try:
                # chaos site: a flaky queue backend must be retried, not
                # kill the serve loop
                faults.inject("serving.claim")
                got = self.queue.claim_batch(cfg.batch_size - len(batch))
                self._claim_fail_streak = 0
            except OSError as e:
                self._count("claim_faults")
                self._claim_fail_streak += 1
                if self._claim_fail_streak > cfg.claim_retries:
                    raise  # dead backend, not a flaky one: surface it
                logger.warning("transient claim failure (%d/%d): %r",
                               self._claim_fail_streak, cfg.claim_retries, e)
                # full-jitter backoff on the fail streak: N servers that
                # all saw the same queue hiccup must not re-claim in
                # lockstep (the retry-discipline lint polices this shape)
                time.sleep(np.random.uniform(
                    0.0, 0.002 * (2 ** min(self._claim_fail_streak, 6))))
                continue
            if got:
                self._last_claim_m = time.monotonic()
                batch.extend(got)
            elif not batch:
                break  # nothing pending at all
            else:
                time.sleep(0.001)
        if batch:
            now = wall_clock()
            with self._counter_lock:
                self._in_flight += len(batch)
                in_flight = self._in_flight
                for uri, rec in batch:
                    self._meta[uri] = (float(rec.get("enqueue_t") or now),
                                       rec.get("trace_id"))
            self._m_in_flight.set(in_flight)
            if _trace.tracing():
                for uri, rec in batch:
                    _trace.flow_point(rec.get("trace_id"),
                                      "serving.claim", "t")
        return batch

    def _filter_expired(self, batch: List[Tuple[str, Dict[str, Any]]]
                        ) -> List[Tuple[str, Dict[str, Any]]]:
        """Deadline check at claim: already-expired records answer the
        deadline error immediately — no decode, no device time."""
        if not batch:
            return batch
        now = wall_clock()
        live, expired = [], []
        for uri, rec in batch:
            exp = self._expiry(rec)
            (expired if exp is not None and now >= exp
             else live).append((uri, rec))
        if expired:
            self._error_batch([u for u, _ in expired], DEADLINE_ERROR,
                              counter="expired")
        return live

    def _decode(self, batch: List):
        """Decode a claimed batch on the thread pool (cv2 releases the GIL);
        undecodable records become error results immediately, and records
        whose deadline expired DURING decode answer the deadline error
        instead of riding to the device."""
        uris, arrays, expiries = [], [], []
        errors, expired = [], []
        tracing = _trace.tracing()
        t_dec = time.perf_counter()
        with time_it("serving.decode_batch"):
            futures = [(uri, rec,
                        self._decode_pool().submit(self._prepare, rec))
                       for uri, rec in batch]
            for uri, rec, fut in futures:
                try:
                    arr = fut.result()
                except Exception as e:  # undecodable record → error result
                    errors.append((uri, str(e)))
                    continue
                if tracing:
                    _trace.flow_point(rec.get("trace_id"),
                                      "serving.decode", "t")
                exp = self._expiry(rec)
                if exp is not None and wall_clock() >= exp:
                    expired.append(uri)
                    continue
                uris.append(uri)
                arrays.append(arr)
                expiries.append(exp)
        _profiler.record_phase("serving", "host_input",
                               time.perf_counter() - t_dec, start=t_dec)
        for uri, msg in errors:
            self._post_terminal(uri, {"error": msg})
        if errors:
            self._count("errors", len(errors))
        self._error_batch(expired, DEADLINE_ERROR, counter="expired")
        return uris, arrays, expiries

    def _expire_before_dispatch(self, uris: List[str], x: np.ndarray,
                                expiries: List[Optional[float]]):
        """Last deadline check, right before device dispatch — queueing
        inside the pipeline must not launder expired work onto the chip."""
        now = wall_clock()
        keep = [i for i, e in enumerate(expiries) if e is None or now < e]
        if len(keep) == len(uris):
            return uris, x
        kept = set(keep)
        self._error_batch([u for i, u in enumerate(uris) if i not in kept],
                          DEADLINE_ERROR, counter="expired")
        if not keep:
            return [], x[:0]
        return [uris[i] for i in keep], x[keep]

    def _dispatch(self, x: np.ndarray):
        """Async device dispatch for one decoded batch. Single choke point
        for the ``serving.predict`` chaos site: callers catch any failure
        and post per-uri error results so one bad batch cannot take the
        loop (or its batch's clients) down with it."""
        faults.inject("serving.predict")
        t_d = time.perf_counter()
        with time_it("serving.dispatch_batch"):
            handle = self.model.predict_async(x)
        _profiler.record_phase("serving", "dispatch",
                               time.perf_counter() - t_d, start=t_d)
        return handle

    def _writeback(self, uris: List[str], probs: np.ndarray,
                   device_elapsed: float) -> None:
        # chaos site: a failed writeback must error its batch and keep the
        # server draining (the writeback thread's per-batch catch)
        faults.inject("serving.writeback")
        cfg = self.config
        with time_it("serving.writeback_batch"):
            for uri, p in zip(uris, probs):
                p = np.asarray(p).reshape(-1)
                if cfg.filter_top_n:
                    self._post_terminal(uri,
                                        {"topN": top_n(p, cfg.filter_top_n)})
                else:
                    self._post_terminal(uri, {"value": p.tolist()})
        self._m_records.inc(len(uris))
        self.records_served += len(uris)
        self.device_seconds += device_elapsed
        if uris:
            per = device_elapsed / len(uris)
            with self._counter_lock:
                self._ewma_record_s = (
                    per if self._ewma_record_s == 0.0
                    else 0.8 * self._ewma_record_s + 0.2 * per)
        if self._writer is not None:
            self._writer.add_scalar("Serving Throughput",
                                    len(uris) / max(device_elapsed, 1e-9),
                                    self.records_served)
            self._writer.add_scalar("Total Records Number",
                                    self.records_served, self.records_served)

    def _force_sentinel(self, q) -> None:
        """Land a ``None`` sentinel on a possibly-full queue. Any real
        in-flight item displaced to make room was already CLAIMED from the
        spool — its requests get error results rather than vanishing (the
        client would otherwise poll to its timeout)."""
        import queue as pyqueue
        while True:
            try:
                q.put(None, timeout=0.2)
                return
            except pyqueue.Full:
                try:
                    item = q.get_nowait()
                except pyqueue.Empty:
                    continue
                if item is None:
                    continue
                self._error_batch(list(item[0]), SHUTDOWN_ERROR)

    # -- deep health ----------------------------------------------------------

    def health_snapshot(self) -> Dict[str, Any]:
        """Structured deep-health snapshot: lifecycle state, queue depth,
        last-claim age, in-flight count, p50/p99 terminal latency, and the
        shed/expired/error counters. Supervisors consume the same dict as
        the periodically-written ``config.health_path`` file; tests consume
        it directly. (``check_health()`` remains the narrow liveness probe
        that re-raises a crashed background loop.)

        This is a per-instance VIEW of the shared metrics registry
        (``common.metrics.metrics_snapshot()``): the counters and the
        latency histogram live there, scrapable as Prometheus text via the
        ``metrics.prom`` file written next to ``health.json``. On an empty
        latency window ``p50``/``p99`` are ``null`` — never a fake
        ``0.0`` (see docs/observability.md)."""
        with self._counter_lock:
            in_flight = self._in_flight
        counters = self.counters

        def _pct(p: float) -> Optional[float]:
            v = self._m_latency.percentile(p)
            return None if v is None else round(v * 1e3, 3)

        err = getattr(self, "_background_error", None)
        if self._terminal_state is not None:
            state = self._terminal_state
        elif err is not None:
            state = "crashed"
        elif self._draining.is_set():
            state = "draining"
        elif self._loop_running or (self._thread is not None
                                    and self._thread.is_alive()):
            state = "running"
        else:
            state = "idle"
        try:
            pending = self.queue.pending_count()
        except Exception:
            pending = None
        now_m = time.monotonic()
        claim_age = (round(now_m - self._last_claim_m, 3)
                     if self._last_claim_m is not None else None)
        # refresh the point-in-time gauges on the same cadence the
        # snapshot is taken (scrapers read them from metrics.prom)
        if pending is not None:
            self._m_depth.set(pending)
        self._m_in_flight.set(in_flight)
        if claim_age is not None:
            self._m_claim_age.set(claim_age)
        with self._counter_lock:
            ewma = self._ewma_record_s
        return {
            "state": state,
            "time": wall_clock(),
            "queue_pending": pending,
            "in_flight": in_flight,
            "records_served": self.records_served,
            "device_seconds": round(self.device_seconds, 4),
            "service_time_s_ewma": (round(ewma, 6) if ewma > 0 else None),
            "brownout_level": self._brownout.level,
            "last_claim_age_s": claim_age,
            "latency_ms": {"p50": _pct(0.50), "p99": _pct(0.99),
                           "window": self._m_latency.count()},
            "counters": counters,
            "prewarmed": self.prewarmed,
            "model_version": self.model_version,
            "alerts": sorted(ops_alerts.active_alerts()),
            "incident": ops_incident.last_incident(),
            "error": repr(err) if err is not None else None,
        }

    def _write_health(self) -> None:
        path = self.config.health_path
        if not path:
            return
        # health cadence doubles as the profiler's slow tick: refresh the
        # HBM/RSS/build-info gauges so they land in THIS metrics.prom, and
        # close any elapsed time-bounded capture window (a quiet queue sees
        # no step boundaries)
        try:
            _profiler.sample_memory()
            _profiler.maybe_stop_capture()
        except Exception:
            logger.debug("profiler health tick failed", exc_info=True)
        tmp = path + ".tmp"
        try:
            with file_io.fopen(tmp, "w") as f:
                f.write(json.dumps(self.health_snapshot()))
            file_io.replace(tmp, path)  # atomic: readers never see a tear
        except OSError:
            logger.warning("health write to %s failed", path)
        # Prometheus exposition rides the same cadence: metrics.prom next
        # to health.json, for a node-exporter textfile collector / sidecar
        sep = "/" if "/" in path or "://" in path else os.sep
        prom = path.rsplit(sep, 1)[0] + sep + "metrics.prom" \
            if sep in path else "metrics.prom"
        tmp = prom + ".tmp"
        try:
            with file_io.fopen(tmp, "w") as f:
                f.write(_metrics.expose_text())
            file_io.replace(tmp, prom)
        except OSError:
            logger.warning("metrics write to %s failed", prom)

    def _maybe_write_health(self) -> None:
        if not self.config.health_path:
            return
        now = time.monotonic()
        if now - self._last_health_m >= self.config.health_interval_s:
            self._last_health_m = now
            self._write_health()

    # -- hot model reload -----------------------------------------------------

    def reload_model(self, model_path: Optional[str] = None, *,
                     model: Optional[InferenceModel] = None,
                     model_type: Optional[str] = None,
                     version: Optional[str] = None) -> InferenceModel:
        """Hot-swap the serving model with canary + rollback. The candidate
        loads and prewarms OFF the serve path (the old model keeps serving
        the whole time), canary-predicts one synthetic batch, and only then
        swaps in — a single attribute store, atomic under the GIL, so no
        request is ever dropped or misrouted: in-flight batches hold a
        reference to whichever model dispatched them. ANY failure (load,
        prewarm, canary, injected ``serving.reload`` chaos) leaves the old
        model serving and raises :class:`ModelReloadError`."""
        with self._reload_lock:
            old = self.model
            cfg = self.config
            try:
                # chaos site: a reload that dies anywhere must roll back
                faults.inject("serving.reload")
                if model is None:
                    if model_path is None:
                        raise ValueError(
                            "reload_model needs model_path= or model=")
                    import dataclasses
                    model = self._load_model(dataclasses.replace(
                        cfg, model_path=model_path,
                        model_type=model_type or cfg.model_type))
                # prewarm + canary off the serve path: the swap only
                # happens once the candidate has proven it can answer
                self._prewarm_model(model)
                example = self._example_batch()
                canary = model.predict(example)
                import jax
                leaves = jax.tree_util.tree_leaves(canary)
                if not leaves:
                    raise ValueError("canary predict returned no outputs")
                for leaf in leaves:
                    a = np.asarray(leaf)
                    if a.shape[0] != cfg.batch_size:
                        raise ValueError(
                            f"canary predict returned leading dim "
                            f"{a.shape[0]} for a batch of {cfg.batch_size}")
                    if np.issubdtype(a.dtype, np.floating) \
                            and not np.isfinite(a).all():
                        raise ValueError(
                            "canary predict produced non-finite values")
                self.model = model  # atomic swap: next dispatch uses it
                if model_path is not None:
                    cfg.model_path = model_path
                    if model_type:
                        cfg.model_type = model_type
                # stamp only on success: a failed reload leaves both the
                # old model AND its version label live
                if version is not None:
                    self.model_version = version
                elif model_path is not None:
                    self.model_version = _model_version_of(model_path)
                else:
                    self.model_version = \
                        f"inline-{next(self._inline_versions)}"
                self._count("reloads")
                _E_RELOAD.emit(label=self.metrics_label, ok=True,
                               version=self.model_version)
                logger.info("model reloaded%s",
                            f" from {model_path}" if model_path else "")
                return model
            except Exception as e:
                self.model = old  # rollback (no-op unless a partial swap)
                self._count("reload_failures")
                _E_RELOAD.emit(label=self.metrics_label, ok=False,
                               version=self.model_version)
                logger.exception(
                    "model reload failed; previous model still serving")
                raise ModelReloadError(
                    f"model reload failed ({e!r}); previous model still "
                    f"serving") from e

    # -- the serve loop -------------------------------------------------------

    def serve_once(self) -> int:
        """One synchronous micro-batch (claim → decode → predict →
        writeback); returns the number of records claimed — every one of
        them receives a terminal result (value, deadline error, decode
        error, or predict error) before this returns. ``run()`` pipelines
        these stages — this method is the single-step form for tests and
        manual driving."""
        batch = self._claim()
        self._maybe_write_health()
        if not batch:
            return 0
        claimed = len(batch)
        uris, arrays, expiries = self._decode(self._filter_expired(batch))
        if arrays:
            x = np.stack(arrays)
            uris, x = self._expire_before_dispatch(uris, x, expiries)
            if uris:
                start = time.perf_counter()
                try:
                    self._flow_uris(uris, "serving.dispatch")
                    fetch = self._dispatch(x)
                    probs = np.asarray(fetch())
                    self._writeback(uris, probs,
                                    time.perf_counter() - start)
                except Exception as e:
                    logger.exception("predict/writeback failed for %d "
                                     "records", len(uris))
                    self._error_batch(uris, repr(e))
        return claimed

    def run(self, poll_interval_s: float = 0.005) -> None:
        """Pipelined serve loop: a claim+decode thread feeds the dispatch
        stage, and a writeback thread drains device results — batch N+1
        decodes on host threads while batch N runs on the device and batch
        N-1's results upload (the reference runs decode serially inside the
        structured-streaming micro-batch, ``ClusterServing.scala:160-259``;
        overlapping the stages is what keeps a fast chip fed)."""
        import queue as pyqueue

        logger.info("serving started (src=%s batch=%d)",
                    self.config.data_src, self.config.batch_size)
        ops_alerts.ensure_default()  # no-op unless ops.enabled
        self._terminal_state = None
        self._loop_running = True
        # a fresh loop gets an immediate admission pass: a backlog that
        # piled up while the server was down must shed BEFORE it is
        # claimed, not ride through because the previous loop's shed
        # stamp is still inside the interval gate
        self._last_shed_m = -1e18
        decoded_q: "pyqueue.Queue" = pyqueue.Queue(maxsize=2)
        fetch_q: "pyqueue.Queue" = pyqueue.Queue(maxsize=2)
        errors: List[BaseException] = []
        dead = threading.Event()  # any stage died — unblock everyone

        def _put(q: "pyqueue.Queue", item) -> bool:
            """Bounded put that can never wedge the pipeline: gives up when
            the loop is stopping or a peer stage has died. Monotonic-clock
            stall accounting — wall steps must not mask a wedged stage."""
            start = time.monotonic()
            while not dead.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except pyqueue.Full:
                    if time.monotonic() - start > 30:
                        logger.warning(
                            "pipeline stage blocked handing off a batch "
                            "for %.0fs", time.monotonic() - start)
                        start = time.monotonic()
                    continue
            return False

        def decoder() -> None:
            try:
                while not self._stop.is_set() and not dead.is_set():
                    if self._draining.is_set():
                        return  # drain: stop CLAIMING; sentinel flushes
                    self._maybe_write_health()
                    batch = self._filter_expired(self._claim())
                    if not batch:
                        time.sleep(poll_interval_s)
                        continue
                    uris, arrays, expiries = self._decode(batch)
                    if arrays and not _put(decoded_q,
                                           (uris, np.stack(arrays),
                                            expiries)):
                        self._error_batch(uris, SHUTDOWN_ERROR)
                        return
            except BaseException as e:  # pragma: no cover - surfaced below
                errors.append(e)
                dead.set()
            finally:
                self._force_sentinel(decoded_q)

        def writeback() -> None:
            while True:
                item = fetch_q.get()
                if item is None:
                    return
                uris, fetch = item
                try:
                    t0 = time.perf_counter()
                    probs = fetch()  # blocks on the device fetch only
                    elapsed = time.perf_counter() - t0
                    # device execute + transfer both resolve inside fetch()
                    # on the async path; attribute the blocked time there
                    _profiler.record_phase("serving", "fetch", elapsed,
                                           start=t0)
                    self._writeback(uris, np.asarray(probs), elapsed)
                except BaseException as e:
                    # one failed batch must not wedge the server: record
                    # error results and keep draining
                    logger.exception("writeback failed for %d records",
                                     len(uris))
                    self._error_batch(list(uris), repr(e))

        threads = [threading.Thread(target=decoder, daemon=True,
                                    name="zoo-serving-claim"),
                   threading.Thread(target=writeback, daemon=True,
                                    name="zoo-serving-writeback")]
        for t in threads:
            t.start()
        try:
            while True:
                item = decoded_q.get()
                if item is None:
                    break
                uris, x, expiries = item
                uris, x = self._expire_before_dispatch(uris, x, expiries)
                if not uris:
                    continue
                # async dispatch: the device computes while the NEXT batch
                # decodes and the PREVIOUS batch's fetch+writeback runs
                try:
                    self._flow_uris(uris, "serving.dispatch")
                    fetch = self._dispatch(x)
                except Exception as e:
                    logger.exception("dispatch failed for %d records",
                                     len(uris))
                    self._error_batch(uris, repr(e))
                    continue
                if not _put(fetch_q, (uris, fetch)):
                    self._error_batch(uris, SHUTDOWN_ERROR)
                    break
        finally:
            drained = (self._draining.is_set() and not dead.is_set()
                       and not errors)
            self._stop.set()
            dead.set()
            self._force_sentinel(fetch_q)
            for t in threads:
                t.join(timeout=10)
            self._shutdown_pool()
            self._loop_running = False
            self._terminal_state = ("crashed" if errors
                                    else "drained" if drained else "stopped")
            _E_LIFECYCLE.emit(label=self.metrics_label,
                              state=self._terminal_state)
            self._write_health()
        if errors:
            raise errors[0]
        if self._writer is not None:
            self._writer.flush()

    def start(self) -> "ClusterServing":
        """Run the loop in a background thread (the spark-submit long-running
        job role). A crash in the loop is captured and re-raised from
        :meth:`stop` / :meth:`check_health` — a dead queue backend must not
        kill the server silently."""
        ops_alerts.ensure_default()  # no-op unless ops.enabled
        self._stop.clear()
        self._draining.clear()
        self._terminal_state = None
        self._background_error: Optional[BaseException] = None

        def _run() -> None:
            try:
                self.run()
            except BaseException as e:
                logger.exception("serving loop died")
                self._background_error = e

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()
        return self

    def check_health(self) -> None:
        """Raise the background loop's failure, if any (liveness probe for
        supervisors driving :meth:`start`; :meth:`health_snapshot` is the
        rich readiness/depth surface)."""
        err = getattr(self, "_background_error", None)
        if err is not None:
            raise RuntimeError("serving loop died in the background") from err

    def drain(self, timeout_s: float = 30.0) -> None:
        """Graceful shutdown, distinct from the hard :meth:`stop`: stop
        CLAIMING new requests, finish every in-flight batch, flush all
        results, then write the terminal ``health.json`` state. A drained
        server has answered everything it ever claimed — zero shutdown
        errors. Called on a foreground :meth:`run` (e.g. from the SIGTERM
        handler) it just flags the loop, which unwinds and finalizes
        itself."""
        self._draining.set()
        if self._loop_running and self._thread is None:
            return  # foreground run(): the loop finalizes itself
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
            if t.is_alive():
                raise RuntimeError(
                    f"drain did not complete within {timeout_s}s "
                    f"({self._in_flight} requests still in flight)")
            self._thread = None
        self._shutdown_pool()
        if self._terminal_state is None:
            self._terminal_state = "drained"
            _E_LIFECYCLE.emit(label=self.metrics_label, state="drained")
        self._write_health()
        self.check_health()

    def stop(self) -> None:
        """Hard stop: the loop exits as fast as it can; displaced in-flight
        work is answered with explicit shutdown errors (never silently
        dropped). Use :meth:`drain` for deploys."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            if self._thread.is_alive():
                # a wedged backend (claim blocked on a dead connection) is as
                # dead as a crashed one — don't report a clean shutdown
                self._thread = None
                raise RuntimeError(
                    "serving loop did not shut down within 10s (queue "
                    "backend wedged?); thread leaked")
            self._thread = None
        self._shutdown_pool()
        if self._terminal_state is None:
            self._terminal_state = "stopped"
            _E_LIFECYCLE.emit(label=self.metrics_label, state="stopped")
        self._write_health()
        self.check_health()


class GenerativeServing:
    """Token-level continuous batching for ``TransformerLM`` generation.

    ``ClusterServing`` is one-request-one-predict: a full decode occupies
    the device while other requests queue, so utilization collapses under
    load. This scheduler keeps ``config.slots`` streams RESIDENT in one
    slot-batched KV cache (``ops/decode.py``) and advances all of them
    with ONE fused device step per token; requests join free slots and
    finished/expired streams are evicted EVERY step, not between requests.
    All device shapes are static — slot indices, lengths and occupancy are
    data — so the step program compiles once and prefill compiles once per
    length bucket (``capture/lm.py PREFILL_BUCKETS``).

    The PR 4 SLO invariant carries over per token: every claimed request
    gets exactly one terminal result (``{"value": tokens}`` or an error),
    deadlines are checked every step (an expired stream is evicted
    mid-flight with a deadline error), overload sheds by the estimated
    queue wait at the CURRENT smoothed tokens/s, and ``drain()`` stops
    admitting but finishes in-flight streams. Partial results
    (``{"stream": [...], "done": false}``) are idempotent overwrites of
    the same result record — they are progress, not terminals — and
    ``OutputQueue.stream()`` turns them into a client-side generator.

    Decode parity: slot-batched streams are BIT-IDENTICAL to serial
    ``TransformerLM.generate()`` runs — both paths share the bucketed
    prefill (``prefill_kv``), the ``make_logit_filter`` sampling chain and
    the ``cached_attention``-mirroring ``slot_attention`` arithmetic
    (tests/test_generative_serving.py holds the line).

    Paged KV engine (``config.kv_pages``): per-slot ``max_len``
    rectangles are replaced by a global page pool + per-slot page tables
    (``ops/decode.py`` paged ops) — HBM is paid per ALLOCATED page, not
    per slot, so concurrency scales with actual stream lengths. Joins
    allocate pages (shedding with ``PAGE_SHED_ERROR`` on exhaustion — the
    ``serving.page_alloc`` fault site), retirement refcounts them back.
    ``register_prefix()`` shares a common prompt's pages across streams
    with copy-on-write tails; ``config.kv_int8`` stores the pool in int8
    with delayed scaling; ``config.spec_k`` + a ``draft_lm`` switches the
    step to speculative draft/verify rounds (greedy-only,
    token-identical to serial greedy). Paged greedy/sampled decode stays
    bit-identical to the contiguous engine
    (tests/test_paged_serving.py)."""

    SHED_INTERVAL_S = 0.05

    def __init__(self, config: ServingConfig, lm,
                 queue: Optional[QueueBackend] = None, draft_lm=None):
        import jax
        import jax.numpy as jnp

        from ..ops.decode import (init_slot_state, make_logit_filter,
                                  page_copy, page_table_clear,
                                  page_table_set, paged_gather, paged_insert,
                                  slot_evict, slot_insert, slot_join,
                                  spec_accept_greedy)

        self.config = config
        self.lm = lm
        self.model_version = _model_version_of(config.model_path)
        self.queue = (queue if queue is not None
                      else make_queue(config.data_src))
        if config.slots < 1:
            raise ValueError(f"slots must be >= 1, got {config.slots}")
        self.slots = int(config.slots)
        self._sampling = (config.temperature is not None
                          or config.top_k is not None
                          or config.top_p is not None)
        filter_logits = None
        if self._sampling:
            filter_logits = make_logit_filter(
                config.temperature if config.temperature is not None
                else 1.0, config.top_k, config.top_p)
        # -- paged KV engine + speculative decoding flags -----------------
        self._paged = config.kv_pages is not None
        self._spec = draft_lm is not None and config.spec_k > 0
        if self._spec and not self._paged:
            raise ValueError("speculative decoding rides the paged KV "
                             "engine: set kv_pages alongside spec_k")
        if self._spec and self._sampling:
            raise ValueError("speculative decoding in the scheduler is "
                             "greedy-only (per-request sampled accept is a "
                             "follow-up); unset temperature/top_k/top_p")
        self._spec_k = int(config.spec_k) if self._spec else 0
        # -- device state: per-block slot caches + ONE shared occupancy ---
        self._params = lm.params
        if self._paged:
            pl = int(config.kv_page_len)
            num_pages = int(config.kv_pages)
            if pl < 1 or (pl & (pl - 1)) or pl > 16:
                raise ValueError(f"kv_page_len must be a power of two "
                                 f"<= 16 (divides every prefill bucket), "
                                 f"got {pl}")
            if lm.max_len % pl:
                raise ValueError(f"kv_page_len {pl} must divide the LM's "
                                 f"max_len {lm.max_len}")
            if num_pages < 2:
                raise ValueError(f"kv_pages must be >= 2 (page 0 is the "
                                 f"null page), got {num_pages}")
            self.page_len = pl
            self.num_pages = num_pages
            # table rows carry slack columns for the transient spec_k
            # overshoot past max_len (those writes land on real pages the
            # stream owns only within its allocation; beyond it, the null
            # page absorbs them)
            self._table_w = (lm.max_len + self._spec_k + pl - 1) // pl
            self._caches = lm.init_paged_caches(num_pages, pl,
                                                int8=config.kv_int8)
            self._kv_shard = int(getattr(config, "kv_shard", 1) or 1)
            if self._kv_shard > 1:
                from ..ops.decode import shard_paged_pool
                # page axis spread over kv_shard devices; decode gathers
                # each stream's pages to the compute device, so tokens
                # stay bit-identical to the single-device pool
                self._caches = shard_paged_pool(self._caches,
                                                self._kv_shard)
            self._table = jnp.zeros((self.slots, self._table_w), jnp.int32)
            # host-side allocator: free-page stack, refcounts, and the
            # pages each slot holds (shared prefix pages appear in many)
            self._free_pages = self._initial_free_pages(num_pages,
                                                        self._kv_shard)
            self._page_refs = np.zeros(num_pages, np.int64)
            self._slot_pages: List[List[int]] = [[] for _ in
                                                 range(self.slots)]
            self._prefixes: List[Dict[str, Any]] = []
        else:
            self._kv_shard = 1
            self._caches = lm.init_slot_caches(self.slots)
        self._state = init_slot_state(self.slots)
        if self._spec:
            self.draft_lm = draft_lm
            self._dparams = draft_lm.params
            self._dcaches = draft_lm.init_slot_caches(self.slots)
            if draft_lm.max_len < lm.max_len + self._spec_k:
                raise ValueError(
                    f"draft max_len={draft_lm.max_len} must cover "
                    f"max_len={lm.max_len} + spec_k={self._spec_k} "
                    f"transient draft positions")

        def _select(logits, keys):
            if filter_logits is None:
                return jnp.argmax(logits, axis=-1)
            filt = filter_logits(logits.astype(jnp.float32))
            return jax.vmap(lambda kk, row: jax.random.categorical(
                kk, row, axis=-1))(keys, filt)

        def _step(params, tokens, keys, state, caches):
            logits, caches = lm.slot_step(params, tokens, state["length"],
                                          caches)
            nxt = _select(logits, keys)
            # lengths advance ONCE, after every block attended with the
            # pre-increment value (write-then-attend, as serial decode)
            state = {"length": (state["length"]
                                + state["active"].astype(jnp.int32)),
                     "active": state["active"]}
            return nxt, state, caches

        def _step_paged(params, tokens, keys, state, table, caches):
            logits, caches = lm.paged_slot_step(params, tokens,
                                                state["length"], table,
                                                caches)
            nxt = _select(logits, keys)
            state = {"length": (state["length"]
                                + state["active"].astype(jnp.int32)),
                     "active": state["active"]}
            return nxt, state, caches

        spec_k = self._spec_k

        def _step_spec(params, dparams, tokens, state, table, caches,
                       dcaches):
            """One speculative round: spec_k chained draft steps, one
            batched verify through the paged cache, longest-agreeing-run
            accept. Lengths advance by each slot's ACCEPTED count."""
            lengths = state["length"]
            active = state["active"]

            def draft_body(carry, _):
                tok, ln, dc = carry
                dlogits, dc = draft_lm.slot_step(dparams, tok, ln, dc)
                nd = jnp.argmax(dlogits, axis=-1).astype(tok.dtype)
                return (nd, ln + active.astype(jnp.int32), dc), nd

            (_, _, dcaches), drafts = jax.lax.scan(
                draft_body, (tokens, lengths, dcaches), None, length=spec_k)
            drafts = jnp.swapaxes(drafts, 0, 1)          # [S, k]
            block = jnp.concatenate([tokens[:, None], drafts], axis=1)
            tlogits, caches = lm.verify_step(params, block, lengths, table,
                                             caches)
            emitted, n = spec_accept_greedy(drafts, tlogits)
            n = n * active.astype(n.dtype)
            state = {"length": lengths + n, "active": active}
            return emitted, n, state, caches, dcaches

        def _prefill(params, padded, caches, state, slot, length):
            kvs = lm.prefill_kv(params, padded)
            caches = [slot_insert(c, slot, k[0], v[0])
                      for c, (k, v) in zip(caches, kvs)]
            return caches, slot_join(state, slot, length)

        def _prefill_paged(params, padded, caches, state, table, row, slot,
                           length):
            kvs = lm.prefill_kv(params, padded)
            caches = [paged_insert(c, row, k[0], v[0])
                      for c, (k, v) in zip(caches, kvs)]
            return (caches, slot_join(state, slot, length),
                    page_table_set(table, slot, row))

        def _prefill_spec(params, dparams, padded, dpadded, caches, dcaches,
                          state, table, row, slot, length):
            kvs = lm.prefill_kv(params, padded)
            caches = [paged_insert(c, row, k[0], v[0])
                      for c, (k, v) in zip(caches, kvs)]
            dkvs = draft_lm.prefill_kv(dparams, dpadded)
            dcaches = [slot_insert(c, slot, k[0], v[0])
                       for c, (k, v) in zip(dcaches, dkvs)]
            return (caches, dcaches, slot_join(state, slot, length),
                    page_table_set(table, slot, row))

        def _prefill_suffix(params, padded, caches, state, table, row, prow,
                            slot, length, plen):
            # gather the shared prefix K/V (refcounted pages, prefilled
            # once) and run only the divergent suffix forward
            pref = [paged_gather(c, prow[None]) for c in caches]
            pref = [(k[:, :, :plen], v[:, :, :plen]) for k, v in pref]
            kvs = lm.prefill_kv_suffix(params, padded, pref, plen)
            caches = [paged_insert(c, row, k[0], v[0], start=plen)
                      for c, (k, v) in zip(caches, kvs)]
            return (caches, slot_join(state, slot, length),
                    page_table_set(table, slot, row))

        def _prefill_prefix(params, padded, caches, row):
            kvs = lm.prefill_kv(params, padded)
            return [paged_insert(c, row, k[0], v[0])
                    for c, (k, v) in zip(caches, kvs)]

        def _copy_pages(caches, src, dst):
            return [page_copy(c, src, dst) for c in caches]

        if self._spec:
            self._step_fn = jax.jit(_step_spec)
            self._prefill_spec_fn = jax.jit(_prefill_spec)
        elif self._paged:
            self._step_fn = jax.jit(_step_paged)
        else:
            self._step_fn = jax.jit(_step)
        if self._paged:
            self._prefill_paged_fn = jax.jit(_prefill_paged)
            self._prefill_suffix_fn = jax.jit(_prefill_suffix,
                                              static_argnames=("plen",))
            self._prefill_prefix_fn = jax.jit(_prefill_prefix)
            self._copy_fn = jax.jit(_copy_pages)
            self._table_set_fn = jax.jit(page_table_set)
            self._table_clear_fn = jax.jit(page_table_clear)
        else:
            self._prefill_fn = jax.jit(_prefill)  # one compile per bucket
        self._join_fn = jax.jit(slot_join)    # T==1 prompts: no prefill
        self._evict_fn = jax.jit(slot_evict)
        self._split = lambda seed, n: np.asarray(
            jax.random.split(jax.random.PRNGKey(seed), n))
        # -- host-side per-slot bookkeeping (scheduler-thread private) ----
        s = self.slots
        self._uri: List[Optional[str]] = [None] * s
        self._tokens: List[Optional[List[int]]] = [None] * s
        self._budget = [0] * s
        self._expires: List[Optional[float]] = [None] * s
        self._enqueue_t = [0.0] * s
        self._first_t: List[Optional[float]] = [None] * s
        self._streamed = [0] * s
        self._keys: List[Optional[np.ndarray]] = [None] * s
        self._next_tokens = np.zeros(s, np.int32)
        self._active_host = np.zeros(s, bool)
        # continuation-on-failover bookkeeping: the original prompt, seed
        # and deadline ride along so a drain handoff can re-enqueue the
        # stream with its accumulated prefix (docs/fleet.md)
        self._prompt: List[Optional[List[int]]] = [None] * s
        self._seed: List[Optional[int]] = [None] * s
        self._deadline_ms: List[Optional[float]] = [None] * s
        # -- SLO bookkeeping (same registry families as ClusterServing) ---
        self.metrics_label = f"srv{next(_instance_ids)}"
        self._m = {key: fam.labels(server=self.metrics_label)
                   for key, fam in _M_COUNTERS.items()}
        self._m_records = _M_RECORDS.labels(server=self.metrics_label)
        self._m_latency = _M_LATENCY.labels(server=self.metrics_label)
        self._m_depth = _M_QUEUE_DEPTH.labels(server=self.metrics_label)
        self._m_in_flight = _M_IN_FLIGHT.labels(server=self.metrics_label)
        self._m_claim_age = _M_CLAIM_AGE.labels(server=self.metrics_label)
        self._m_ttft = _M_TTFT.labels(server=self.metrics_label)
        self._m_tokens = _M_TOKENS.labels(server=self.metrics_label)
        self._m_slots = _M_SLOTS.labels(server=self.metrics_label)
        self._m_pages_free = _M_PAGES_FREE.labels(server=self.metrics_label)
        self._m_page_evict = _M_PAGE_EVICT.labels(server=self.metrics_label)
        self._m_spec_accept = _M_SPEC_ACCEPT.labels(
            server=self.metrics_label)
        self._m_brownout = _M_BROWNOUT.labels(server=self.metrics_label)
        self._brownout = _Brownout(self.metrics_label)
        if self._paged:
            self._m_pages_free.set(len(self._free_pages))
        self._counter_lock = threading.Lock()
        self._in_flight = 0
        self._meta: Dict[str, Tuple[float, Optional[int]]] = {}
        self._ewma_token_s = 0.0  # smoothed wall seconds per decoded token
        self._last_claim_m: Optional[float] = None
        self._last_health_m = -1e18
        self._last_shed_m = -1e18
        self._claim_fail_streak = 0
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._handoff_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._loop_running = False
        self._terminal_state: Optional[str] = None

    # -- terminal accounting (ClusterServing's exactly-one-terminal rule) --

    @property
    def counters(self) -> Dict[str, int]:
        return {key: int(c.value()) for key, c in self._m.items()}

    def _count(self, key: str, n: int = 1) -> None:
        self._m[key].inc(n)
        if key in ("shed", "expired"):
            _profiler.on_slo_breach(key)

    def _expiry(self, rec: Dict[str, Any]) -> Optional[float]:
        deadline_ms = (rec.get("deadline_ms")
                       or self.config.default_deadline_ms)
        if not deadline_ms:
            return None
        t0 = rec.get("enqueue_t")
        base = float(t0) if t0 is not None else wall_clock()
        return base + float(deadline_ms) / 1000.0

    def _post_terminal(self, uri: str, value: Dict[str, Any]) -> None:
        """Every claimed request funnels its ONE terminal result (value or
        error) through here — partial ``stream`` records do NOT. Error
        terminals carry ``retriable`` (shed yes; deadline/validation/
        shutdown no) for the client's retry-budget discipline."""
        if "error" in value and "retriable" not in value:
            value = dict(value)
            value["retriable"] = value["error"] in (SHED_ERROR,
                                                    PAGE_SHED_ERROR)
        try:
            self.queue.put_result(uri, value)
        except Exception:
            logger.exception("posting result for %s failed", uri)
        with self._counter_lock:
            self._in_flight = max(0, self._in_flight - 1)
            in_flight = self._in_flight
            meta = self._meta.pop(uri, None)
        self._m_in_flight.set(in_flight)
        if meta is not None:
            t0, flow_id = meta
            self._m_latency.observe(max(wall_clock() - t0, 0.0))
            _trace.flow_point(flow_id, "serving.result", "f")

    def _retire(self, slot: int, value: Dict[str, Any],
                counter: Optional[str] = None) -> None:
        """Terminal-result a slot's stream and free its host bookkeeping
        (the DEVICE evict is the caller's one vectorized ``_evict_slots``)."""
        self._post_terminal(self._uri[slot], value)
        if counter is not None:
            self._count(counter)
        elif "value" in value:
            self._m_records.inc()
        if self._paged:
            self._release_pages(slot)
        self._clear_slot(slot)

    def _clear_slot(self, slot: int) -> None:
        self._uri[slot] = None
        self._tokens[slot] = None
        self._keys[slot] = None
        self._expires[slot] = None
        self._first_t[slot] = None
        self._streamed[slot] = 0
        self._prompt[slot] = None
        self._seed[slot] = None
        self._deadline_ms[slot] = None
        self._active_host[slot] = False

    def _abandon(self, slot: int) -> None:
        """Release a slot WITHOUT posting a terminal — the stream's one
        terminal will be posted by whichever instance adopts its re-routed
        continuation. Only :meth:`handoff` may do this: every other exit
        path funnels through :meth:`_retire`."""
        with self._counter_lock:
            self._in_flight = max(0, self._in_flight - 1)
            in_flight = self._in_flight
            self._meta.pop(self._uri[slot], None)
        self._m_in_flight.set(in_flight)
        if self._paged:
            self._release_pages(slot)
        self._clear_slot(slot)

    @staticmethod
    def _initial_free_pages(num_pages: int, kv_shard: int):
        """Allocatable pages ``1..num_pages-1`` as a pop()-able stack.
        Sharded pools interleave the stack round-robin across page shards
        so consecutive allocations land on different devices — without it
        a cold pool would fill shard 0 solid before touching shard 1,
        hot-spotting its HBM and its gather traffic."""
        if kv_shard <= 1:
            return list(range(num_pages - 1, 0, -1))
        per = num_pages // kv_shard  # pages per shard (validated to divide)
        order = sorted(range(1, num_pages),
                       key=lambda p: (p % per, p // per))
        return order[::-1]  # .pop() walks shards round-robin

    def _pages_free_per_shard(self):
        """Free-page count per pool shard (shard of page p: ``p // per``).
        The fleet router sizes sharded capacity by the MIN shard: an
        allocation needs a free page on whichever shard the round-robin
        stack surfaces, and a full shard stalls placement even when other
        shards have room."""
        per = self.num_pages // self._kv_shard
        counts = [0] * self._kv_shard
        for p in self._free_pages:
            counts[p // per] += 1
        return counts

    def _release_pages(self, slot: int) -> None:
        """Decrement every page the slot holds; refcount-0 pages return to
        the free stack (shared prefix pages outlive the stream via the
        registry's own reference)."""
        pages, self._slot_pages[slot] = self._slot_pages[slot], []
        freed = 0
        for p in pages:
            self._page_refs[p] -= 1
            if self._page_refs[p] == 0:
                self._free_pages.append(p)
                freed += 1
        if freed:
            self._m_page_evict.inc(freed)
        self._m_pages_free.set(len(self._free_pages))

    # -- device hot path (policed by scripts/check_hot_path_syncs.py) ------

    def _dispatch_step(self, tokens, keys):
        # chaos site: a failed fused step must error every active stream
        # (their one terminal result) and keep the scheduler serving
        faults.inject("serving.decode_step")
        t0 = time.perf_counter()
        if self._spec:
            out = self._step_fn(self._params, self._dparams, tokens,
                                self._state, self._table, self._caches,
                                self._dcaches)
        elif self._paged:
            out = self._step_fn(self._params, tokens, keys, self._state,
                                self._table, self._caches)
        else:
            out = self._step_fn(self._params, tokens, keys, self._state,
                                self._caches)
        _profiler.record_phase("serving", "dispatch",
                               time.perf_counter() - t0, start=t0)
        return out

    def _insert_request_device(self, padded, slot, length):
        self._caches, self._state = self._prefill_fn(
            self._params, padded, self._caches, self._state, slot, length)

    def _insert_request_paged(self, padded, row, slot, length):
        self._caches, self._state, self._table = self._prefill_paged_fn(
            self._params, padded, self._caches, self._state, self._table,
            row, slot, length)

    def _insert_request_spec(self, padded, dpadded, row, slot, length):
        (self._caches, self._dcaches, self._state,
         self._table) = self._prefill_spec_fn(
            self._params, self._dparams, padded, dpadded, self._caches,
            self._dcaches, self._state, self._table, row, slot, length)

    def _insert_suffix_paged(self, padded, row, prow, slot, length, plen):
        self._caches, self._state, self._table = self._prefill_suffix_fn(
            self._params, padded, self._caches, self._state, self._table,
            row, prow, slot, length, plen=plen)

    def _copy_page_device(self, src, dst):
        # copy-on-write: a private copy of a shared prefix tail page
        self._caches = self._copy_fn(self._caches, np.int32(src),
                                     np.int32(dst))

    def _evict_slots(self, mask):
        self._state = self._evict_fn(self._state, mask)
        if self._paged:
            self._table = self._table_clear_fn(self._table, mask)

    def _fetch_tokens(self, nxt) -> np.ndarray:
        # the one host sync per step, deliberately OUTSIDE the policed
        # dispatch body: everything queued ahead of it stays async
        t0 = time.perf_counter()
        out = np.asarray(nxt)
        _profiler.record_phase("serving", "fetch",
                               time.perf_counter() - t0, start=t0)
        return out

    # -- admission -----------------------------------------------------------

    def _shed(self) -> None:
        """Admission control at TOKEN granularity: a queued request waits
        for a free slot, and slots free up at ``slots / (budget x smoothed
        per-token seconds)`` streams per second — shed the backlog down to
        what answers within ``shed_wait_ms`` at the CURRENT decode rate."""
        now = time.monotonic()
        if now - self._last_shed_m < self.SHED_INTERVAL_S:
            return
        self._last_shed_m = now
        cfg = self.config
        allowed = cfg.max_pending
        # the brownout token cap shortens the estimated stream time, so a
        # browned-out server ADMITS deeper queues instead of shedding them
        eff_budget = self._brownout.token_cap(cfg.max_new_tokens)
        if cfg.shed_wait_ms and self._ewma_token_s > 0:
            stream_s = eff_budget * self._ewma_token_s
            allowed = min(allowed, max(
                self.slots,
                int(cfg.shed_wait_ms / 1000.0 / stream_s * self.slots)))
        try:
            dropped = self.queue.shed(allowed, reason=SHED_ERROR)
        except OSError as e:
            logger.warning("shed pass failed (transient): %r", e)
            return
        # brownout feedback: pressure is the max of queue fill (against
        # the shed-allowed depth) and KV-page scarcity (docs/serving.md)
        try:
            pending = self.queue.pending_count()
        except Exception:
            pending = None
        fill = (pending / float(max(allowed, 1))
                if pending is not None else 0.0)
        scarcity = 0.0
        if self._paged:
            scarcity = 1.0 - (len(self._free_pages)
                              / float(max(self.num_pages - 1, 1)))
        self._m_brownout.set(self._brownout.tick(max(fill, scarcity)))
        if dropped:
            self._count("shed", len(dropped))
            _E_SHED.emit(label=self.metrics_label, count=len(dropped),
                         allowed=allowed)
            logger.warning(
                "overload: shed %d oldest streams with error results "
                "(allowed depth %d)", len(dropped), allowed)

    # -- paged join: page allocation + shared-prefix attach ----------------

    def _match_prefix(self, prompt) -> Optional[Dict[str, Any]]:
        """Longest registered prefix that ``prompt`` strictly extends (the
        last prompt token is never prefilled, so the prompt must be longer
        than the prefix)."""
        best = None
        for pfx in self._prefixes:
            n = pfx["len"]
            if (len(prompt) > n and list(prompt[:n]) == pfx["tokens"]
                    and (best is None or n > best["len"])):
                best = pfx
        return best

    def register_prefix(self, tokens) -> int:
        """Prefill a shared prompt prefix ONCE into refcounted pool pages.
        Every later join whose prompt extends it references those pages
        (full pages shared in place; a partially-filled tail page gets a
        private copy-on-write duplicate, since the stream appends into it)
        and prefills only its divergent suffix. The registry holds a
        permanent reference, so the pages survive every stream's
        retirement. Admin-plane call — register before ``start()`` or
        between steps, not concurrently with the loop."""
        if not self._paged:
            raise RuntimeError("shared prefixes require the paged KV "
                               "engine (set kv_pages)")
        if self._spec:
            raise RuntimeError("shared prefixes are not wired into the "
                               "speculative scheduler yet (the draft "
                               "cache is contiguous)")
        from ..capture.lm import prefill_bucket
        toks = [int(x) for x in tokens]
        n = len(toks)
        if n < 1 or n >= self.lm.max_len:
            raise ValueError(f"prefix length {n} out of range for "
                             f"max_len={self.lm.max_len}")
        npages = -(-n // self.page_len)
        if len(self._free_pages) < npages:
            raise RuntimeError(
                f"kv page pool exhausted: prefix needs {npages} pages, "
                f"{len(self._free_pages)} free")
        pages = [self._free_pages.pop() for _ in range(npages)]
        for p in pages:
            self._page_refs[p] = 1  # the registry's permanent hold
        row = np.zeros(self._table_w, np.int32)
        row[:npages] = pages
        tb = prefill_bucket(n, self.lm.max_len)
        padded = np.zeros((1, tb), np.int32)
        padded[0, :n] = toks
        self._caches = self._prefill_prefix_fn(self._params, padded,
                                               self._caches, row)
        self._prefixes.append({"tokens": toks, "len": n, "pages": pages})
        self._m_pages_free.set(len(self._free_pages))
        return len(self._prefixes) - 1

    def _join_paged(self, slot: int, uri: str, prompt, t: int,
                    budget: int) -> bool:
        """Allocate pages for a validated request and prefill it into
        ``slot``. Pool exhaustion (or the armed ``serving.page_alloc``
        fault) SHEDS the request — its one terminal result is the page
        shed error — and every resident stream keeps decoding."""
        from ..capture.lm import prefill_bucket
        pl = self.page_len
        pfx = self._match_prefix(prompt) if not self._spec else None
        plen = pfx["len"] if pfx else 0
        full = plen // pl       # whole shared pages
        rem = plen % pl         # prefix tokens on the shared tail page
        fed = t - 1             # positions prefilled before decode starts
        tb = (prefill_bucket(fed - plen, self.lm.max_len)
              if fed > plen else 0)
        # highest position the stream may WRITE within its allocation:
        # bucket padding past the suffix, the decode budget, and the
        # transient spec_k overshoot all need real (owned) pages
        high = max(plen + tb, t + budget + self._spec_k)
        # bucket padding past the table width is never visible and never
        # decoded over — the null page absorbs it; no page needed
        fresh_needed = min(-(-high // pl), self._table_w) - full
        # chaos site: pool exhaustion at join → shed-or-evict, not a crash
        if (faults.inject("serving.page_alloc")
                or len(self._free_pages) < fresh_needed):
            self._post_terminal(uri, {"error": PAGE_SHED_ERROR})
            self._count("shed")
            logger.warning(
                "kv page pool exhausted: shed %s (need %d pages, %d free)",
                uri, fresh_needed, len(self._free_pages))
            return False
        fresh = [self._free_pages.pop() for _ in range(fresh_needed)]
        shared = [int(p) for p in pfx["pages"][:full]] if pfx else []
        row = np.zeros(self._table_w, np.int32)
        row[:full] = shared
        row[full:full + fresh_needed] = fresh
        for p in shared:
            self._page_refs[p] += 1
        for p in fresh:
            self._page_refs[p] = 1
        self._slot_pages[slot] = shared + fresh
        self._m_pages_free.set(len(self._free_pages))
        if pfx and rem:
            # CoW: the stream appends into logical page ``full``, which
            # still holds shared prefix tail tokens — give it a private
            # copy (fresh[0] occupies that table position)
            self._copy_page_device(pfx["pages"][full], fresh[0])
        if fed > plen:
            padded = np.zeros((1, tb), np.int32)
            padded[0, :fed - plen] = prompt[plen:fed]
            if pfx:
                prow = np.asarray(pfx["pages"], np.int32)
                self._insert_suffix_paged(padded, row, prow,
                                          np.int32(slot), np.int32(fed),
                                          plen)
            elif self._spec:
                dtb = prefill_bucket(fed, self.draft_lm.max_len)
                dpadded = np.zeros((1, dtb), np.int32)
                dpadded[0, :fed] = prompt[:fed]
                self._insert_request_spec(padded, dpadded, row,
                                          np.int32(slot), np.int32(fed))
            else:
                self._insert_request_paged(padded, row, np.int32(slot),
                                           np.int32(fed))
        else:
            # nothing to prefill (one-token prompt, or the prompt is
            # prefix + one token): join + install the table row
            self._state = self._join_fn(self._state, np.int32(slot),
                                        np.int32(fed))
            self._table = self._table_set_fn(self._table, np.int32(slot),
                                             row)
        return True

    def _join(self, slot: int, uri: str, rec: Dict[str, Any],
              now: float) -> bool:
        """Validate a claimed request and prefill it into ``slot``. Returns
        False (slot stays free) when the request terminates immediately
        (bad prompt, over-budget, already expired).

        A request carrying a ``prefix`` (tokens already decoded elsewhere
        — a re-routed stream after its server died or drained) is ADOPTED:
        ``prompt + prefix`` is re-prefilled through the same bucketed path
        and decoding resumes at position ``len(prefix)``; with an explicit
        ``seed`` the key schedule is rebuilt over the FULL original budget
        so step ``i`` uses the same key an uninterrupted stream would —
        the continuation is token-identical (docs/fleet.md)."""
        from ..capture.lm import prefill_bucket

        cfg = self.config
        prompt = rec.get("prompt")
        if not prompt:
            self._post_terminal(uri, {"error": "empty prompt"})
            self._count("errors")
            return False
        budget = int(rec.get("max_new_tokens") or cfg.max_new_tokens)
        # brownout L2/L3: new streams join with a capped budget — shorter
        # answers for everyone beat no answers for the queue tail. An
        # adopted prefix that already exceeds the cap settles immediately
        # (the prefix >= budget branch below).
        budget = self._brownout.token_cap(budget)
        prompt = [int(x) for x in prompt]
        prefix = [int(x) for x in (rec.get("prefix") or [])]
        t = len(prompt)
        if budget < 1 or t + budget > self.lm.max_len:
            self._post_terminal(uri, {
                "error": f"prompt ({t}) + max_new_tokens ({budget}) "
                         f"out of range for max_len={self.lm.max_len}"})
            self._count("errors")
            return False
        exp = self._expiry(rec)
        if exp is not None and now >= exp:
            self._post_terminal(uri, {"error": DEADLINE_ERROR})
            self._count("expired")
            return False
        if prefix and len(prefix) >= budget:
            # the dead server decoded the whole budget but never posted
            # the terminal — settle it here, nothing left to decode
            self._post_terminal(uri, {"value": prefix[:budget],
                                      "done": True})
            self._m_records.inc()
            return False
        full = prompt + prefix
        t_full = len(full)
        t0 = time.perf_counter()
        if self._paged:
            if not self._join_paged(slot, uri, full, t_full,
                                    budget - len(prefix)):
                _profiler.record_phase("serving", "host_input",
                                       time.perf_counter() - t0, start=t0)
                return False
        elif t_full > 1:
            # right-pad full[:-1] to its length bucket: the SAME compiled
            # prefill program serial generate() uses (bit-parity anchor);
            # an adopted prefix re-prefills here — the KV it rebuilds is
            # bit-identical to what the dead server's decode steps wrote
            tb = prefill_bucket(t_full - 1, self.lm.max_len)
            padded = np.zeros((1, tb), np.int32)
            padded[0, :t_full - 1] = full[:-1]
            self._insert_request_device(padded, np.int32(slot),
                                        np.int32(t_full - 1))
        else:
            self._state = self._join_fn(self._state, np.int32(slot),
                                        np.int32(0))
        _profiler.record_phase("serving", "host_input",
                               time.perf_counter() - t0, start=t0)
        self._uri[slot] = uri
        self._tokens[slot] = list(prefix)
        self._budget[slot] = budget
        self._expires[slot] = exp
        self._enqueue_t[slot] = float(rec.get("enqueue_t") or now)
        # TTFT was already observed on the original server for an adopted
        # stream — don't observe it twice
        self._first_t[slot] = now if prefix else None
        self._streamed[slot] = len(prefix)
        self._next_tokens[slot] = int(full[-1])
        self._prompt[slot] = prompt
        self._deadline_ms[slot] = rec.get("deadline_ms")
        self._seed[slot] = None
        if self._sampling:
            seed = rec.get("seed")
            if seed is None:  # fresh entropy: repeated requests differ
                seed = int(np.random.SeedSequence().entropy % (2 ** 31))
            # the FULL per-request key schedule, precomputed once: step i
            # uses key [i] — identical to serial sample_generate's
            # split(PRNGKey(seed), budget) schedule. The step index is
            # len(self._tokens[slot]), so an adopted prefix resumes the
            # schedule exactly where the dead server left off.
            self._seed[slot] = int(seed)
            self._keys[slot] = self._split(int(seed), budget)
        self._active_host[slot] = True
        return True

    def _admit(self) -> None:
        free = [i for i in range(self.slots) if not self._active_host[i]]
        if not free:
            return
        self._shed()
        try:
            got = self.queue.claim_batch(len(free))
            self._claim_fail_streak = 0
        except OSError as e:
            self._count("claim_faults")
            self._claim_fail_streak += 1
            if self._claim_fail_streak > self.config.claim_retries:
                raise  # dead backend, not a flaky one: surface it
            logger.warning("transient claim failure (%d/%d): %r",
                           self._claim_fail_streak,
                           self.config.claim_retries, e)
            return
        if not got:
            return
        self._last_claim_m = time.monotonic()
        now = wall_clock()
        with self._counter_lock:
            self._in_flight += len(got)
            in_flight = self._in_flight
            for uri, rec in got:
                self._meta[uri] = (float(rec.get("enqueue_t") or now),
                                   rec.get("trace_id"))
        self._m_in_flight.set(in_flight)
        if _trace.tracing():
            for uri, rec in got:
                _trace.flow_point(rec.get("trace_id"), "serving.claim", "t")
        for uri, rec in got:
            slot = free.pop(0)
            if not self._join(slot, uri, rec, now):
                free.insert(0, slot)

    # -- the step loop -------------------------------------------------------

    def _expire_slots(self) -> None:
        """Per-token deadline check: an expired stream is evicted
        MID-FLIGHT — its one terminal result is the deadline error (the
        partials it already streamed are not terminals)."""
        now = wall_clock()
        mask = np.zeros(self.slots, bool)
        for i in range(self.slots):
            if (self._active_host[i] and self._expires[i] is not None
                    and now >= self._expires[i]):
                mask[i] = True
                self._retire(i, {"error": DEADLINE_ERROR}, counter="expired")
        if mask.any():
            self._evict_slots(mask)

    def _fail_active(self, message: str) -> None:
        mask = np.zeros(self.slots, bool)
        for i in range(self.slots):
            if self._active_host[i]:
                mask[i] = True
                self._retire(i, {"error": message}, counter="errors")
        if mask.any():
            self._evict_slots(mask)

    def _post_tokens(self, nxt: np.ndarray) -> None:
        """Fold one step's tokens into every active stream: TTFT on the
        first token, partial results every ``stream_interval`` tokens,
        terminal value + evict on eos / budget exhaustion."""
        now = wall_clock()
        cfg = self.config
        # brownout L1+: coarser partials — every queue write the streamers
        # skip is backend bandwidth returned to terminals
        stream_stride = self._brownout.stream_stride(cfg.stream_interval)
        finished = np.zeros(self.slots, bool)
        n_tok = 0
        for i in range(self.slots):
            if not self._active_host[i]:
                continue
            tok = int(nxt[i])
            self._tokens[i].append(tok)
            self._next_tokens[i] = tok
            n_tok += 1
            if self._first_t[i] is None:
                self._first_t[i] = now
                self._m_ttft.observe(max(now - self._enqueue_t[i], 0.0))
            if (len(self._tokens[i]) >= self._budget[i]
                    or (cfg.eos_id is not None and tok == cfg.eos_id)):
                finished[i] = True
                self._retire(i, {"value": list(self._tokens[i]),
                                 "done": True})
            elif (stream_stride > 0
                  and (len(self._tokens[i]) - self._streamed[i]
                       >= stream_stride)):
                try:
                    self.queue.put_result(self._uri[i], self._partial(i))
                    self._streamed[i] = len(self._tokens[i])
                except Exception:
                    logger.exception("partial result for %s failed",
                                     self._uri[i])
        if n_tok:
            self._m_tokens.inc(n_tok)
        if finished.any():
            self._evict_slots(finished)

    def _partial(self, slot: int) -> Dict[str, Any]:
        """A stream-progress record: accumulated tokens + the sampling seed
        (when sampling). The seed is the failover handle — a router that
        adopts the stream re-enqueues ``{prefix: stream, seed: seed}`` and
        the adopting server's key schedule resumes bit-identically."""
        out: Dict[str, Any] = {"stream": list(self._tokens[slot]),
                               "done": False}
        if self._seed[slot] is not None:
            out["seed"] = self._seed[slot]
        return out

    def _post_tokens_spec(self, emitted: np.ndarray,
                          n_acc: np.ndarray) -> None:
        """Fold one speculative round's ACCEPTED tokens into every active
        stream — same TTFT/stream/terminal rules as ``_post_tokens``, but
        up to ``spec_k + 1`` tokens land per stream per round. The budget
        clamp and eos truncation are host-side; a stream they cut short is
        retired in the same pass, so the device's over-advanced length
        never feeds another step."""
        now = wall_clock()
        cfg = self.config
        # brownout L1+: coarser partials — every queue write the streamers
        # skip is backend bandwidth returned to terminals
        stream_stride = self._brownout.stream_stride(cfg.stream_interval)
        finished = np.zeros(self.slots, bool)
        n_tok = 0
        for i in range(self.slots):
            if not self._active_host[i]:
                continue
            take = min(int(n_acc[i]),
                       self._budget[i] - len(self._tokens[i]))
            toks = [int(x) for x in emitted[i, :take]]
            if cfg.eos_id is not None and cfg.eos_id in toks:
                toks = toks[:toks.index(cfg.eos_id) + 1]
            if not toks:
                continue
            self._tokens[i].extend(toks)
            self._next_tokens[i] = toks[-1]
            n_tok += len(toks)
            if self._first_t[i] is None:
                self._first_t[i] = now
                self._m_ttft.observe(max(now - self._enqueue_t[i], 0.0))
            if (len(self._tokens[i]) >= self._budget[i]
                    or (cfg.eos_id is not None and toks[-1] == cfg.eos_id)):
                finished[i] = True
                self._retire(i, {"value": list(self._tokens[i]),
                                 "done": True})
            elif (stream_stride > 0
                  and (len(self._tokens[i]) - self._streamed[i]
                       >= stream_stride)):
                try:
                    self.queue.put_result(self._uri[i], self._partial(i))
                    self._streamed[i] = len(self._tokens[i])
                except Exception:
                    logger.exception("partial result for %s failed",
                                     self._uri[i])
        if n_tok:
            self._m_tokens.inc(n_tok)
        if finished.any():
            self._evict_slots(finished)

    def serve_step(self) -> int:
        """One scheduler step: evict expired streams, admit new requests
        into free slots (shed + bucketed prefill), run ONE fused decode
        step over every occupied slot, stream/terminate per token. Returns
        the number of streams stepped — the single-step form tests and
        the bench drive directly; :meth:`run` loops it."""
        self._maybe_write_health()
        self._expire_slots()
        if not self._draining.is_set():
            self._admit()
        n_active = int(np.sum(self._active_host))
        self._m_slots.set(n_active)
        if n_active == 0:
            return 0
        tokens = np.ascontiguousarray(self._next_tokens)
        keys = np.zeros((self.slots, 2), np.uint32)
        if self._sampling:
            for i in range(self.slots):
                if self._active_host[i]:
                    keys[i] = self._keys[i][len(self._tokens[i])]
        t_step = time.perf_counter()
        try:
            if self._spec:
                emitted, n_acc, state, caches, dcaches = \
                    self._dispatch_step(tokens, keys)
                em_host = self._fetch_tokens(emitted)
                n_host = self._fetch_tokens(n_acc)
            else:
                nxt, state, caches = self._dispatch_step(tokens, keys)
                nxt_host = self._fetch_tokens(nxt)
        except Exception as e:
            logger.exception("decode step failed for %d streams", n_active)
            self._fail_active(repr(e))
            return 0
        self._state, self._caches = state, caches
        if self._spec:
            self._dcaches = dcaches
            n_emitted = int(np.sum(n_host[self._active_host]))
            per = (time.perf_counter() - t_step) / max(n_emitted, 1)
            self._ewma_token_s = (per if self._ewma_token_s == 0.0
                                  else 0.8 * self._ewma_token_s + 0.2 * per)
            self._m_spec_accept.set(float(np.mean(np.maximum(
                n_host[self._active_host] - 1, 0))) / self._spec_k)
            self._post_tokens_spec(em_host, n_host)
            return n_active
        per = (time.perf_counter() - t_step) / n_active
        self._ewma_token_s = (per if self._ewma_token_s == 0.0
                              else 0.8 * self._ewma_token_s + 0.2 * per)
        self._post_tokens(nxt_host)
        return n_active

    # -- lifecycle (mirrors ClusterServing) ----------------------------------

    def run(self, poll_interval_s: float = 0.005) -> None:
        logger.info("generative serving started (src=%s slots=%d)",
                    self.config.data_src, self.slots)
        ops_alerts.ensure_default()  # no-op unless ops.enabled
        self._terminal_state = None
        self._loop_running = True
        self._last_shed_m = -1e18
        try:
            while (not self._stop.is_set()
                   and not self._handoff_evt.is_set()):
                stepped = self.serve_step()
                if self._draining.is_set() and stepped == 0:
                    return  # drained: every in-flight stream finished
                if stepped == 0:
                    time.sleep(poll_interval_s)
        finally:
            self._loop_running = False
            if self._stop.is_set():
                self._fail_active(SHUTDOWN_ERROR)
            self._maybe_write_health()

    def start(self) -> "GenerativeServing":
        ops_alerts.ensure_default()  # no-op unless ops.enabled
        self._stop.clear()
        self._draining.clear()
        self._handoff_evt.clear()
        self._terminal_state = None
        self._background_error: Optional[BaseException] = None

        def _run() -> None:
            try:
                self.run()
            except BaseException as e:
                logger.exception("generative serving loop died")
                self._background_error = e

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()
        return self

    def check_health(self) -> None:
        err = getattr(self, "_background_error", None)
        if err is not None:
            raise RuntimeError(
                "generative serving loop died in the background") from err

    def drain(self, timeout_s: float = 30.0) -> None:
        """Stop ADMITTING, finish every in-flight stream (each runs out
        its budget / eos / deadline), then write terminal health."""
        self._draining.set()
        if self._loop_running and self._thread is None:
            return  # foreground run(): the loop finalizes itself
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
            if t.is_alive():
                raise RuntimeError(
                    f"drain did not complete within {timeout_s}s "
                    f"({int(np.sum(self._active_host))} streams active)")
            self._thread = None
        if self._terminal_state is None:
            self._terminal_state = "drained"
            _E_LIFECYCLE.emit(label=self.metrics_label, state="drained")
        self._write_health()
        self.check_health()

    def handoff(self, to_queue, timeout_s: float = 30.0) -> int:
        """Drain WITHOUT finishing locally: pause the loop and re-enqueue
        every in-flight stream onto ``to_queue`` carrying its accumulated
        token ``prefix`` (+ sampling ``seed``), so another instance adopts
        it mid-stream and continues token-identically — the fast half of
        the failover protocol (docs/fleet.md). No terminal is posted here;
        the adopting server posts the stream's ONE terminal. A stream
        whose re-enqueue fails is errored instead (never silently lost).
        Returns the number of streams handed off."""
        self._draining.set()
        self._handoff_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout_s)
            if t.is_alive():
                raise RuntimeError(
                    f"handoff: serve loop did not pause within {timeout_s}s")
            self._thread = None
        elif self._loop_running:
            # foreground run(): wait for the loop to notice the event
            deadline = time.monotonic() + timeout_s
            while self._loop_running:
                if time.monotonic() >= deadline:
                    raise RuntimeError(
                        f"handoff: serve loop did not pause within "
                        f"{timeout_s}s")
                time.sleep(0.002)
        moved = 0
        mask = np.zeros(self.slots, bool)
        for i in range(self.slots):
            if not self._active_host[i]:
                continue
            uri = self._uri[i]
            rec: Dict[str, Any] = {
                "prompt": list(self._prompt[i]),
                "prefix": list(self._tokens[i]),
                "max_new_tokens": self._budget[i],
                "enqueue_t": self._enqueue_t[i],
            }
            if self._deadline_ms[i] is not None:
                rec["deadline_ms"] = self._deadline_ms[i]
            if self._seed[i] is not None:
                rec["seed"] = self._seed[i]
            mask[i] = True
            try:
                to_queue.enqueue(uri, rec)
            except Exception:
                logger.exception("handoff enqueue for %s failed", uri)
                self._retire(i, {"error": SHUTDOWN_ERROR},
                             counter="errors")
                continue
            self._abandon(i)
            moved += 1
        if mask.any():
            self._evict_slots(mask)
        if self._terminal_state is None:
            self._terminal_state = "drained"
            _E_LIFECYCLE.emit(label=self.metrics_label, state="drained")
        self._write_health()
        self.check_health()
        return moved

    def stop(self) -> None:
        """Hard stop: active streams are answered with explicit shutdown
        errors (never silently dropped). Use :meth:`drain` for deploys."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            if self._thread.is_alive():
                self._thread = None
                raise RuntimeError(
                    "generative serving loop did not shut down within 10s "
                    "(queue backend wedged?); thread leaked")
            self._thread = None
        else:
            self._fail_active(SHUTDOWN_ERROR)
        if self._terminal_state is None:
            self._terminal_state = "stopped"
            _E_LIFECYCLE.emit(label=self.metrics_label, state="stopped")
        self._write_health()
        self.check_health()

    # -- deep health ---------------------------------------------------------

    def health_snapshot(self) -> Dict[str, Any]:
        """Generative twin of ``ClusterServing.health_snapshot``: lifecycle
        state, queue depth, slots occupied, tokens decoded, TTFT/latency
        percentiles and the SLO counters — a per-instance view of the
        shared metrics registry."""
        with self._counter_lock:
            in_flight = self._in_flight

        def _pct(fam, p: float) -> Optional[float]:
            v = fam.percentile(p)
            return None if v is None else round(v * 1e3, 3)

        err = getattr(self, "_background_error", None)
        if self._terminal_state is not None:
            state = self._terminal_state
        elif err is not None:
            state = "crashed"
        elif self._draining.is_set():
            state = "draining"
        elif self._loop_running or (self._thread is not None
                                    and self._thread.is_alive()):
            state = "running"
        else:
            state = "idle"
        try:
            pending = self.queue.pending_count()
        except Exception:
            pending = None
        if pending is not None:
            self._m_depth.set(pending)
        self._m_in_flight.set(in_flight)
        now_m = time.monotonic()
        claim_age = (round(now_m - self._last_claim_m, 3)
                     if self._last_claim_m is not None else None)
        if claim_age is not None:
            self._m_claim_age.set(claim_age)
        return {
            "state": state,
            "time": wall_clock(),
            "queue_pending": pending,
            "in_flight": in_flight,
            "slots": self.slots,
            "slots_occupied": int(np.sum(self._active_host)),
            "tokens_total": int(self._m_tokens.value()),
            "tokens_per_sec_ewma": (round(1.0 / self._ewma_token_s, 1)
                                    if self._ewma_token_s > 0 else None),
            "kv_pages_free": (len(self._free_pages) if self._paged
                              else None),
            "kv_shards": (self._kv_shard if self._paged else None),
            "kv_pages_free_min_shard": (
                min(self._pages_free_per_shard())
                if self._paged and self._kv_shard > 1 else None),
            "spec_accept_ratio": (
                round(float(self._m_spec_accept.value()), 4)
                if self._spec else None),
            "brownout_level": self._brownout.level,
            "last_claim_age_s": claim_age,
            "ttft_ms": {"p50": _pct(self._m_ttft, 0.50),
                        "p99": _pct(self._m_ttft, 0.99),
                        "window": self._m_ttft.count()},
            "latency_ms": {"p50": _pct(self._m_latency, 0.50),
                           "p99": _pct(self._m_latency, 0.99),
                           "window": self._m_latency.count()},
            "counters": self.counters,
            "model_version": self.model_version,
            "alerts": sorted(ops_alerts.active_alerts()),
            "incident": ops_incident.last_incident(),
            "error": repr(err) if err is not None else None,
        }

    def _write_health(self) -> None:
        path = self.config.health_path
        if not path:
            return
        tmp = path + ".tmp"
        try:
            with file_io.fopen(tmp, "w") as f:
                f.write(json.dumps(self.health_snapshot()))
            file_io.replace(tmp, path)
        except OSError:
            logger.warning("health write to %s failed", path)

    def _maybe_write_health(self) -> None:
        if not self.config.health_path:
            return
        now = time.monotonic()
        if now - self._last_health_m >= self.config.health_interval_s:
            self._last_health_m = now
            self._write_health()


def main() -> None:
    """CLI entry (the ``cluster-serving-start`` role, packaged as
    ``zoo-serving``): read a YAML config, write a pidfile, serve. SIGTERM
    drains (deploy-friendly: finish in-flight, flush, terminal health);
    SIGINT stops hard."""
    import signal
    import sys

    cfg_path = sys.argv[1] if len(sys.argv) > 1 else "config.yaml"
    cfg = ServingConfig.from_yaml(cfg_path)
    # construct (model load, queue init) BEFORE writing the pidfile so a
    # startup failure can't leave a stale pidfile for a supervisor to kill
    # an unrelated reused pid with
    serving = ClusterServing(cfg)
    signal.signal(signal.SIGTERM, lambda *_: serving.drain())
    signal.signal(signal.SIGINT, lambda *_: serving.stop())
    pidfile = os.environ.get("ZOO_SERVING_PIDFILE", "/tmp/zoo_serving.pid")
    try:
        with open(pidfile, "w") as f:
            f.write(str(os.getpid()))
        serving.run()
    finally:
        try:
            with open(pidfile) as f:
                if f.read().strip() == str(os.getpid()):
                    os.remove(pidfile)
        except OSError:
            pass
