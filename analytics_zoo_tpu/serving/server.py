"""Serving engine (reference ``serving/ClusterServing.scala:45``): the loop
is claim micro-batch → decode base64 images → preprocess to the model shape
→ batched ``InferenceModel.doPredict`` → top-N postprocess → result
write-back, with a pending-queue trim guard and throughput summaries
(``:312-331``). One process per host; the TPU executes the batched forward,
threads only move bytes."""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..common import faults
from ..inference.inference_model import InferenceModel
from .config import ServingConfig
from .queues import QueueBackend, decode_image, make_queue

logger = logging.getLogger("analytics_zoo_tpu.serving")


def top_n(probs: np.ndarray, n: int) -> List[Dict[str, float]]:
    """Per-record topN (class, prob) filter (reference
    ``PostProcessing.scala``)."""
    idx = np.argsort(-probs)[:n]
    return [{"class": int(i), "prob": float(probs[i])} for i in idx]


class ClusterServing:
    def __init__(self, config: ServingConfig,
                 model: Optional[InferenceModel] = None,
                 queue: Optional[QueueBackend] = None):
        self.config = config
        self.queue = queue if queue is not None else make_queue(config.data_src)
        self.model = model if model is not None else self._load_model()
        # compile warmth before traffic: the first claimed micro-batch must
        # hit an already-compiled program, not eat a multi-second XLA
        # compile while clients poll (InferenceModel.compile_counts proves
        # it — tests assert no NEW compile on the first request)
        self.prewarmed = self._prewarm_model()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pool = None
        self.records_served = 0
        self.device_seconds = 0.0  # dispatch→fetch time across batches
        self._writer = None
        if config.log_dir:
            from ..utils.tensorboard import SummaryWriter
            self._writer = SummaryWriter(
                os.path.join(config.log_dir, "serving"))

    def _load_model(self) -> InferenceModel:
        cfg = self.config
        im = InferenceModel(concurrent_num=cfg.concurrent_num)
        if cfg.model_type == "zoo":
            im.load_zoo(cfg.model_path)
        elif cfg.model_type == "savedmodel":
            im.load_savedmodel(cfg.model_path)
        elif cfg.model_type == "torch":
            im.load_torch(cfg.model_path)
        elif cfg.model_type == "onnx":
            im.load_onnx(cfg.model_path)
        elif cfg.model_type == "caffe":
            h, w, c = cfg.image_shape
            im.load_caffe(cfg.model_path, cfg.model_weight_path or None,
                          input_shape=(c, h, w))
        else:
            raise ValueError(f"unknown model_type {cfg.model_type}")
        if cfg.quantize:
            im.quantize(cfg.quantize)
        return im

    def _prewarm_model(self) -> bool:
        """AOT-compile the configured ``batch_size`` bucket at startup.
        The example batch mirrors what ``_prepare`` produces: image records
        decode to ``image_shape`` arrays (uint8 or float32 per
        ``input_dtype``), tensor records are always float32. A model whose
        forward rejects a zeros batch just logs and compiles lazily."""
        cfg = self.config
        if not getattr(self.model, "prewarm", None):
            return False
        dtype = np.uint8 if cfg.input_dtype == "uint8" else np.float32
        example = np.zeros((cfg.batch_size,) + tuple(cfg.image_shape), dtype)
        try:
            self.model.prewarm(example, buckets=(cfg.batch_size,))
            return True
        except Exception:
            logger.exception(
                "startup prewarm failed; the first request at each shape "
                "bucket will pay the compile instead")
            return False

    # -- record prep ----------------------------------------------------------

    def _prepare(self, record: Dict[str, Any]) -> np.ndarray:
        # chaos site: a faulty decode must become THIS record's error
        # result (the _decode future handler), never kill the claim loop
        faults.inject("serving.decode")
        cfg = self.config
        if "image" in record:  # base64-encoded image bytes
            img = decode_image(record["image"])
            h, w = cfg.image_shape[0], cfg.image_shape[1]
            if img.shape[:2] != (h, w):
                import cv2
                img = cv2.resize(img, (w, h))
            # uint8 wire applies to IMAGES only (pixels are uint8 by nature)
            dtype = np.uint8 if cfg.input_dtype == "uint8" else np.float32
            return np.asarray(img, dtype)
        if "tensor" in record:  # raw numeric payload: always float32 — a
            # uint8 cast would silently truncate/wrap client floats
            return np.asarray(record["tensor"], np.float32)
        raise ValueError(f"record has neither image nor tensor: "
                         f"{sorted(record)}")

    def _decode_pool(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=self.config.decode_threads,
                thread_name_prefix="zoo-serving-decode")
        return self._pool

    # -- pipeline stages ------------------------------------------------------

    def _claim(self) -> List:
        """Claim up to one micro-batch, honoring the batch-wait deadline and
        the backpressure trim guard."""
        cfg = self.config
        dropped = self.queue.trim(cfg.max_pending)
        if dropped:
            logger.warning("backpressure: dropped %d oldest requests", dropped)
        deadline = time.time() + cfg.batch_wait_ms / 1000.0
        batch: List = []
        while len(batch) < cfg.batch_size and time.time() < deadline:
            got = self.queue.claim_batch(cfg.batch_size - len(batch))
            if got:
                batch.extend(got)
            elif not batch:
                return []  # nothing pending at all
            else:
                time.sleep(0.001)
        return batch

    def _decode(self, batch: List):
        """Decode a claimed batch on the thread pool (cv2 releases the GIL);
        undecodable records become error results immediately."""
        uris, arrays, errors = [], [], []
        futures = [(uri, self._decode_pool().submit(self._prepare, rec))
                   for uri, rec in batch]
        for uri, fut in futures:
            try:
                arrays.append(fut.result())
                uris.append(uri)
            except Exception as e:  # undecodable record → error result
                errors.append((uri, str(e)))
        for uri, msg in errors:
            self.queue.put_result(uri, {"error": msg})
        return uris, arrays

    def _writeback(self, uris: List[str], probs: np.ndarray,
                   device_elapsed: float) -> None:
        # chaos site: a failed writeback must error its batch and keep the
        # server draining (the writeback thread's per-batch catch)
        faults.inject("serving.writeback")
        cfg = self.config
        for uri, p in zip(uris, probs):
            p = np.asarray(p).reshape(-1)
            if cfg.filter_top_n:
                self.queue.put_result(uri, {"topN": top_n(p, cfg.filter_top_n)})
            else:
                self.queue.put_result(uri, {"value": p.tolist()})
        self.records_served += len(uris)
        self.device_seconds += device_elapsed
        if self._writer is not None:
            self._writer.add_scalar("Serving Throughput",
                                    len(uris) / max(device_elapsed, 1e-9),
                                    self.records_served)
            self._writer.add_scalar("Total Records Number",
                                    self.records_served, self.records_served)

    def _force_sentinel(self, q) -> None:
        """Land a ``None`` sentinel on a possibly-full queue. Any real
        in-flight item displaced to make room was already CLAIMED from the
        spool — its requests get error results rather than vanishing (the
        client would otherwise poll to its timeout)."""
        import queue as pyqueue
        while True:
            try:
                q.put(None, timeout=0.2)
                return
            except pyqueue.Full:
                try:
                    item = q.get_nowait()
                except pyqueue.Empty:
                    continue
                if item is None:
                    continue
                uris = item[0]
                for uri in uris:
                    try:
                        self.queue.put_result(
                            uri, {"error": "serving shut down before this "
                                           "request completed"})
                    except Exception:
                        pass

    # -- the serve loop -------------------------------------------------------

    def serve_once(self) -> int:
        """One synchronous micro-batch (claim → decode → predict → writeback);
        returns number of records served. ``run()`` pipelines these stages —
        this method is the single-step form for tests and manual driving."""
        batch = self._claim()
        if not batch:
            return 0
        uris, arrays = self._decode(batch)
        if arrays:
            x = np.stack(arrays)
            start = time.perf_counter()
            probs = np.asarray(self.model.predict(x))
            elapsed = time.perf_counter() - start
            self._writeback(uris, probs, elapsed)
        return len(batch)

    def run(self, poll_interval_s: float = 0.005) -> None:
        """Pipelined serve loop: a claim+decode thread feeds the dispatch
        stage, and a writeback thread drains device results — batch N+1
        decodes on host threads while batch N runs on the device and batch
        N-1's results upload (the reference runs decode serially inside the
        structured-streaming micro-batch, ``ClusterServing.scala:160-259``;
        overlapping the stages is what keeps a fast chip fed)."""
        import queue as pyqueue

        logger.info("serving started (src=%s batch=%d)",
                    self.config.data_src, self.config.batch_size)
        decoded_q: "pyqueue.Queue" = pyqueue.Queue(maxsize=2)
        fetch_q: "pyqueue.Queue" = pyqueue.Queue(maxsize=2)
        errors: List[BaseException] = []
        dead = threading.Event()  # any stage died — unblock everyone

        def _put(q: "pyqueue.Queue", item) -> bool:
            """Bounded put that can never wedge the pipeline: gives up when
            the loop is stopping or a peer stage has died."""
            while not dead.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except pyqueue.Full:
                    continue
            return False

        def decoder() -> None:
            try:
                while not self._stop.is_set() and not dead.is_set():
                    batch = self._claim()
                    if not batch:
                        time.sleep(poll_interval_s)
                        continue
                    uris, arrays = self._decode(batch)
                    if arrays and not _put(decoded_q, (uris,
                                                       np.stack(arrays))):
                        return
            except BaseException as e:  # pragma: no cover - surfaced below
                errors.append(e)
                dead.set()
            finally:
                self._force_sentinel(decoded_q)

        def writeback() -> None:
            while True:
                item = fetch_q.get()
                if item is None:
                    return
                uris, fetch = item
                try:
                    t0 = time.perf_counter()
                    probs = fetch()  # blocks on the device fetch only
                    self._writeback(uris, np.asarray(probs),
                                    time.perf_counter() - t0)
                except BaseException as e:
                    # one failed batch must not wedge the server: record
                    # error results and keep draining
                    logger.exception("writeback failed for %d records",
                                     len(uris))
                    for uri in uris:
                        try:
                            self.queue.put_result(uri, {"error": repr(e)})
                        except Exception:
                            pass

        threads = [threading.Thread(target=decoder, daemon=True,
                                    name="zoo-serving-claim"),
                   threading.Thread(target=writeback, daemon=True,
                                    name="zoo-serving-writeback")]
        for t in threads:
            t.start()
        try:
            while True:
                item = decoded_q.get()
                if item is None:
                    break
                uris, x = item
                # async dispatch: the device computes while the NEXT batch
                # decodes and the PREVIOUS batch's fetch+writeback runs
                fetch = self.model.predict_async(x)
                if not _put(fetch_q, (uris, fetch)):
                    break
        finally:
            self._stop.set()
            dead.set()
            self._force_sentinel(fetch_q)
            for t in threads:
                t.join(timeout=10)
        if errors:
            raise errors[0]
        if self._writer is not None:
            self._writer.flush()

    def start(self) -> "ClusterServing":
        """Run the loop in a background thread (the spark-submit long-running
        job role). A crash in the loop is captured and re-raised from
        :meth:`stop` / :meth:`check_health` — a dead queue backend must not
        kill the server silently."""
        self._stop.clear()
        self._background_error: Optional[BaseException] = None

        def _run() -> None:
            try:
                self.run()
            except BaseException as e:
                logger.exception("serving loop died")
                self._background_error = e

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()
        return self

    def check_health(self) -> None:
        """Raise the background loop's failure, if any (liveness probe for
        supervisors driving :meth:`start`)."""
        err = getattr(self, "_background_error", None)
        if err is not None:
            raise RuntimeError("serving loop died in the background") from err

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            if self._thread.is_alive():
                # a wedged backend (claim blocked on a dead connection) is as
                # dead as a crashed one — don't report a clean shutdown
                self._thread = None
                raise RuntimeError(
                    "serving loop did not shut down within 10s (queue "
                    "backend wedged?); thread leaked")
            self._thread = None
        self.check_health()


def main() -> None:
    """CLI entry (the ``cluster-serving-start`` role, packaged as
    ``zoo-serving``): read a YAML config, write a pidfile, serve."""
    import signal
    import sys

    cfg_path = sys.argv[1] if len(sys.argv) > 1 else "config.yaml"
    cfg = ServingConfig.from_yaml(cfg_path)
    # construct (model load, queue init) BEFORE writing the pidfile so a
    # startup failure can't leave a stale pidfile for a supervisor to kill
    # an unrelated reused pid with
    serving = ClusterServing(cfg)
    signal.signal(signal.SIGTERM, lambda *_: serving.stop())
    signal.signal(signal.SIGINT, lambda *_: serving.stop())
    pidfile = os.environ.get("ZOO_SERVING_PIDFILE", "/tmp/zoo_serving.pid")
    try:
        with open(pidfile, "w") as f:
            f.write(str(os.getpid()))
        serving.run()
    finally:
        try:
            with open(pidfile) as f:
                if f.read().strip() == str(os.getpid()):
                    os.remove(pidfile)
        except OSError:
            pass
