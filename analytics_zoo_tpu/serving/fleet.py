"""Fleet tier: a telemetry-driven router/admission layer over N serving
instances (docs/fleet.md).

The reference platform scales serving by adding containers behind ONE
shared Redis queue — every server pulls blindly, so a hot instance and an
idle one look identical to the work, and a dead server's claimed requests
sit in its PEL until a lease expires. This module makes the *fleet* the
unit of design instead:

- **Per-instance queues.** Each server gets its own request spool
  (:func:`instance_queue` — a FileQueue under ``<root>/inst/<name>`` whose
  results land in the FRONT spool, so clients poll one place no matter
  which instance answers). Clients keep enqueueing to the front; the
  router is the only consumer of the front spool.
- **Telemetry-driven placement.** The router reads each instance's
  ``health.json`` (queue depth, in-flight, EWMA service time, per-instance
  p99, ``slots_occupied``, ``kv_pages_free``, claim age) and places every
  request on the instance with the lowest *estimated completion time* —
  least-loaded for one-shot predicts, slot/page-aware for generative
  joins. The scoring body (:func:`_score_instances`) is pure vectorized
  numpy over the instance axis and is policed by the zoolint hot-path
  pass: no host syncs, no per-request Python loops over instance gauges.
- **Shed before enqueue.** When no instance can meet a request's deadline
  the router answers ``FLEET_SHED_ERROR`` immediately — the client learns
  in one poll instead of burning queue time to a deadline error.
- **Continuation-on-failover.** A stale health file (``health_age_s`` past
  ``fleet.stale_after_s``) marks an instance dead: its unstarted spool is
  reclaimed, and every stream the router had assigned to it is re-enqueued
  carrying the accumulated token ``prefix`` (+ sampling ``seed``) from its
  last partial result. The adopting server re-prefills ``prompt + prefix``
  through the same bucketed prefill path serial ``generate()`` uses and
  continues the stream **token-identically** (``server.py _join``).
- **Scale signals.** ``fleet.instances_alive`` / ``fleet.desired_instances``
  gauges give an autoscaler the observed and target fleet size; headroom
  is ``fleet.scale_headroom``.

The router never holds the only copy of a request: anything claimed from
the front spool lives in the router backlog or an instance spool or the
``_assigned`` failover map until its ONE terminal result lands — the
``fleet.route`` fault site proves a failed placement pass parks work in
the backlog rather than losing it.

**Circuit breakers (docs/fleet.md "Overload survival").** Health files
age out in ``fleet.stale_after_s`` seconds — far too slow for a
sick-but-writing instance (GC thrash, a wedged accelerator) that keeps
stamping fresh gauges while answering nothing. Each instance carries a
:class:`_Breaker`: consecutive settled-error terminals or an EWMA service
time persistently above ``fleet.breaker_latency_ratio`` x the fleet
median trips it OPEN, removing the instance from placement immediately.
After ``fleet.breaker_cooldown_s`` it goes HALF-OPEN: exactly one probe
request is placed; a clean terminal closes the breaker, an error re-opens
it for another cooldown. The ``fleet.breaker`` flag fault trips a named
instance on demand, and ``fleet.breaker_state`` exports the state machine
per instance (0=closed, 1=open, 2=half-open). When NO instance is
placeable (breakers open, health missing) the router parks work in the
backlog and counts ``fleet.no_capacity_total`` — it never raises, and the
first half-open probe success re-places the parked work.
"""
from __future__ import annotations

import json
import logging
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..common import faults, file_io
from ..common import metrics as _metrics
from ..common.config import global_config
from ..common.utils import wall_clock
from ..ops import events as ops_events
from .queues import FileQueue, QueueBackend
from .server import DEADLINE_ERROR

logger = logging.getLogger("analytics_zoo_tpu.serving")

#: terminal error text for router-level admission shed (clients match it)
FLEET_SHED_ERROR = "shed: no instance can meet the deadline"

#: states a router may place NEW work on (idle = constructed, stepped
#: manually or not yet started — still claims from its spool)
_ROUTABLE_STATES = ("running", "idle")
#: terminal states: the instance will never claim again — reclaim its
#: spool and fail its streams over immediately, don't wait for staleness
_DEAD_STATES = ("crashed", "stopped", "drained")

_M_ROUTED = _metrics.counter(
    "fleet.routed_total", "Requests placed on an instance by the router.",
    labels=("instance",))
_M_SHED = _metrics.counter(
    "fleet.shed_total",
    "Requests shed by the router before enqueue (no instance could meet "
    "the deadline).")
_M_EXPIRED = _metrics.counter(
    "fleet.expired_total",
    "Requests already past their deadline at routing time.")
_M_FAILOVERS = _metrics.counter(
    "fleet.failovers_total",
    "Streams re-enqueued with their token prefix after their instance "
    "died or drained.")
_M_ALIVE = _metrics.gauge(
    "fleet.instances_alive",
    "Instances with a fresh health file in a routable state.")
_M_DESIRED = _metrics.gauge(
    "fleet.desired_instances",
    "Scale signal: instances needed for observed demand x headroom.")
_M_BACKLOG = _metrics.gauge(
    "fleet.backlog_depth",
    "Requests parked in the router awaiting a routable instance.")
_M_ROUTE_PASS = _metrics.histogram(
    "fleet.route_pass_seconds", "Wall seconds per route_once() pass.")
_M_NO_CAPACITY = _metrics.counter(
    "fleet.no_capacity_total",
    "Requests parked in the backlog because no instance was placeable "
    "(all breakers open / health files missing).")
_M_BREAKER = _metrics.gauge(
    "fleet.breaker_state",
    "Per-instance circuit breaker state: 0=closed, 1=open, 2=half-open.",
    labels=("instance",))

#: breaker states (gauge values)
BREAKER_CLOSED, BREAKER_OPEN, BREAKER_HALF_OPEN = 0, 1, 2

_BREAKER_STATE_NAMES = {BREAKER_CLOSED: "closed", BREAKER_OPEN: "open",
                        BREAKER_HALF_OPEN: "half_open"}

_E_BREAKER = ops_events.event_type(
    "fleet.breaker",
    "Per-instance circuit breaker transition (state_from/state, "
    "reason=errors|latency|probe_ok|probe_fail|forced|cooldown).")


class _Breaker:
    """Per-instance circuit breaker (closed -> open -> half-open ->
    closed). Trip inputs are *settled* terminals (recorded by the
    router's ``_settle`` pass) and the latency ratio check in
    ``_refresh``; while OPEN the instance receives no placements at all,
    and HALF-OPEN admits exactly one probe request."""

    def __init__(self, failures: int, latency_ratio: float,
                 cooldown_s: float, name: str = ""):
        self.failures = int(failures)
        self.latency_ratio = float(latency_ratio)
        self.cooldown_s = float(cooldown_s)
        self.name = name
        self.state = BREAKER_CLOSED
        self._error_streak = 0
        self._slow_streak = 0
        self._opened_at = 0.0
        self._probe_uri: Optional[str] = None

    def _transition(self, state: int, reason: str) -> None:
        """Move the state machine, emitting one ``fleet.breaker`` event
        per actual change (re-tripping an already-open breaker is not a
        transition)."""
        if state == self.state:
            return
        prev = self.state
        self.state = state
        _E_BREAKER.emit(label=self.name,
                        state=_BREAKER_STATE_NAMES[state],
                        state_from=_BREAKER_STATE_NAMES[prev],
                        reason=reason)

    def record_result(self, uri: str, is_error: bool, now: float) -> None:
        """Feed one settled terminal. In HALF-OPEN only the probe's
        terminal moves the state machine; a clean probe closes the
        breaker, a failed probe re-opens it for another cooldown."""
        if self.state == BREAKER_HALF_OPEN:
            if uri != self._probe_uri:
                return
            self._probe_uri = None
            if is_error:
                self.trip(now, reason="probe_fail")
            else:
                self._error_streak = self._slow_streak = 0
                self._transition(BREAKER_CLOSED, "probe_ok")
            return
        if is_error:
            self._error_streak += 1
            if self._error_streak >= self.failures:
                self.trip(now, reason="errors")
        else:
            self._error_streak = 0

    def record_latency(self, service_s: float, fleet_median_s: float,
                       now: float) -> None:
        """Feed one health refresh: an EWMA persistently above
        ``latency_ratio`` x the fleet median trips the breaker even when
        the instance is still answering (slow is the new down)."""
        if self.state != BREAKER_CLOSED:
            return
        if (fleet_median_s > 0.0
                and service_s > self.latency_ratio * fleet_median_s):
            self._slow_streak += 1
            if self._slow_streak >= self.failures:
                self.trip(now, reason="latency")
        else:
            self._slow_streak = 0

    def trip(self, now: float, reason: str = "forced") -> None:
        """Force-open the breaker (also the entry point for the
        ``fleet.breaker`` flag fault)."""
        self._opened_at = now
        self._error_streak = self._slow_streak = 0
        self._probe_uri = None
        self._transition(BREAKER_OPEN, reason)

    def placeable(self, now: float) -> bool:
        """May the router place a request here? OPEN breakers move to
        HALF-OPEN once the cooldown elapses; HALF-OPEN admits only while
        no probe is outstanding."""
        if self.state == BREAKER_CLOSED:
            return True
        if self.state == BREAKER_OPEN:
            if now - self._opened_at >= self.cooldown_s:
                self._probe_uri = None
                self._transition(BREAKER_HALF_OPEN, "cooldown")
                return True
            return False
        return self._probe_uri is None  # half-open: one probe at a time

    def note_placed(self, uri: str) -> None:
        """A placement landed on this instance; in HALF-OPEN it becomes
        the probe whose terminal decides the breaker's fate."""
        if self.state == BREAKER_HALF_OPEN and self._probe_uri is None:
            self._probe_uri = uri


def read_health(path: str, now: Optional[float] = None) -> Optional[Dict]:
    """Read an instance's ``health.json`` and stamp its **age**: the
    snapshot's gauges froze at ``snap['time']``, so consumers must not
    trust them without knowing how stale they are. Returns the snapshot
    with ``health_age_s`` added, or ``None`` when the file is missing or
    unreadable (an instance that never came up)."""
    try:
        with file_io.fopen(path) as f:
            snap = json.loads(f.read())
    except (OSError, ValueError, FileNotFoundError):
        return None
    if not isinstance(snap, dict) or "time" not in snap:
        return None
    t = now if now is not None else wall_clock()
    snap["health_age_s"] = max(0.0, t - float(snap["time"]))
    return snap


def instance_queue(root: str, name: str) -> FileQueue:
    """A per-instance request spool under the fleet front spool: requests
    at ``<root>/inst/<name>``, results shared with the front's
    ``results/`` so placement stays invisible to clients."""
    return FileQueue(file_io.join(root, "inst", name), results_root=root)


@dataclass
class FleetInstance:
    """One routable serving instance: its private queue, the health file
    its server writes, and its slot count (decode slots for generative
    servers, concurrent batch capacity for one-shot predict servers)."""
    name: str
    queue: QueueBackend
    health_path: str
    slots: int = 1
    #: latest health snapshot (with health_age_s), None before first read
    health: Optional[Dict[str, Any]] = field(default=None, repr=False)


def _score_instances(alive, depth, in_flight, slots_free, pages_free,
                     service_s, token_s, need_tokens, need_pages):
    """Estimated completion seconds per instance for ONE request —
    vectorized over the instance axis (policed by the zoolint hot-path
    pass: no host syncs, no Python loops). ``np.inf`` marks an instance
    the request must not be placed on.

    One-shot predicts (``need_tokens == 0``) queue behind the backlog at
    the instance's EWMA service time. Generative joins wait for a free
    slot (when none is free, a resident stream must run out first — the
    backlog-scaled slot wait), then stream the remaining budget at the
    instance's per-token EWMA; an instance whose free KV pages cannot hold
    the stream yet pays a retirement-wait penalty per missing page."""
    backlog = depth + in_flight
    one_shot = (backlog + 1.0) * service_s
    slot_wait = np.where(slots_free > 0.5, 0.0,
                         (backlog + 1.0) * need_tokens * token_s)
    gen = slot_wait + need_tokens * token_s
    est = np.where(need_tokens > 0.5, gen, one_shot)
    page_short = np.maximum(need_pages - np.maximum(pages_free, 0.0), 0.0)
    est = est + np.where((pages_free > -0.5) & (need_pages > 0.5),
                         page_short * token_s * 4.0, 0.0)
    return np.where(alive, est, np.inf)


class FleetRouter:
    """Route requests from a FRONT queue onto per-instance queues by
    estimated completion time; reclaim and fail over the work of dead
    instances; emit scale signals. Drive with :meth:`route_once` (tests)
    or :meth:`start`/:meth:`stop` (a background thread)."""

    def __init__(self, front: QueueBackend,
                 instances: List[FleetInstance], *,
                 stale_after_s: Optional[float] = None,
                 health_refresh_s: Optional[float] = None,
                 scale_headroom: Optional[float] = None,
                 default_deadline_ms: Optional[float] = None,
                 default_max_new_tokens: int = 32,
                 default_service_s: float = 0.05,
                 default_token_s: float = 0.02,
                 page_len: int = 0,
                 settle_batch: int = 128):
        cfg = global_config()
        self.front = front
        self.instances = list(instances)
        self.stale_after_s = (float(stale_after_s) if stale_after_s
                              is not None
                              else float(cfg.get("fleet.stale_after_s")))
        self.health_refresh_s = (
            float(health_refresh_s) if health_refresh_s is not None
            else float(cfg.get("fleet.health_refresh_s")))
        self.scale_headroom = (
            float(scale_headroom) if scale_headroom is not None
            else float(cfg.get("fleet.scale_headroom")))
        self.default_deadline_ms = default_deadline_ms
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.default_service_s = float(default_service_s)
        self.default_token_s = float(default_token_s)
        self.page_len = int(page_len)
        self.settle_batch = int(settle_batch)
        self._breaker_failures = int(cfg.get("fleet.breaker_failures"))
        self._breaker_latency_ratio = float(
            cfg.get("fleet.breaker_latency_ratio"))
        self._breaker_cooldown_s = float(
            cfg.get("fleet.breaker_cooldown_s"))
        #: name -> circuit breaker, created lazily on first refresh
        self._breakers: Dict[str, _Breaker] = {}
        #: uri -> {"instance": name, "rec": original request} for every
        #: request placed and not yet seen terminal — the failover map
        self._assigned: Dict[str, Dict[str, Any]] = {}
        #: requests the router holds but could not place yet (fault, all
        #: instances dead, ...) — retried every pass, never dropped
        self._backlog: List[Tuple[str, Dict[str, Any]]] = []
        self._g: Optional[Dict[str, np.ndarray]] = None
        self._last_refresh = -1e18
        self._desired = 0
        self._settle_cursor = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- telemetry ---------------------------------------------------------

    def _breaker(self, name: str) -> _Breaker:
        br = self._breakers.get(name)
        if br is None:
            br = self._breakers[name] = _Breaker(
                self._breaker_failures, self._breaker_latency_ratio,
                self._breaker_cooldown_s, name=name)
        return br

    def _refresh(self, now: float) -> None:
        """Re-read every instance's health file and rebuild the placement
        gauge arrays. ``dead`` instances additionally get their spool
        reclaimed and their assigned streams failed over."""
        n = len(self.instances)
        alive = np.zeros(n, bool)
        dead = np.zeros(n, bool)
        depth = np.zeros(n)
        in_flight = np.zeros(n)
        slots_free = np.zeros(n)
        pages_free = np.full(n, -1.0)
        service_s = np.full(n, self.default_service_s)
        token_s = np.full(n, self.default_token_s)
        for i, inst in enumerate(self.instances):
            snap = read_health(inst.health_path, now=now)
            inst.health = snap
            if snap is None or snap["health_age_s"] > self.stale_after_s \
                    or snap.get("state") in _DEAD_STATES:
                dead[i] = True
                continue
            if snap.get("state") not in _ROUTABLE_STATES:
                continue  # draining: not dead, not routable
            alive[i] = True
            depth[i] = snap.get("queue_pending") or 0
            in_flight[i] = snap.get("in_flight") or 0
            occupied = snap.get("slots_occupied")
            if occupied is not None:
                slots_free[i] = max(0, (snap.get("slots") or inst.slots)
                                    - occupied)
            else:
                slots_free[i] = max(0, inst.slots - in_flight[i])
            kv = snap.get("kv_pages_free")
            if kv is not None:
                # sharded pools: capacity is bounded by the emptiest page
                # shard (the round-robin allocator stalls on a full shard
                # even when the pool-wide free count looks ample), so the
                # effective free count is min_shard x shards
                min_shard = snap.get("kv_pages_free_min_shard")
                shards = snap.get("kv_shards") or 1
                if min_shard is not None and shards > 1:
                    kv = min_shard * shards
                pages_free[i] = kv
            ewma = snap.get("service_time_s_ewma")
            p99 = (snap.get("latency_ms") or {}).get("p99")
            if ewma:
                service_s[i] = ewma
            elif p99:
                service_s[i] = p99 / 1e3
            tps = snap.get("tokens_per_sec_ewma")
            if tps:
                token_s[i] = 1.0 / tps
        # circuit breakers: latency-ratio trip against the fleet median,
        # the fleet.breaker flag fault, then mask placement. A breaker
        # opening on a *live* instance must NOT fail its streams over —
        # it is still answering, just not receiving new work.
        med = (float(np.median(service_s[alive]))
               if bool(alive.any()) else 0.0)
        for i, inst in enumerate(self.instances):
            br = self._breaker(inst.name)
            # chaos site (flag kind): force-open this instance's breaker
            # (arm with budget=N to trip the first N instances refreshed)
            if faults.inject("fleet.breaker"):
                br.trip(now)
            if alive[i]:
                br.record_latency(float(service_s[i]), med, now)
                alive[i] = br.placeable(now)
            _M_BREAKER.labels(instance=inst.name).set(br.state)
        self._g = {"alive": alive, "dead": dead, "depth": depth,
                   "in_flight": in_flight, "slots_free": slots_free,
                   "pages_free": pages_free, "service_s": service_s,
                   "token_s": token_s}
        _M_ALIVE.set(int(alive.sum()))
        for i in np.flatnonzero(dead):
            self._reclaim_dead(self.instances[i])

    # -- failover ----------------------------------------------------------

    def _reclaim_dead(self, inst: FleetInstance) -> None:
        """Sweep a dead instance: pull its UNSTARTED spool entries back
        into the router backlog, and fail over every stream assigned to
        it — from its accumulated prefix when a partial result exists,
        from scratch otherwise. A terminal that already landed settles
        the request instead (the instance died after answering)."""
        try:
            stolen = inst.queue.claim_batch(1 << 16)
        except Exception:
            logger.exception("reclaiming %s's spool failed", inst.name)
            stolen = []
        for uri, rec in stolen:
            self._assigned.pop(uri, None)
            self._backlog.append((uri, rec))
        orphans = [u for u, a in self._assigned.items()
                   if a["instance"] == inst.name]
        for uri in orphans:
            entry = self._assigned.pop(uri)
            try:
                res = self.front.get_result(uri)
            except Exception:
                res = None
            if res is not None and ("error" in res or "value" in res):
                continue  # answered before dying: settled
            rec = dict(entry["rec"])
            if res is not None and res.get("stream"):
                # mid-stream death: carry the decoded prefix (and the
                # sampling seed the partial exported) so the adopter
                # continues token-identically instead of restarting
                rec["prefix"] = [int(x) for x in res["stream"]]
                if res.get("seed") is not None:
                    rec["seed"] = int(res["seed"])
                _M_FAILOVERS.inc()
                logger.warning(
                    "failing over %s from %s with a %d-token prefix",
                    uri, inst.name, len(rec["prefix"]))
            self._backlog.append((uri, rec))

    def _settle(self) -> None:
        """Drop assigned entries whose terminal result has landed — a
        bounded round-robin slice per pass so a large in-flight set never
        stalls routing."""
        uris = list(self._assigned)
        if not uris:
            return
        now = wall_clock()
        start = self._settle_cursor % len(uris)
        for uri in (uris[start:start + self.settle_batch]
                    or uris[:self.settle_batch]):
            try:
                res = self.front.get_result(uri)
            except Exception:
                continue
            if res is not None and ("error" in res or "value" in res):
                entry = self._assigned.pop(uri, None)
                if entry is not None:
                    # every settled terminal feeds the instance's
                    # breaker: error streaks trip it, and a half-open
                    # probe's terminal decides whether it closes
                    self._breaker(entry["instance"]).record_result(
                        uri, "error" in res, now)
        self._settle_cursor = start + self.settle_batch

    # -- placement ---------------------------------------------------------

    def _place(self, uri: str, rec: Dict[str, Any], now: float) -> bool:
        """Route one request. True = handled (placed, shed, or expired);
        False = park it in the backlog for the next pass."""
        try:
            # chaos site: a flaky placement (queue hiccup, torn health
            # read) must PARK the request, never lose or double-place it
            faults.inject("fleet.route")
        except faults.FaultInjected:
            return False
        deadline_ms = rec.get("deadline_ms") or self.default_deadline_ms
        enq = float(rec.get("enqueue_t") or now)
        remain = (enq + float(deadline_ms) / 1e3 - now
                  if deadline_ms else None)
        if remain is not None and remain <= 0:
            self.front.put_result(
                uri, {"error": DEADLINE_ERROR, "retriable": False})
            _M_EXPIRED.inc()
            return True
        g = self._g
        if g is None or not bool(g["alive"].any()):
            # zero placeable instances (all breakers open, every health
            # file missing/stale, or an empty fleet): park, never raise.
            # The backlog is retried every pass, so the first half-open
            # probe success re-places this work.
            _M_NO_CAPACITY.inc()
            return False
        prompt = rec.get("prompt")
        if prompt:
            budget = int(rec.get("max_new_tokens")
                         or self.default_max_new_tokens)
            need_tokens = max(1, budget - len(rec.get("prefix") or []))
            need_pages = (math.ceil((len(prompt) + budget) / self.page_len)
                          if self.page_len > 0 else 0)
        else:
            need_tokens = 0
            need_pages = 0
        est = _score_instances(
            g["alive"], g["depth"], g["in_flight"], g["slots_free"],
            g["pages_free"], g["service_s"], g["token_s"],
            np.float64(need_tokens), np.float64(need_pages))
        while True:
            best = int(np.argmin(est))
            if not np.isfinite(est[best]):
                # every candidate got masked mid-pass (half-open probes
                # already outstanding): same no-capacity park as above
                _M_NO_CAPACITY.inc()
                return False
            inst = self.instances[best]
            if self._breaker(inst.name).placeable(now):
                break
            # a half-open instance admits exactly ONE probe per cooldown;
            # once this pass placed it, later requests must look elsewhere
            est[best] = np.inf
            g["alive"][best] = False
        if remain is not None and float(est[best]) > remain:
            # admission control: answer NOW instead of queueing work no
            # instance can finish in time — shed is retriable (capacity
            # may free up), unlike a blown deadline
            self.front.put_result(
                uri, {"error": FLEET_SHED_ERROR, "retriable": True})
            _M_SHED.inc()
            return True
        try:
            inst.queue.enqueue(uri, rec)
        except Exception:
            logger.exception("enqueue to %s failed", inst.name)
            return False
        self._assigned[uri] = {"instance": inst.name, "rec": rec}
        self._breaker(inst.name).note_placed(uri)
        # optimistic gauge bump: later placements in this same pass see
        # the queued work without waiting for the next health refresh
        g["depth"][best] += 1.0
        if need_tokens:
            g["slots_free"][best] = max(0.0, g["slots_free"][best] - 1.0)
        _M_ROUTED.labels(instance=inst.name).inc()
        return True

    def route_once(self, max_items: int = 64) -> int:
        """One router pass: refresh telemetry (cadenced), fail over dead
        instances, settle finished work, then place the backlog plus a
        fresh batch from the front queue. Returns requests placed."""
        t0 = time.perf_counter()
        now = wall_clock()
        if now - self._last_refresh >= self.health_refresh_s:
            self._last_refresh = now
            self._refresh(now)
        self._settle()
        work, self._backlog = self._backlog, []
        try:
            work.extend(self.front.claim_batch(max_items))
        except Exception:
            logger.exception("front claim failed (transient)")
        placed = 0
        for uri, rec in work:
            if self._place(uri, rec, now):
                placed += 1
            else:
                self._backlog.append((uri, rec))
        self._scale_signals()
        _M_ROUTE_PASS.observe(time.perf_counter() - t0)
        return placed

    def _scale_signals(self) -> None:
        """Demand-derived autoscale gauges: an operator (or test) watches
        ``fleet.desired_instances`` against ``fleet.instances_alive`` to
        decide scale-out/in; headroom keeps failover capacity spare."""
        _M_BACKLOG.set(len(self._backlog))
        g = self._g
        demand = len(self._backlog) + len(self._assigned)
        if g is not None:
            demand += int(g["depth"].sum() + g["in_flight"].sum())
        per = max(1.0, float(np.mean([i.slots for i in self.instances]))
                  if self.instances else 1.0)
        self._desired = (int(math.ceil(self.scale_headroom * demand / per))
                         if demand else 0)
        _M_DESIRED.set(self._desired)

    def desired_instances(self) -> int:
        """Latest demand-derived target fleet size (the value behind the
        ``fleet.desired_instances`` gauge) — what an actuator
        (:class:`~analytics_zoo_tpu.cluster.supervisor.FleetSupervisor`)
        reconciles the live fleet against."""
        return self._desired

    def register_instance(self, inst: FleetInstance) -> None:
        """Add a freshly spawned instance to the routable set and force a
        health re-read on the next pass (the actuator's scale-out hook)."""
        self.instances.append(inst)
        self._last_refresh = -1e18

    def remove_instance(self, name: str) -> None:
        """Forget a drained/dead instance after its spool was reclaimed.
        The actuator calls this once the server subprocess has exited; any
        work still assigned to the name fails over on the next refresh."""
        self.instances = [i for i in self.instances if i.name != name]
        self._breakers.pop(name, None)
        self._g = None
        self._last_refresh = -1e18

    # -- lifecycle ---------------------------------------------------------

    def run(self, poll_interval_s: float = 0.01) -> None:
        logger.info("fleet router started (%d instances)",
                    len(self.instances))
        while not self._stop.is_set():
            if self.route_once() == 0:
                time.sleep(poll_interval_s)

    def start(self) -> "FleetRouter":
        self._stop.clear()
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stop routing. Backlogged requests are returned to the FRONT
        queue so a successor router (or a direct consumer) finds them —
        the router never takes work to its grave."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        for uri, rec in self._backlog:
            try:
                self.front.enqueue(uri, rec)
            except Exception:
                logger.exception("returning %s to the front failed", uri)
        self._backlog = []

    def breaker_states(self) -> Dict[str, int]:
        """Per-instance breaker state (the values behind the
        ``fleet.breaker_state`` gauge): 0=closed, 1=open, 2=half-open."""
        return {name: br.state for name, br in self._breakers.items()}

    @property
    def stats(self) -> Dict[str, int]:
        return {"assigned": len(self._assigned),
                "backlog": len(self._backlog)}
