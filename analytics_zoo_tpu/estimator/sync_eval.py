"""Synchronous (pre-pipelining) evaluate/predict loops.

These are the per-batch host-round-trip forms the async paths in
``estimator.py`` replaced: host-side ``shard_batch`` with zero prefetch and
a blocking ``float(...)`` / ``np.asarray(...)`` device sync per batch. They
are kept — behind ``eval.async = False`` — for two jobs:

1. **parity reference**: the async paths must reproduce these results
   bit-for-bit (``tests/test_eval_async.py``); the numerics contract
   (f32 per-batch losses, f64 host accumulation, record weighting) is
   defined HERE.
2. **A/B benchmarking**: ``bench.py eval`` measures async vs. this
   fallback on the same FeatureSet, so the pipelining win is a number,
   not a claim.

Deliberately not exported; every entry takes the estimator as first
argument and mirrors the exact code the async methods grew out of. New
behavior goes in ``estimator.py`` — this module only changes if the
numerics contract itself changes.
"""
from __future__ import annotations

# zoolint: disable-file=jit-host-sync — synchronous parity reference: the per-batch sync IS the contract this module exists to define

from typing import Dict

import jax
import numpy as np

from ..feature.featureset import FeatureSet
from ..keras import metrics as metrics_mod
from ..parallel.mesh import replicated, shard_batch


def evaluate_sync(est, val_set: FeatureSet, batch_size: int,
                  local_batch: int) -> Dict[str, float]:
    """Metric-path eval: synchronous shard per batch, metric states carried
    on device (this path never had a per-batch sync), host finalize."""
    # ONE iterator pass: streaming sets restart their generator per
    # eval_iterator call, so peeking with a second iterator would decode
    # the first batch twice on every evaluation
    it = val_set.eval_iterator(local_batch, pad_remainder=True)
    metric_states = None
    for x, y, valid in it:
        if metric_states is None:
            est._ensure_initialized(x)
            if est._eval_step is None:
                est._eval_step = est._build_eval_step()
            metric_states = [
                jax.device_put(m.init_state(), replicated(est.mesh))
                for m in est.metrics]
        mask = (np.arange(local_batch) < valid).astype(np.float32)
        batch = shard_batch(est.mesh, (x, y, mask))
        metric_states = est._eval_step(est.params, est.model_state,
                                       metric_states, *batch)
    if metric_states is None:
        raise ValueError("validation set produced no batches")
    return metrics_mod.compute_all(est.metrics, metric_states)


def evaluate_direct_exact_sync(est, val_set: FeatureSet, local_batch: int,
                               n_steps: int) -> Dict[str, float]:
    """Per-example masked direct eval with a blocking float() pair per
    batch. ``n_steps``/``local_batch`` come from the caller (the collective
    batch-count agreement is shared with the async path)."""
    eval_rng = jax.random.PRNGKey(0)
    it = val_set.eval_iterator(local_batch, pad_remainder=True)
    last = None
    total, weight = 0.0, 0.0
    for _ in range(n_steps):
        try:
            x, y, valid = next(it)
            last = (x, y)
        except StopIteration:  # short host re-feeds with mask all-zero
            (x, y), valid = last, 0
        mask = (np.arange(local_batch) < valid).astype(np.float32)
        bx, by, bm = shard_batch(est.mesh, (x, y, mask))
        s, w = est._direct_pe_step(est.params, est.model_state,
                                   eval_rng, bx, by, bm)
        total += float(s)
        weight += float(w)
    if weight == 0:
        raise ValueError(
            f"validation set is empty ({val_set.size} records)")
    return {"loss": total / weight}


def evaluate_direct_multiproc_sync(est, val_set: FeatureSet,
                                   local_batch: int, n_global: int,
                                   v_globals) -> Dict[str, float]:
    """Multi-process batch-mean direct eval: every host runs ``n_global``
    identically-shaped padded steps, blocking float() per batch, tail
    batches weighted by their GLOBAL valid count."""
    eval_rng = jax.random.PRNGKey(0)
    it = val_set.eval_iterator(local_batch, pad_remainder=True)
    last = None
    total, weight = 0.0, 0
    for t in range(n_global):
        try:
            x, y, _ = next(it)
            last = (x, y)
        except StopIteration:
            x, y = last
        xs, ys = shard_batch(est.mesh, (x, y))
        loss = float(est._direct_eval_step(
            est.params, est.model_state, eval_rng, xs, ys))
        total += loss * int(v_globals[t])
        weight += int(v_globals[t])
    return {"loss": total / weight}


def evaluate_direct_single_sync(est, val_set: FeatureSet,
                                local_batch: int) -> Dict[str, float]:
    """Single-process batch-mean direct eval: full batches sharded, the
    tail runs UNPADDED through the same jitted step (one extra compile at
    the tail shape), blocking float() per batch."""
    eval_rng = jax.random.PRNGKey(0)
    total, weight = 0.0, 0
    for x, y, valid in val_set.eval_iterator(local_batch,
                                             pad_remainder=False):
        if valid == local_batch:
            x, y = shard_batch(est.mesh, (x, y))
        # single-process: the tail evaluates exactly via a
        # replicated-batch compile at its true size
        loss = float(est._direct_eval_step(
            est.params, est.model_state, eval_rng, x, y))
        total += loss * valid
        weight += valid
    if weight == 0:
        raise ValueError(
            f"validation set is empty ({val_set.size} records)")
    return {"loss": total / weight}


def predict_sync(est, x: FeatureSet, local_batch: int):
    """Synchronous predict: blocking np.asarray fetch per batch."""
    outs = []
    for bx, _, valid in x.eval_iterator(local_batch, pad_remainder=True):
        bx = shard_batch(est.mesh, bx)
        y = est._predict_step(est.params, est.model_state, bx)
        outs.append(jax.tree_util.tree_map(
            lambda t: np.asarray(t)[:valid], y))
    if isinstance(outs[0], (list, tuple)):
        return type(outs[0])(
            np.concatenate([o[i] for o in outs]) for i in range(len(outs[0])))
    return np.concatenate(outs)
