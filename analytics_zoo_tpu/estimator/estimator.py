"""Estimator — the distributed training loop, on device.

Re-designs the reference's ``Estimator.train/evaluate``
(``pipeline/estimator/Estimator.scala:118,163``) +
``InternalDistriOptimizer.train()`` (``Topology.scala:1085-1268``) as a single
jitted train step over a device mesh:

- the reference's per-iteration two-Spark-job dance (fetch param slices →
  forward/backward per core replica → put grad slices → slice owners apply the
  optimizer → workers fetch updated slices) collapses into ONE XLA program:
  ``value_and_grad`` → (XLA-inserted) psum over the ``data`` axis →
  optimizer update, with params donated so updates are in-place in HBM.
- per-core model replicas become per-chip shards of the batch axis; the
  global-batch contract (global batch = chips × per-chip batch,
  ``Topology.scala:1110-1119``) is kept: ``batch_size`` is always global.
- the driver-side retry-with-checkpoint elasticity loop
  (``Topology.scala:1180-1262``) is reproduced: on failure, reload the newest
  checkpoint within a retry budget (``failure.retry_times`` /
  ``failure.retry_interval_s`` config, ≙ ``bigdl.failure.retryTimes``).
- the reference's straggler mitigation (``dropPercentage`` — drop the
  slowest tasks' results per iteration, ``Topology.scala:1096-1099``) is
  DESIGNED AWAY: synchronous SPMD over ICI has no per-worker task results to
  drop — chips run one lock-step program, and a slow/failed chip surfaces as
  a step failure handled by the elastic retry above.
- TensorBoard scalars Loss/LearningRate/Throughput per iteration + validation
  scalars per metric (``Topology.scala:206-238``).
"""
from __future__ import annotations

import json
import logging
import os
import signal
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..common import faults, file_io
from ..common import metrics as zoo_metrics
from ..common import profiler as _profiler
from ..common.config import global_config
from ..common.context import get_context
from ..common.triggers import EveryEpoch, MaxEpoch, TrainingState, Trigger
from ..common.utils import time_it
from ..feature.featureset import FeatureSet, HostDataset
from ..feature.device_feed import (DeviceFeed, masked_eval_batches,
                                   shard_payload)
from ..keras import metrics as metrics_mod
from ..keras.optimizers import Optimizer
from ..parallel import embedding as _embed_engine
from ..parallel.mesh import (param_sharding, replicated, shard_batch,
                             vocab_sharding_rule)
from ..utils.tensorboard import SummaryWriter


class CheckpointCorruptError(ValueError):
    """A snapshot failed checksum-manifest verification (torn write,
    bit-rot, tampering). The elastic restore path treats it as 'skip this
    snapshot and fall back to the next-older valid one'."""


class PreemptedError(RuntimeError):
    """Training stopped on a preemption notice (SIGTERM / the
    ``train.preempt`` fault site). A final snapshot and a resumable marker
    were written first when a checkpoint dir is configured; ``snapshot``
    carries its path (or ``None``)."""

    def __init__(self, message: str, snapshot: Optional[str] = None):
        super().__init__(message)
        self.snapshot = snapshot


#: train-loop + checkpoint telemetry (the shared registry every subsystem
#: reports into — see docs/observability.md for the full metric table)
_M_STEP = zoo_metrics.histogram(
    "train.step_seconds",
    "Train-step dispatch latency (device sync included only when the "
    "loop syncs the loss).")
_M_EXAMPLES = zoo_metrics.counter(
    "train.examples_total", "Examples consumed by the train loop.")
_M_CKPT_WRITE = zoo_metrics.histogram(
    "ckpt.write_seconds", "Snapshot serialize+publish latency.")
_M_CKPT_VERIFY = zoo_metrics.histogram(
    "ckpt.verify_seconds", "Checksum-manifest verification latency.")
_M_CKPT_RESTORE = zoo_metrics.histogram(
    "ckpt.restore_seconds", "Snapshot restore latency (verify included).")
_M_CKPT_FALLBACK = zoo_metrics.counter(
    "ckpt.fallback_total",
    "Restores that skipped a torn/corrupt newest snapshot and fell back "
    "to an older one.")

#: step-phase attribution for the train loop (host_input / dispatch /
#: execute / fetch / compile per step) — active only under profile.enabled
_P_TRAIN = _profiler.StepProfiler("train")


def _profiled_feed(feed, prof):
    """Wrap the device feed so each step window opens just before its
    blocking ``next()`` — host-input stalls land in THIS step's phases."""
    it = iter(feed)
    while True:
        prof.step_start()
        t0 = time.perf_counter()
        try:
            item = next(it)
        except StopIteration:
            return
        prof.add("host_input", time.perf_counter() - t0, start=t0)
        yield item


#: resumable-preemption marker filename, written next to the snapshots
PREEMPT_MARKER = "PREEMPTED.json"

#: per-snapshot checksum manifest filename (inside each snapshot dir)
_MANIFEST_NAME = "zoo_manifest.json"

#: per-rank seal stamp: ``zoo_rank-<i>.ok``, written by EVERY process of a
#: multi-process pod after the collective orbax save returns. Excluded
#: from the checksum manifest (ranks write them concurrently with rank
#: 0's manifest), but verification requires all of them: a rank killed
#: between save and seal leaves a snapshot no survivor may resume from.
_RANK_STAMP_FMT = "zoo_rank-{}.ok"


def _is_rank_stamp(name: str) -> bool:
    return (name.startswith("zoo_rank-") and name.endswith(".ok")
            and name[len("zoo_rank-"):-len(".ok")].isdigit())


def _dir_checksums(local_dir: str) -> Dict[str, List[int]]:
    """``{relpath: [size, crc32]}`` for every file under ``local_dir``
    except the manifest itself and the per-rank seal stamps. crc32 (not a
    cryptographic hash) on purpose: the threat model is torn writes and
    bit-rot, not an adversary, and restore-time verification must stay
    cheap next to the orbax read it guards."""
    entries: Dict[str, List[int]] = {}
    for root, _dirs, files in os.walk(local_dir):
        for name in sorted(files):
            if name == _MANIFEST_NAME or _is_rank_stamp(name):
                continue
            p = os.path.join(root, name)
            rel = os.path.relpath(p, local_dir).replace(os.sep, "/")
            crc, size = 0, 0
            with open(p, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    crc = zlib.crc32(chunk, crc)
                    size += len(chunk)
            entries[rel] = [size, crc]
    return entries


def _write_manifest(local_dir: str, ranks: Optional[int] = None) -> None:
    manifest: Dict[str, Any] = {"version": 1,
                                "files": _dir_checksums(local_dir)}
    if ranks:
        # seal which ranks must have stamped this snapshot: restore
        # refuses it until every one of zoo_rank-0..N-1.ok exists
        manifest["ranks"] = int(ranks)
    with open(os.path.join(local_dir, _MANIFEST_NAME), "w") as f:
        json.dump(manifest, f)


def _verify_manifest(local_dir: str, origin: str) -> bool:
    """Verify ``local_dir`` against its checksum manifest. Returns False
    for pre-manifest snapshots (nothing to verify — legacy tolerance);
    raises :class:`CheckpointCorruptError` on any size/checksum mismatch,
    missing file, unexpected extra file, or (for pod snapshots) a missing
    per-rank seal stamp."""
    mpath = os.path.join(local_dir, _MANIFEST_NAME)
    if not os.path.exists(mpath):
        return False
    t0 = time.perf_counter()
    with open(mpath) as f:
        manifest = json.load(f)
    want = {k: tuple(v) for k, v in manifest.get("files", {}).items()}
    have = {k: tuple(v) for k, v in _dir_checksums(local_dir).items()}
    _M_CKPT_VERIFY.observe(time.perf_counter() - t0)
    if want != have:
        missing = sorted(set(want) - set(have))
        extra = sorted(set(have) - set(want))
        corrupt = sorted(k for k in set(want) & set(have)
                         if want[k] != have[k])
        raise CheckpointCorruptError(
            f"checkpoint at {origin} failed checksum verification — torn "
            f"or corrupt snapshot (missing={missing[:4]}, "
            f"corrupt={corrupt[:4]}, unexpected={extra[:4]})")
    ranks = int(manifest.get("ranks") or 0)
    if ranks:
        unsealed = [i for i in range(ranks) if not os.path.exists(
            os.path.join(local_dir, _RANK_STAMP_FMT.format(i)))]
        if unsealed:
            raise CheckpointCorruptError(
                f"checkpoint at {origin} was written by a {ranks}-process "
                f"pod but ranks {unsealed[:8]} never sealed it (killed "
                f"between the collective save and the stamp) — refusing "
                f"the partial snapshot")
    return True


class _AsyncSnapshotWriter:
    """One-in-flight background checkpoint writer with an explicit fence.

    The TPU-first snapshot split: the device→host copy happens synchronously
    at trigger time (cheap — HBM→RAM), the serialize+write happens on this
    thread so the train loop never stalls on storage. ``wait()`` is the
    fence: called before the next snapshot is submitted, before any restore,
    and at train end; a failed background write surfaces there."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    def wait(self) -> None:
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise RuntimeError("background checkpoint write failed") from err

    def submit(self, fn) -> None:
        self.wait()  # fence: at most one write in flight

        def run():
            try:
                fn()
            except BaseException as e:  # surfaced at the next fence
                self._error = e

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="zoo-ckpt-writer")
        self._thread.start()

    @property
    def in_flight(self) -> bool:
        return self._thread is not None and self._thread.is_alive()


def _flat_losses(vals):
    """Flatten a drain of per-dispatch losses: scalars (single-step) and
    [k] arrays (multi-step dispatch) both become per-step floats."""
    out: List[float] = []
    for leaf in vals:
        out.extend(float(v) for v in np.atleast_1d(np.asarray(leaf)))
    return out


def _drain_sum_pairs(pending):
    """Drain a pass worth of per-batch ``(sum, weight)`` device scalar
    pairs: ONE ``device_get`` for the whole list, then the same f64 host
    accumulation the synchronous loop performed per batch — bit-identical
    totals, one sync instead of 2·n."""
    host = jax.device_get(pending)
    total, weight = 0.0, 0.0
    for s, w in host:
        total += float(s)
        weight += float(w)
    return total, weight


def _drain_weighted_losses(pending):
    """Drain per-batch ``(loss_device_scalar, weight_int)`` pairs: ONE
    ``device_get`` over the loss scalars, then f64 ``loss * weight`` host
    accumulation (the record-weighted contract sync_eval defines)."""
    host = jax.device_get([loss for loss, _ in pending])
    total, weight = 0.0, 0
    for loss, (_, w) in zip(host, pending):
        total += float(loss) * w
        weight += w
    return total, weight


def _group_host_batches(it, first_epoch_remaining, per_epoch, k):
    """Stack up to ``k`` host batches into one step-stacked ``[g, B, ...]``
    group for the multi-step dispatch path. Groups never span an epoch
    boundary (the tail group is smaller), so epoch accounting and per-epoch
    reshuffles stay exact."""
    remaining = int(first_epoch_remaining)
    while True:
        if remaining <= 0:
            remaining = per_epoch
        g = min(k, remaining)
        batches = []
        for _ in range(g):
            try:
                batches.append(next(it))
            except StopIteration:
                # finite duck-typed iterator exhausted mid-group (the train
                # iterator contract is endless, but the g=1 path tolerates
                # finite ones — so must this): flush what we have
                break
        if not batches:
            return
        yield jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)
        if len(batches) < g:
            return
        remaining -= g

def _prepare_dataset(dataset, local_batch: int) -> None:
    """Duck-typed warm-up hook: lazy/mp data planes fork their worker
    pools, map shared-memory slabs and create replay caches here — one-time
    setup that must not land inside the overlapped dispatch loop."""
    prepare = getattr(dataset, "prepare", None)
    if prepare is not None:
        prepare(local_batch)


logger = logging.getLogger("analytics_zoo_tpu")


class Estimator:
    def __init__(self, model, loss_fn: Callable, optimizer: Optimizer,
                 metrics: Optional[Sequence] = None,
                 mesh=None, param_sharding_rules: Optional[Sequence] = None,
                 direct_loss_fn: Optional[Callable] = None,
                 direct_eval_loss_fn: Optional[Callable] = None,
                 direct_eval_per_example_fn: Optional[Callable] = None,
                 compute_dtype=None,
                 seed: int = 42):
        """``direct_loss_fn(params, model_state, rng, x, y) -> (loss,
        new_state)`` bypasses the model.call→loss_fn(y, y_pred) convention —
        the capture-style API hook (≙ TFOptimizer.from_loss, where the user
        hands over the whole loss graph instead of a model).
        ``direct_eval_loss_fn`` is the eval-mode variant (no dropout etc.);
        defaults to ``direct_loss_fn``.

        ``direct_eval_per_example_fn(params, model_state, rng, x, y) ->
        [batch] per-record losses`` makes padded-tail evaluation EXACT:
        pad rows are masked out of the sum before the global weighting, so
        multi-process eval equals the single-process result bit-for-bit in
        expectation (without it, the batch-mean form leaves an
        O(pad/batch) bias on tail batches, documented below).

        ``compute_dtype`` (e.g. ``jnp.bfloat16``) enables mixed precision:
        float inputs are cast to it before the forward pass (layers follow
        activation dtype, so matmuls hit the MXU in bf16) while params, the
        optimizer state, and the loss stay float32 — the standard TPU
        mixed-precision policy."""
        self.model = model
        self.loss_fn = loss_fn
        self.direct_loss_fn = direct_loss_fn
        self.direct_eval_loss_fn = direct_eval_loss_fn or direct_loss_fn
        self.direct_eval_per_example_fn = direct_eval_per_example_fn
        self.optimizer = optimizer
        self.metrics = [metrics_mod.get(m) for m in (metrics or [])]
        self.compute_dtype = compute_dtype
        self.ctx = get_context()
        self.mesh = mesh if mesh is not None else self.ctx.mesh
        self.param_rules = param_sharding_rules
        # vocab-sharded embedding layers built outside a mesh context must
        # shard against THIS estimator's mesh (parallel/embedding.py)
        _embed_engine.set_default_mesh(self.mesh)
        rng_impl = global_config().get("rng.impl") or None
        if rng_impl:
            # "rbg"/"unsafe_rbg" use the TPU's hardware RNG for bit
            # generation — dropout-heavy training (BERT: ~600M draws/step)
            # pays double-digit ms/step for threefry's ALU chain; rbg is
            # deterministic per seed but its streams differ from threefry's
            self.root_rng = jax.random.key(seed, impl=rng_impl)
        else:
            self.root_rng = jax.random.PRNGKey(seed)

        self.params = None
        self.opt_state = None
        self.model_state: Any = {}
        self.global_step = 0
        self.epoch = 1

        self._train_step = None
        self._multi_step = None
        self._eval_step = None
        self._predict_step = None
        self._direct_eval_step = None
        self._direct_pe_step = None
        self._clip: Optional[Tuple[str, Any]] = None
        self._tb: Optional[Tuple[str, str]] = None
        self._ckpt_dir: Optional[str] = None
        self._ckpt_trigger: Optional[Trigger] = None
        self._ckpt_writer = _AsyncSnapshotWriter()
        self._train_writer: Optional[SummaryWriter] = None
        self._val_writer: Optional[SummaryWriter] = None
        self._preempt_requested = False
        #: per-traced-step (exchange, grad) byte totals of the sharded
        #: embedding path; None until the first dispatch of a fresh step fn
        self._embed_step_bytes: Optional[Tuple[int, int]] = None
        #: high-water mark of MoE drop counts already drained into the
        #: parallel.moe_dropped_tokens_total counter (the __moe_dropped__
        #: state contract accumulates a RUNNING total on device)
        self._moe_drops_seen = 0

    def _drain_moe_drops(self) -> None:
        """Publish MoE capacity-drop counts at the per-epoch sync point.

        MoE layers accumulate a running dropped-token count in model state
        under the ``MOE_DROP_KEY`` contract (keras/engine.py); this drains
        the delta since the last epoch into the
        ``parallel.moe_dropped_tokens_total`` counter. Runs next to the
        loss drain — already a sanctioned host sync — so capacity-factor
        dropping is never silent yet never adds a per-step sync."""
        from ..keras.engine import MOE_DROP_KEY
        from ..parallel.moe import drain_drop_counter
        flat = jax.tree_util.tree_flatten_with_path(self.model_state)[0]
        total = 0
        for path, leaf in flat:
            if path and str(getattr(path[-1], "key", "")) == MOE_DROP_KEY:
                total += int(jax.device_get(leaf))
        if total:
            self._moe_drops_seen = drain_drop_counter(
                total, self._moe_drops_seen)

    # -- configuration (reference KerasNet setters, Topology.scala:111-127) ---

    def set_gradient_clipping(self, clip: Tuple[str, Any]) -> None:
        self._clip = clip
        self._train_step = None  # rebuild
        self._multi_step = None

    def set_tensorboard(self, log_dir: str, app_name: str) -> None:
        self._tb = (log_dir, app_name)

    def set_checkpoint(self, path: str, trigger: Optional[Trigger] = None) -> None:
        self._ckpt_dir = path
        self._ckpt_trigger = trigger or EveryEpoch()

    # -- initialization -------------------------------------------------------

    def _model_layers(self) -> List:
        m = self.model
        if hasattr(m, "flattened_layers"):
            return m.flattened_layers()
        return list(getattr(m, "layers", None) or [m])

    def _sharded_table_specs(self) -> Dict[Tuple[str, str], Any]:
        """``{(layer_name, param_key): ShardSpec}`` over every vocab-sharded
        embedding table in the model. Deterministic PRE-BUILD (layers compute
        their spec on demand), so checkpoint restore can rebuild the split
        optimizer-state structure before the first trace."""
        out: Dict[Tuple[str, str], Any] = {}
        for layer in self._model_layers():
            tables = getattr(layer, "sharded_tables", None)
            if tables is None:
                continue
            for key, spec in tables().items():
                out[(layer.name, key)] = spec
        return out

    def _embed_plan(self) -> Dict[Tuple[str, str], Any]:
        """Tables the SPARSE row-subset optimizer path owns this build:
        vocab-sharded tables x an optimizer whose math has a sparse
        equivalent. Empty plan == exactly the historical dense behavior."""
        if (self.optimizer is None
                or getattr(self.optimizer, "sparse_rows", None) is None
                or self.direct_loss_fn is not None
                or not global_config().get("embed.sparse_updates")):
            return {}
        return self._sharded_table_specs()

    def _maybe_add_vocab_rules(self) -> None:
        """Idempotently append the GSPMD vocab-sharding rule for the
        model's sharded tables to ``param_rules`` (params, frozen-table
        model state and row-wise optimizer state all ride the same rule)."""
        _embed_engine.set_default_mesh(self.mesh)
        tables = {k: spec.axis
                  for k, spec in self._sharded_table_specs().items()}
        if not tables or getattr(self, "_vocab_rule_tables", None) == tables:
            return
        rule = vocab_sharding_rule(tables)
        rule._is_vocab_rule = True
        base = [r for r in (self.param_rules or [])
                if not getattr(r, "_is_vocab_rule", False)]
        self.param_rules = base + [rule]
        self._vocab_rule_tables = tables

    def _opt_rules(self) -> Optional[List]:
        """Sharding rules for the optimizer state tree (row-wise embed
        state shards with its table; everything else stays replicated)."""
        tables = {k: spec.axis
                  for k, spec in self._sharded_table_specs().items()}
        return [vocab_sharding_rule(tables)] if tables else None

    def _init_opt_state(self, params):
        """Optimizer-state init honoring the sparse-embedding plan: plan
        tables get row-wise state under ``opt["embed"]`` (read/written only
        for touched rows each step) and are STRIPPED from the dense optax
        state; an empty plan returns the plain optax init unchanged."""
        plan = self._embed_plan()
        plan = {k: v for k, v in plan.items()
                if k[0] in params and k[1] in params[k[0]]}
        if not plan:
            return self.optimizer.init(params)
        kind, _hyper = self.optimizer.sparse_rows
        stripped = {ln: {k: v for k, v in sub.items()
                         if (ln, k) not in plan}
                    for ln, sub in params.items()}
        stripped = {ln: sub for ln, sub in stripped.items() if sub}
        embed: Dict[str, Dict[str, Any]] = {}
        for ln, key in sorted(plan):
            embed.setdefault(ln, {})[key] = _embed_engine.init_row_state(
                kind, params[ln][key])
        return {"dense": self.optimizer.init(stripped), "embed": embed}

    def _ensure_initialized(self, sample_x) -> None:
        # "state resolved" distinguishes a genuinely-stateless model (state
        # legitimately {}) from state that simply hasn't been built yet — an
        # empty dict alone can't express that, and skipping the build for a
        # BatchNorm model means KeyError at call time
        state_resolved = (getattr(self, "_state_resolved", False)
                          or bool(self.model_state))
        if self.params is not None and state_resolved and (
                self.opt_state is not None or self.optimizer is None):
            return
        self._maybe_add_vocab_rules()
        from ..keras.engine import init_model
        self.root_rng, init_rng = jax.random.split(self.root_rng)
        if self.params is None:
            params, state = init_model(self.model, init_rng, sample_x)
            sharding = param_sharding(self.mesh, params, self.param_rules)
            self.params = jax.device_put(params, sharding)
            if not self.model_state:
                self.model_state = jax.device_put(
                    state, param_sharding(self.mesh, state, self.param_rules))
            self._state_resolved = True
        elif not state_resolved:
            # params were imported (set_params); build only fresh model state
            # — under jit XLA dead-code-eliminates the (discarded) param init
            state = jax.jit(
                lambda r: init_model(self.model, r, sample_x)[1])(init_rng)
            if jax.tree_util.tree_leaves(state):
                self.model_state = jax.device_put(
                    state, param_sharding(self.mesh, state, self.param_rules))
            else:
                self.model_state = {}
            self._state_resolved = True
        if self.opt_state is None and self.optimizer is not None:
            opt = self._init_opt_state(self.params)
            self.opt_state = jax.device_put(
                opt, param_sharding(self.mesh, opt, self._opt_rules()))

    def _clip_transform(self):
        if self._clip is None:
            return None
        kind, val = self._clip
        if kind == "l2":
            return optax.clip_by_global_norm(val)
        lo, hi = val
        if abs(lo) != abs(hi):
            # optax.clip is symmetric; emulate asymmetric constant clip
            return optax.stateless(
                lambda g, p: jax.tree_util.tree_map(
                    lambda t: jnp.clip(t, lo, hi), g))
        return optax.clip(hi)

    # -- compiled steps -------------------------------------------------------

    def _cast_inputs(self, x):
        """Mixed precision: float inputs -> compute_dtype (ints untouched)."""
        if self.compute_dtype is None:
            return x
        dtype = self.compute_dtype
        return jax.tree_util.tree_map(
            lambda t: t.astype(dtype)
            if jnp.issubdtype(jnp.asarray(t).dtype, jnp.floating) else t, x)

    def _build_train_step(self):
        model, loss_fn, optimizer = self.model, self.loss_fn, self.optimizer
        direct = self.direct_loss_fn
        clip = self._clip_transform()
        cast = self._cast_inputs
        plan = self._embed_plan()
        sparse = getattr(optimizer, "sparse_rows", None) if plan else None

        # transfer learning: frozen layers get stop_gradient (XLA then
        # dead-code-eliminates their backward pass) and zeroed updates (so
        # weight-decay terms can't drift them either)
        frozen = frozenset(getattr(model, "frozen_layers", ()) or ())

        from ..keras.engine import AUX_LOSS_KEY

        def fold_aux(loss, new_state):
            # the AUX_LOSS_KEY state contract: layers (MoE router balance,
            # activation regularizers...) publish scalar penalties in their
            # state; they join the objective here — on BOTH the model.call
            # and the direct-loss (capture) paths
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                    new_state)[0]:
                if path and str(getattr(path[-1], "key", "")) == AUX_LOSS_KEY:
                    loss = loss + leaf
            return loss

        def train_step(params, opt_state, model_state, rng, x, y):
            def compute_loss(p):
                if frozen:
                    p = {k: jax.lax.stop_gradient(v) if k in frozen else v
                         for k, v in p.items()}
                if direct is not None:
                    loss, new_state = direct(p, model_state, rng, x, y)
                    return fold_aux(loss, new_state), new_state
                y_pred, new_state = model.call(p, model_state, cast(x),
                                               training=True, rng=rng)
                # loss in float32 regardless of activation dtype
                y_pred = jax.tree_util.tree_map(
                    lambda t: t.astype(jnp.float32), y_pred)
                return fold_aux(loss_fn(y, y_pred), new_state), new_state

            (loss, new_state), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(params)
            # sharded embedding layers stash their forward exchange blob in
            # the state tree; it must come OUT of the carried state (scan
            # carry structure) whether or not the sparse update consumes it
            rows_map, new_state = _embed_engine.pop_stashed_rows(new_state)
            if clip is not None:
                grads, _ = clip.update(grads, clip.init(params), params)
            if not plan:
                updates, opt_state = optimizer.update(grads, opt_state, params)
                if frozen:
                    updates = {k: jax.tree_util.tree_map(jnp.zeros_like, u)
                               if k in frozen else u
                               for k, u in updates.items()}
                params = optax.apply_updates(params, updates)
                return params, opt_state, new_state, loss

            # sparse path: dense optax over the non-plan leaves, row-subset
            # updates over the sharded tables (untouched rows' optimizer
            # state is neither read nor written)
            kind, hyper = sparse
            dense_params = {ln: {k: v for k, v in sub.items()
                                 if (ln, k) not in plan}
                            for ln, sub in params.items()}
            dense_params = {ln: sub for ln, sub in dense_params.items() if sub}
            dense_grads = {ln: {k: g for k, g in sub.items()
                                if (ln, k) not in plan}
                           for ln, sub in grads.items()}
            dense_grads = {ln: sub for ln, sub in dense_grads.items() if sub}
            updates, dense_opt = optimizer.update(
                dense_grads, opt_state["dense"], dense_params)
            if frozen:
                updates = {k: jax.tree_util.tree_map(jnp.zeros_like, u)
                           if k in frozen else u
                           for k, u in updates.items()}
            new_dense = optax.apply_updates(dense_params, updates)
            out_params = {ln: dict(sub) for ln, sub in params.items()}
            for ln, sub in new_dense.items():
                for k, v in sub.items():
                    out_params[ln][k] = v
            embed_opt = {ln: dict(sub)
                         for ln, sub in opt_state["embed"].items()}
            for ln, key in sorted(plan):
                spec = plan[(ln, key)]
                table, g = params[ln][key], grads[ln][key]
                rstate = opt_state["embed"][ln][key]
                blob = rows_map.get(ln, {}).get(key)
                if ln in frozen:
                    new_table, new_rstate = table, rstate
                elif blob is not None:
                    new_table, new_rstate = _embed_engine.apply_row_update(
                        kind, hyper, spec, table, g, blob, rstate)
                else:
                    # lookup fell back to the dense gather this step (id
                    # count not divisible over the shards): same optimizer
                    # arithmetic applied to the whole (sharded) table
                    new_table, new_rstate = _embed_engine.apply_dense_update(
                        kind, hyper, table, g, rstate)
                out_params[ln][key] = new_table
                embed_opt[ln][key] = new_rstate
            return (out_params, {"dense": dense_opt, "embed": embed_opt},
                    new_state, loss)

        return jax.jit(train_step, donate_argnums=(0, 1, 2))

    def _build_multi_step(self):
        """K train steps in ONE dispatch: ``lax.scan`` over a step-stacked
        batch ``[k, B, ...]``. Amortizes per-dispatch host/RPC latency — the
        TPU-first answer to the reference's twice-per-step Spark job launch
        (SURVEY §5: "the loop lives on-device, the host only feeds data");
        essential on remote-attached chips, a win everywhere. Losses come
        back per step; triggers quantize to the group boundary."""
        step = self._train_step  # jitted; inlines under the outer jit

        def multi(params, opt_state, mstate, root_rng, step0, xs, ys):
            def body(carry, inp):
                p, o, m, i = carry
                x, y = inp
                rng = jax.random.fold_in(root_rng, i)
                p, o, m, loss = step(p, o, m, rng, x, y)
                return (p, o, m, i + 1), loss

            (p, o, m, _), losses = jax.lax.scan(
                body, (params, opt_state, mstate,
                       jnp.asarray(step0, jnp.int32)), (xs, ys))
            return p, o, m, losses

        return jax.jit(multi, donate_argnums=(0, 1, 2))

    def _build_eval_step(self):
        model, metrics = self.model, self.metrics

        cast = self._cast_inputs

        def eval_step(params, model_state, metric_states, x, y, mask):
            y_pred, _ = model.call(params, model_state, cast(x), training=False)
            y_pred = jax.tree_util.tree_map(
                lambda t: t.astype(jnp.float32), y_pred)
            return [m.update(s, y, y_pred, mask)
                    for m, s in zip(metrics, metric_states)]

        return jax.jit(eval_step, donate_argnums=(2,))

    def _wire_step_cost(self, group, x, y):
        """One-time per compiled step fn: install the XLA cost model
        (FLOPs + HBM bytes per dispatch) behind the train loop's MFU and
        roofline gauges. ``lower()`` retraces abstractly — no execution,
        no recompile — and any failure just leaves the gauges unset."""
        try:
            if group > 1:
                lowered = self._multi_step.lower(
                    self.params, self.opt_state, self.model_state,
                    self.root_rng, np.int32(self.global_step), x, y)
            else:
                step_rng = jax.random.fold_in(self.root_rng,
                                              self.global_step)
                lowered = self._train_step.lower(
                    self.params, self.opt_state, self.model_state,
                    step_rng, x, y)
            _P_TRAIN.set_cost(_profiler.cost_flops(lowered),
                              _profiler.cost_bytes(lowered))
        except Exception:
            pass

    def _build_predict_step(self):
        model = self.model

        cast = self._cast_inputs

        def predict_step(params, model_state, x):
            y_pred, _ = model.call(params, model_state, cast(x), training=False)
            return jax.tree_util.tree_map(
                lambda t: t.astype(jnp.float32), y_pred)

        if self.ctx.process_count > 1:
            # multi-host: every host must be able to fetch the predictions
            # (np.asarray on a batch-sharded output would span
            # non-addressable devices) — replicate outputs; XLA inserts the
            # all-gather over the batch axis
            from jax.sharding import NamedSharding, PartitionSpec
            return jax.jit(predict_step, out_shardings=NamedSharding(
                self.mesh, PartitionSpec()))
        return jax.jit(predict_step)

    # -- train (the InternalDistriOptimizer.train equivalent) -----------------

    def train(self, train_set: FeatureSet, batch_size: int,
              epochs: Optional[int] = None,
              end_trigger: Optional[Trigger] = None,
              validation_set: Optional[FeatureSet] = None,
              validation_trigger: Optional[Trigger] = None,
              checkpoint_trigger: Optional[Trigger] = None,
              steps_per_dispatch: int = 1) -> Dict[str, Any]:
        """Train with preemption protection: a SIGTERM during this call
        (the TPU preemption notice — seconds of warning) stops at the next
        step boundary, fences the async checkpoint writer, writes a final
        snapshot plus a ``PREEMPTED.json`` resumable marker, and raises
        :class:`PreemptedError`. A leftover marker from a previous
        preempted run is consumed (removed) here — resuming is
        ``load_checkpoint(latest)`` + ``train()`` as usual. See
        :meth:`_train_impl` for the loop semantics."""
        self._preempt_requested = False
        restore_handler = self._install_preemption_handler()
        try:
            if self._ckpt_dir:
                marker = file_io.join(self._ckpt_dir, PREEMPT_MARKER)
                if file_io.exists(marker):
                    file_io.remove(marker)
            return self._train_impl(
                train_set, batch_size, epochs=epochs,
                end_trigger=end_trigger, validation_set=validation_set,
                validation_trigger=validation_trigger,
                checkpoint_trigger=checkpoint_trigger,
                steps_per_dispatch=steps_per_dispatch)
        finally:
            restore_handler()

    def train_online(self, train_set: FeatureSet, batch_size: int,
                     max_steps: Optional[int] = None,
                     end_trigger: Optional[Trigger] = None,
                     snapshot_interval_s: Optional[float] = None,
                     validation_set: Optional[FeatureSet] = None,
                     validation_trigger: Optional[Trigger] = None,
                     steps_per_dispatch: int = 1) -> Dict[str, Any]:
        """Continual training off a stream: unbounded by default (runs
        until SIGTERM preemption or ``max_steps``/``end_trigger``), with
        snapshots paced by wall time (``snapshot_interval_s``, default
        config ``online.snapshot_interval_s``) instead of epoch
        boundaries — an unbounded stream has none worth waiting for.

        This is :meth:`train` with online-shaped triggers; everything
        else — resumable ``data_state`` capture, async checksummed
        snapshots, elastic retry, preemption protection — is the same
        loop.  Pair with a :class:`~analytics_zoo_tpu.online.stream.
        QueueFeatureSet` (``FeatureSet.from_queue``) for exact resume:
        its journal cursor rides in every snapshot's data_state.  Sparse
        embedding updates (``sparse_rows``) make the per-step cost scale
        with rows *touched* by the stream, not table size — see
        docs/online.md."""
        from ..common.triggers import MaxIteration, Never, TimeInterval
        if snapshot_interval_s is None:
            snapshot_interval_s = float(
                global_config().get("online.snapshot_interval_s"))
        if end_trigger is None:
            end_trigger = (MaxIteration(int(max_steps))
                           if max_steps is not None else Never())
        checkpoint_trigger = (TimeInterval(snapshot_interval_s)
                              if self._ckpt_dir else None)
        return self.train(
            train_set, batch_size, end_trigger=end_trigger,
            validation_set=validation_set,
            validation_trigger=validation_trigger,
            checkpoint_trigger=checkpoint_trigger,
            steps_per_dispatch=steps_per_dispatch)

    def _install_preemption_handler(self):
        """Install the SIGTERM→preempt-flag handler for the duration of a
        train() call; returns the undo callable. Signals only land on the
        main thread — a train() driven from a worker thread (pod tests,
        notebooks) keeps whatever handler the host process installed."""
        if threading.current_thread() is not threading.main_thread():
            return lambda: None
        try:
            prev = signal.signal(signal.SIGTERM, self._on_sigterm)
        except ValueError:  # embedded interpreters without signal support
            return lambda: None
        return lambda: signal.signal(signal.SIGTERM, prev)

    def _on_sigterm(self, signum, frame) -> None:
        logger.warning(
            "SIGTERM: preemption requested — will write a final snapshot "
            "and a resumable marker at the next step boundary")
        self._preempt_requested = True

    @staticmethod
    def preemption_marker(ckpt_dir: str) -> Optional[Dict[str, Any]]:
        """Read a checkpoint dir's resumable-preemption marker (``None``
        when the last run was not preempted)."""
        path = file_io.join(ckpt_dir, PREEMPT_MARKER)
        if not file_io.exists(path):
            return None
        with file_io.fopen(path) as f:
            return json.load(f)

    def _finalize_preemption(self, history: List[float],
                             pending: List[Any]) -> None:
        """The preempt flag is set and the step loop has stopped: drain
        what the device still owes, fence the writer, publish a final
        snapshot + marker, and surface :class:`PreemptedError`."""
        try:
            history.extend(_flat_losses(jax.device_get(pending)))
        except Exception:
            logger.exception(
                "async step failure surfaced while draining losses during "
                "preemption; the final snapshot still reflects the last "
                "good params")
        pending.clear()
        snap = None
        if self._ckpt_dir:
            try:
                self._ckpt_writer.wait()
            except RuntimeError:
                logger.exception(
                    "background checkpoint write had failed; the "
                    "preemption snapshot below replaces it")
            snap = file_io.join(self._ckpt_dir,
                                f"snapshot-{self.global_step}")
            self._write_snapshot(snap, self._snapshot_tree())
            with file_io.fopen(file_io.join(self._ckpt_dir, PREEMPT_MARKER),
                               "w") as f:
                json.dump({"global_step": self.global_step,
                           "epoch": self.epoch,
                           "snapshot": f"snapshot-{self.global_step}",
                           "resumable": True}, f)
        if self._train_writer is not None:
            self._train_writer.flush()
            self._val_writer.flush()
        raise PreemptedError(
            f"training preempted (SIGTERM) at step {self.global_step}"
            + (f"; resume from {snap}" if snap
               else "; no checkpoint dir configured — progress lost"),
            snapshot=snap)

    def _train_impl(self, train_set: FeatureSet, batch_size: int,
                    epochs: Optional[int] = None,
                    end_trigger: Optional[Trigger] = None,
                    validation_set: Optional[FeatureSet] = None,
                    validation_trigger: Optional[Trigger] = None,
                    checkpoint_trigger: Optional[Trigger] = None,
                    steps_per_dispatch: int = 1) -> Dict[str, Any]:
        """``steps_per_dispatch > 1`` runs K train steps per device dispatch
        (host stacks K batches, the device scans over them): trigger checks,
        per-step TB scalars and loss syncs then happen every K steps —
        interval triggers (``SeveralIteration``) fire whenever a boundary is
        crossed inside the K-step group (quantized to the group boundary,
        never skipped) — and ``MaxIteration`` end triggers may overshoot by
        up to K-1 steps. Groups never span an epoch boundary."""
        cfg = global_config()
        if end_trigger is None:
            end_trigger = MaxEpoch(epochs if epochs is not None else 1)
        validation_trigger = validation_trigger or EveryEpoch()
        checkpoint_trigger = checkpoint_trigger or self._ckpt_trigger or EveryEpoch()
        local_batch = self.ctx.local_batch(batch_size)
        # the batch axis is sharded over the mesh's data axis only; this host
        # contributes its per-host share of that axis
        from ..parallel.mesh import DATA_AXIS
        dp_size = (self.mesh.shape[DATA_AXIS]
                   if DATA_AXIS in self.mesh.axis_names else 1)
        local_dp = max(1, dp_size // self.ctx.process_count)
        if local_batch % local_dp:
            good = self.ctx.process_count * local_dp * max(1, local_batch // local_dp)
            raise ValueError(
                f"per-host batch {local_batch} must be divisible by this "
                f"host's {local_dp} data-axis devices; use batch_size={good}")

        _prepare_dataset(train_set, local_batch)
        sample = next(train_set.train_iterator(local_batch))
        self._ensure_initialized(sample[0])
        # freeze()/unfreeze() may have changed since the step was compiled —
        # the frozen set is baked into the jitted program, so compare rather
        # than rely on the model holding a reference back to this estimator
        frozen_now = frozenset(getattr(self.model, "frozen_layers", ()) or ())
        if self._train_step is None or frozen_now != getattr(
                self, "_frozen_at_build", frozenset()):
            self._frozen_at_build = frozen_now
            self._train_step = self._build_train_step()
            self._multi_step = None  # closes over _train_step
            # first dispatch of a fresh step fn is compile-dominated: the
            # profiler books it as phase=compile, not dispatch
            self._prof_fresh_dispatch = True
            self._prof_cost_done = False
            # the sharded-embedding engine counts its exchange bytes at
            # trace time; a fresh step fn re-traces, so re-attribute
            self._embed_step_bytes = None
            _embed_engine.reset_trace_bytes()
        if self._tb and self._train_writer is None:
            log_dir, app = self._tb
            self._train_writer = SummaryWriter(os.path.join(log_dir, app, "train"))
            self._val_writer = SummaryWriter(os.path.join(log_dir, app, "validation"))

        batches_per_epoch = train_set.num_batches(local_batch)
        slice_bounds = train_set.slice_boundaries(local_batch)
        state = TrainingState(epoch=self.epoch, iteration=self.global_step,
                              num_slices=train_set.num_slices)

        retry_budget = int(cfg.get("failure.retry_times"))
        retry_window = float(cfg.get("failure.retry_interval_s"))
        retries_left = retry_budget
        last_failure = float("-inf")  # monotonic domain: no epoch-0 anchor
        history: List[float] = []
        pending: List[Any] = []  # device loss scalars, drained per epoch
        # only sync loss to host per-step when something consumes it; otherwise
        # jax's async dispatch pipelines the whole epoch without host stalls
        # (duck-typed callables without requires_loss are treated as consumers)
        need_loss = (self._tb is not None
                     or getattr(end_trigger, "requires_loss", True)
                     or getattr(validation_trigger, "requires_loss", True)
                     or getattr(checkpoint_trigger, "requires_loss", True))

        # the data pipeline is part of the checkpoint: expose enough state for
        # _snapshot_tree to record "which permutation, how far in"
        self._active_train_set = train_set
        self._batches_per_epoch = batches_per_epoch
        self._local_batch = local_batch

        while not end_trigger(state):
            skip = 0
            resumable = hasattr(train_set, "data_state")
            if getattr(self, "_restore_data", None) is not None and resumable:
                rng_json, skip, saved_batch = self._restore_data
                self._restore_data = None
                train_set.set_data_state(rng_json)
                if skip and saved_batch and saved_batch != local_batch:
                    raise ValueError(
                        f"resuming a mid-epoch snapshot taken with per-host "
                        f"batch {saved_batch} using batch {local_batch} would "
                        f"replay the wrong records; resume with the original "
                        f"batch size (or from an epoch-boundary snapshot)")
                skip = min(skip, batches_per_epoch)
            self._epoch_data_state = (train_set.data_state() if resumable
                                      else None)
            group = max(1, int(steps_per_dispatch))
            host_it = train_set.train_iterator(local_batch, skip_batches=skip)
            if group > 1:
                if self._multi_step is None:
                    self._multi_step = self._build_multi_step()
                    self._prof_fresh_dispatch = True
                    self._prof_cost_done = False
                    self._embed_step_bytes = None
                    _embed_engine.reset_trace_bytes()
                host_it = _group_host_batches(
                    host_it, batches_per_epoch - skip, batches_per_epoch,
                    group)
                feed = DeviceFeed(
                    host_it, self.mesh,
                    shard_fn=lambda m, b: shard_batch(m, b, batch_axis=1))
            else:
                feed = DeviceFeed(host_it, self.mesh)
            epoch_iter = skip
            self._epoch_offset = epoch_iter
            prof = _profiler.enabled()
            step_source = (_profiled_feed(feed, _P_TRAIN) if prof
                           else iter(feed))
            try:
                for x, y in step_source:
                    # chaos site: a firing injection models a chip/tunnel
                    # failure at step dispatch — caught by the elastic
                    # retry below exactly like a real one
                    faults.inject("train.step")
                    step_start = time.perf_counter()
                    if group > 1:
                        g = jax.tree_util.tree_leaves(x)[0].shape[0]
                        with time_it("train_step"):
                            (self.params, self.opt_state, self.model_state,
                             losses) = self._multi_step(
                                self.params, self.opt_state,
                                self.model_state, self.root_rng,
                                np.int32(self.global_step), x, y)
                        loss = losses[-1]
                    else:
                        g = 1
                        step_rng = jax.random.fold_in(self.root_rng,
                                                      self.global_step)
                        with time_it("train_step"):
                            (self.params, self.opt_state, self.model_state,
                             loss) = self._train_step(
                                self.params, self.opt_state, self.model_state,
                                step_rng, x, y)
                        losses = loss
                    if prof:
                        now = time.perf_counter()
                        _P_TRAIN.add(
                            "compile" if self._prof_fresh_dispatch
                            else "dispatch", now - step_start,
                            start=step_start)
                        self._prof_fresh_dispatch = False
                        if not self._prof_cost_done:
                            self._prof_cost_done = True
                            self._wire_step_cost(group, x, y)
                        # explicit fence: device compute becomes its own
                        # phase instead of hiding inside the loss sync —
                        # profiling trades the async pipeline for this
                        t_x = time.perf_counter()
                        # zoolint: disable=jit-host-sync — deliberate profiling fence (prof mode trades the async pipeline for phase attribution)
                        jax.block_until_ready(losses)
                        _P_TRAIN.add("execute", time.perf_counter() - t_x,
                                     start=t_x)
                    self.global_step += g
                    epoch_iter += g
                    self._epoch_offset = epoch_iter
                    state.iteration = self.global_step
                    state.dispatch_width = g
                    pending.append(losses)

                    if need_loss:
                        with _P_TRAIN.phase("fetch"):
                            # device sync point
                            # zoolint: disable=jit-host-sync — gated: runs only when a trigger/writer consumes the loss
                            loss_val = float(loss)
                        state.loss = loss_val
                        if self._train_writer is not None:
                            lr = self.optimizer.learning_rate
                            lr_val = (float(lr(self.global_step)) if callable(lr)  # zoolint: disable=jit-host-sync — host-side LR schedule, evaluated behind the gated loss sync
                                      else float(lr))
                            self._train_writer.add_scalar("Loss", loss_val,
                                                          self.global_step)
                            self._train_writer.add_scalar("LearningRate", lr_val,
                                                          self.global_step)
                            # per-iteration Throughput (reference
                            # Topology.scala:218-224): timed over dispatch +
                            # the loss sync just above, which bounds this
                            # step's device work — validation/checkpoint time
                            # between steps is deliberately NOT counted
                            step_time = time.perf_counter() - step_start
                            if step_time > 0:
                                global_batch = (local_batch * g
                                                * self.ctx.process_count)
                                self._train_writer.add_scalar(
                                    "Throughput", global_batch / step_time,
                                    self.global_step)

                    # telemetry: one histogram sample per dispatch (the
                    # sync above is inside the window when it ran, so the
                    # recorded time bounds this step's device work) + the
                    # examples throughput counter
                    _M_STEP.observe(time.perf_counter() - step_start)
                    _M_EXAMPLES.inc(local_batch * g)
                    if self._embed_step_bytes is None:
                        # the first dispatch traced the step: the engine's
                        # accumulator now holds ONE step's exchange bytes
                        self._embed_step_bytes = \
                            _embed_engine.take_trace_bytes()
                    ex_b, gr_b = self._embed_step_bytes
                    if ex_b or gr_b:
                        _embed_engine.note_exchange_bytes(ex_b * g, gr_b * g)
                    if prof:
                        _P_TRAIN.step_end()

                    state.epoch_finished = epoch_iter >= batches_per_epoch
                    # boundaries CROSSED by this dispatch (g > 1 can jump
                    # over several sub-epoch slice marks at once)
                    crossed = sum(1 for b in slice_bounds
                                  if epoch_iter - g < b <= epoch_iter)
                    if state.epoch_finished and crossed == 0:
                        crossed = 1
                    state.slice_index += crossed
                    if state.epoch_finished:
                        # drain device losses inside the try: this is the sync
                        # point where async step failures surface so the
                        # checkpoint-retry path below can catch them, and it
                        # bounds the number of live device scalars
                        # zoolint: disable=jit-host-sync — per-EPOCH drain, not per-step: the sanctioned pattern
                        history.extend(_flat_losses(jax.device_get(pending)))
                        pending.clear()
                        self._drain_moe_drops()
                        state.epoch += 1
                        self.epoch = state.epoch

                    if validation_set is not None and validation_trigger(state):
                        results = self.evaluate(validation_set, batch_size)
                        state.score = next(iter(results.values()), None)
                        if self._val_writer is not None:
                            for k, v in results.items():
                                self._val_writer.add_scalar(k, v, self.global_step)
                    if self._ckpt_dir and checkpoint_trigger(state):
                        self._save_snapshot()
                    if faults.inject("train.preempt"):
                        self._preempt_requested = True
                    if (self._preempt_requested or state.epoch_finished
                            or end_trigger(state)):
                        break
                if not state.epoch_finished and not end_trigger(state):
                    # featureset exhausted mid-epoch (shouldn't happen: endless)
                    state.epoch_finished = True
                    state.epoch += 1
            except Exception:
                # elasticity: retry from newest checkpoint (Topology.scala:1180-1262)
                now = time.monotonic()
                if now - last_failure > retry_window:
                    retries_left = retry_budget  # sparse failures reset budget
                last_failure = now
                retries_left -= 1
                pending.clear()  # discard losses from the failed dispatch
                try:
                    # drain a failed BACKGROUND write separately: it must not
                    # consume the retry or mask the step failure being
                    # retried (snapshot writes are atomic-publish, so the
                    # newest intact snapshot is still loadable)
                    self._ckpt_writer.wait()
                except RuntimeError:
                    logger.exception(
                        "background checkpoint write had failed; retrying "
                        "from the newest intact snapshot anyway")
                if retries_left < 0 or not self._snapshot_candidates():
                    # budget exhausted (or nothing to restore from):
                    # surface the error — but restore the newest VALID
                    # snapshot first, so the estimator's params are a
                    # known-good state the caller can still save/serve
                    if self._restore_latest_valid() is not None:
                        logger.error(
                            "retry budget exhausted after %d attempts; "
                            "params restored to the newest valid snapshot "
                            "(step %d) before surfacing the failure",
                            retry_budget + 1, self.global_step)
                    raise
                logger.exception(
                    "training step failed; resuming from checkpoint "
                    "(%d retries left)", retries_left)
                # a torn/corrupt NEWEST snapshot must not kill the retry:
                # fall back past checksum-invalid snapshots to the newest
                # valid one
                if self._restore_latest_valid() is None:
                    logger.error(
                        "no restorable snapshot survived validation; "
                        "surfacing the original step failure")
                    raise
                state.epoch = self.epoch
                state.iteration = self.global_step
                continue
            finally:
                # epochs usually end by `break` with the feed still mid-epoch;
                # stop its producer thread and release prefetched device batches
                feed.close()
            if self._preempt_requested:
                self._finalize_preemption(history, pending)
            state.epoch_finished = False

        if pending:
            # trailing drain (end_trigger fired mid-epoch): an async failure
            # here means params are in an undefined state — restore the newest
            # checkpoint so the estimator stays usable, then surface the error
            try:
                history.extend(_flat_losses(jax.device_get(pending)))
            except Exception:
                if self._ckpt_dir and self._snapshot_candidates():
                    logger.exception(
                        "trailing training step failed; restoring newest "
                        "valid checkpoint before surfacing the error")
                    self._restore_latest_valid()
                raise
            finally:
                pending.clear()
        if self._train_writer is not None:
            self._train_writer.flush()
            self._val_writer.flush()
        # train() must not return with a checkpoint still writing (and a
        # failed background write must surface to the caller)
        self._ckpt_writer.wait()
        return {"loss_history": history, "iterations": self.global_step}

    # -- evaluate (Estimator.evaluate / InternalDistriOptimizer eval) ---------

    def evaluate(self, val_set: FeatureSet, batch_size: int) -> Dict[str, float]:
        """Pipelined evaluation: host gather/shard for batch N+1 runs on the
        DeviceFeed producer thread while the device computes batch N, and
        metric accumulation stays ON DEVICE (the eval step folds each batch
        into the metric-state carry) — the whole pass syncs to host exactly
        once, in :func:`metrics.compute_all`. ``eval.async = False`` falls
        back to the synchronous per-batch loop (``sync_eval``)."""
        if self.direct_loss_fn is not None and not self.metrics:
            return self._evaluate_direct(val_set, batch_size)
        if not self.metrics:
            self.metrics = [metrics_mod.Loss(self.loss_fn)]
        local_batch = min(self.ctx.local_batch(batch_size), val_set.size)
        ndev = self.mesh.devices.size
        local_batch = max(ndev, (local_batch // ndev) * ndev)
        if not global_config().get("eval.async"):
            from . import sync_eval
            return sync_eval.evaluate_sync(self, val_set, batch_size,
                                           local_batch)
        # ONE iterator pass: streaming sets restart their generator per
        # eval_iterator call, so peeking with a second iterator would decode
        # the first batch twice on every evaluation — the first batch is
        # consumed here for initialization and chained back into the feed
        import itertools
        _prepare_dataset(val_set, local_batch)
        it = val_set.eval_iterator(local_batch, pad_remainder=True)
        try:
            first = next(it)
        except StopIteration:
            raise ValueError("validation set produced no batches") from None
        self._ensure_initialized(first[0])
        if self._eval_step is None:
            self._eval_step = self._build_eval_step()
        metric_states = [
            jax.device_put(m.init_state(), replicated(self.mesh))
            for m in self.metrics]
        host_it = masked_eval_batches(itertools.chain([first], it),
                                      local_batch)
        prof = _profiler.enabled()
        with DeviceFeed(host_it, self.mesh, shard_fn=shard_payload,
                        profile_loop="eval" if prof else None) as feed:
            for (bx, by, bm), _ in feed:
                t_d = time.perf_counter() if prof else 0.0
                metric_states = self._eval_step(self.params, self.model_state,
                                                metric_states, bx, by, bm)
                if prof:
                    _profiler.record_phase(
                        "eval", "dispatch", time.perf_counter() - t_d,
                        start=t_d)
        if prof:
            # the single host sync of the pass: everything blocked here
            # is the fetch phase
            t_f = time.perf_counter()
            out = metrics_mod.compute_all(self.metrics, metric_states)
            _profiler.record_phase("eval", "fetch",
                                   time.perf_counter() - t_f, start=t_f)
            return out
        return metrics_mod.compute_all(self.metrics, metric_states)

    def _evaluate_direct_exact(self, val_set: FeatureSet, batch_size: int
                               ) -> Dict[str, float]:
        """Per-example masked eval — ZERO tail bias on any process
        topology: pad rows (and whole valid=0 re-fed batches on short
        hosts) contribute nothing, the result is
        sum(valid per-record losses) / global valid count, identical to a
        single-process pass over the concatenated shards. One compile
        shape total (the mask is data)."""
        import math

        pe = self.direct_eval_per_example_fn
        multiproc = self.ctx.process_count > 1
        if not multiproc and val_set.size == 0:
            raise ValueError("validation set is empty (0 records)")
        ndev = self.mesh.devices.size
        local_batch = self.ctx.local_batch(batch_size)
        if not multiproc:
            local_batch = min(local_batch, val_set.size)
        local_batch = max(ndev, (local_batch // ndev) * ndev)
        _prepare_dataset(val_set, local_batch)
        n_local = math.ceil(val_set.size / local_batch)
        if multiproc:
            from jax.experimental import multihost_utils as mhu
            counts = np.asarray(mhu.process_allgather(
                np.asarray([n_local], np.int64)))
            if counts.min() == 0:
                raise ValueError(
                    "a host has an empty validation shard; every process "
                    "needs at least one batch for the collective eval")
            n_steps = int(counts.max())
        else:
            n_steps = n_local
        sample = next(val_set.eval_iterator(local_batch, pad_remainder=True))
        self._ensure_initialized(sample[0])
        if self._direct_pe_step is None:
            def step(p, s, rng, x, y, mask):
                losses = pe(p, s, rng, x, y)
                return (jnp.sum(losses.astype(jnp.float32) * mask),
                        jnp.sum(mask))

            self._direct_pe_step = jax.jit(step)
        if not global_config().get("eval.async"):
            from . import sync_eval
            return sync_eval.evaluate_direct_exact_sync(
                self, val_set, local_batch, n_steps)
        eval_rng = jax.random.PRNGKey(0)

        def host_batches():
            it = val_set.eval_iterator(local_batch, pad_remainder=True)
            last = None
            for _ in range(n_steps):
                try:
                    x, y, valid = next(it)
                    last = (x, y)
                except StopIteration:  # short host re-feeds mask all-zero
                    (x, y), valid = last, 0
                mask = (np.arange(local_batch) < valid).astype(np.float32)
                yield x, y, mask

        # per-batch (loss-sum, valid-count) scalars stay on device; the
        # dispatch loop never blocks — ONE device_get drains the pass
        pending: List[Any] = []
        prof = _profiler.enabled()
        with DeviceFeed(host_batches(), self.mesh,
                        profile_loop="eval" if prof else None) as feed:
            for bx, by, bm in feed:
                t_d = time.perf_counter() if prof else 0.0
                pending.append(self._direct_pe_step(
                    self.params, self.model_state, eval_rng, bx, by, bm))
                if prof:
                    _profiler.record_phase(
                        "eval", "dispatch", time.perf_counter() - t_d,
                        start=t_d)
        if prof:
            t_f = time.perf_counter()
            total, weight = _drain_sum_pairs(pending)
            _profiler.record_phase("eval", "fetch",
                                   time.perf_counter() - t_f, start=t_f)
        else:
            total, weight = _drain_sum_pairs(pending)
        if weight == 0:
            raise ValueError(
                f"validation set is empty ({val_set.size} records)")
        return {"loss": total / weight}

    def _evaluate_direct(self, val_set: FeatureSet, batch_size: int
                         ) -> Dict[str, float]:
        """Record-weighted average of the captured loss (direct-loss capture
        mode: the loss fn sees the raw batch, so padding cannot be masked).
        Single process: full batches run sharded and the tail runs UNPADDED
        through the same jitted step (one extra compile at the tail shape) —
        exact. Multi-process: every host runs the same number of
        identically-shaped padded steps (batch count agreed by allgather),
        tail batches weighted by their global valid count — every record
        counts; see the inline note for the tail-pad approximation."""
        if self.direct_eval_per_example_fn is not None:
            return self._evaluate_direct_exact(val_set, batch_size)
        multiproc = self.ctx.process_count > 1
        ndev = self.mesh.devices.size
        local_batch = self.ctx.local_batch(batch_size)
        if not multiproc:
            # single process may clamp to the data; multi-process must NOT —
            # local_batch derives from batch_size alone there, so every host
            # compiles the same global shape regardless of its shard size
            local_batch = min(local_batch, val_set.size)
        local_batch = max(ndev, (local_batch // ndev) * ndev)
        _prepare_dataset(val_set, local_batch)
        if multiproc:
            # all-hosts-agree padded-tail eval: every host runs the SAME
            # number of identically-shaped sharded steps (the black-box
            # direct loss is a global-batch program — per-host early exit
            # or shape changes would diverge SPMD). The full per-step
            # valid-count schedule is known upfront on every host, so ONE
            # allgather (before any data is touched — an empty shard fails
            # collectively, not with a bare StopIteration leaving peers
            # hung) exchanges both the batch counts and the weights. Short
            # hosts re-feed their last batch with valid=0. Tail batches are
            # weighted by their GLOBAL valid count — the pad rows (repeats
            # of the last row) leave an O(pad/batch) bias on that one
            # batch's mean, but every record is counted (previously tails
            # were silently dropped).
            import math

            from jax.experimental import multihost_utils as mhu
            n_local = math.ceil(val_set.size / local_batch)
            cap = int(np.asarray(mhu.process_allgather(
                np.asarray([n_local], np.int64))).max())
            sched = np.zeros(cap + 1, np.int64)
            sched[0] = n_local
            for t in range(n_local):
                sched[t + 1] = min(val_set.size - t * local_batch,
                                   local_batch)
            all_sched = np.asarray(mhu.process_allgather(sched)
                                   ).reshape(self.ctx.process_count, cap + 1)
            if all_sched[:, 0].min() == 0:
                raise ValueError(
                    "a host has an empty validation shard; every process "
                    "needs at least one batch for the collective eval")
            n_global = cap
            v_globals = all_sched[:, 1:].sum(axis=0)  # per-step weights
            sample = next(val_set.eval_iterator(local_batch,
                                                pad_remainder=True))
            self._ensure_initialized(sample[0])
            if self._direct_eval_step is None:
                direct = self.direct_eval_loss_fn
                self._direct_eval_step = jax.jit(
                    lambda p, s, rng, x, y: direct(p, s, rng, x, y)[0])
            if not global_config().get("eval.async"):
                from . import sync_eval
                return sync_eval.evaluate_direct_multiproc_sync(
                    self, val_set, local_batch, n_global, v_globals)
            eval_rng = jax.random.PRNGKey(0)

            def host_batches():
                it = val_set.eval_iterator(local_batch, pad_remainder=True)
                last = None
                for t in range(n_global):
                    try:
                        x, y, _ = next(it)
                        last = (x, y)
                    except StopIteration:
                        x, y = last
                    yield (x, y), int(v_globals[t])

            pending: List[Any] = []
            with DeviceFeed(host_batches(), self.mesh,
                            shard_fn=shard_payload) as feed:
                for (xs, ys), w in feed:
                    pending.append((self._direct_eval_step(
                        self.params, self.model_state, eval_rng, xs, ys), w))
            total, weight = _drain_weighted_losses(pending)
            return {"loss": total / weight}
        sample = next(val_set.eval_iterator(local_batch, pad_remainder=True))
        self._ensure_initialized(sample[0])
        if self._direct_eval_step is None:
            direct = self.direct_eval_loss_fn
            self._direct_eval_step = jax.jit(
                lambda p, s, rng, x, y: direct(p, s, rng, x, y)[0])
        if not global_config().get("eval.async"):
            from . import sync_eval
            return sync_eval.evaluate_direct_single_sync(
                self, val_set, local_batch)
        eval_rng = jax.random.PRNGKey(0)

        def shard_full(mesh, item):
            # single-process: full batches shard over the data axis; the
            # tail evaluates exactly via a replicated-batch compile at its
            # true size (host arrays pass straight into the jitted step)
            (x, y), valid = item
            if valid == local_batch:
                return shard_batch(mesh, (x, y)), valid
            return (x, y), valid

        def host_batches():
            for x, y, valid in val_set.eval_iterator(local_batch,
                                                     pad_remainder=False):
                yield (x, y), valid

        pending: List[Any] = []
        with DeviceFeed(host_batches(), self.mesh,
                        shard_fn=shard_full) as feed:
            for (x, y), valid in feed:
                pending.append((self._direct_eval_step(
                    self.params, self.model_state, eval_rng, x, y), valid))
        total, weight = _drain_weighted_losses(pending)
        if weight == 0:
            raise ValueError(
                f"validation set is empty ({val_set.size} records)")
        return {"loss": total / weight}

    # -- predict (TFNet/Predictable equivalent) -------------------------------

    def predict(self, x, batch_size: int = 32):
        """Pipelined prediction: batches stream through the DeviceFeed and a
        bounded window of ``eval.predict_window`` dispatches stays in
        flight — results are fetched (trimmed to their valid rows) BEHIND
        the dispatch frontier, so the host→device upload of batch N+K, the
        device compute of N+1..N+K-1, and the device→host download of batch
        N all overlap. ``eval.async = False`` falls back to the synchronous
        fetch-per-batch loop."""
        if not isinstance(x, HostDataset):
            x = FeatureSet.from_ndarrays(x, None, shuffle=False, shard=False)
        local_batch = min(self.ctx.local_batch(batch_size), x.size)
        ndev = self.mesh.devices.size
        local_batch = max(ndev, (local_batch // ndev) * ndev)
        _prepare_dataset(x, local_batch)
        sample = next(x.eval_iterator(local_batch, pad_remainder=True))
        self._ensure_initialized(sample[0])
        if self._predict_step is None:
            self._predict_step = self._build_predict_step()
        cfg = global_config()
        if not cfg.get("eval.async"):
            from . import sync_eval
            return sync_eval.predict_sync(self, x, local_batch)
        window = max(1, int(cfg.get("eval.predict_window")))

        def host_batches():
            for bx, _, valid in x.eval_iterator(local_batch,
                                                pad_remainder=True):
                yield bx, valid

        def fetch(y, valid):
            # device→host download of a batch K dispatches behind the
            # frontier — the one place predict touches host memory
            return jax.tree_util.tree_map(
                lambda t: np.asarray(t)[:valid], y)

        from collections import deque
        outs = []
        inflight: "deque" = deque()
        with DeviceFeed(host_batches(), self.mesh,
                        shard_fn=shard_payload) as feed:
            for bx, valid in feed:
                inflight.append(
                    (self._predict_step(self.params, self.model_state, bx),
                     valid))
                if len(inflight) > window:
                    outs.append(fetch(*inflight.popleft()))
        while inflight:
            outs.append(fetch(*inflight.popleft()))
        if isinstance(outs[0], (list, tuple)):
            return type(outs[0])(
                np.concatenate([o[i] for o in outs]) for i in range(len(outs[0])))
        return np.concatenate(outs)

    # -- params / checkpoint --------------------------------------------------

    def get_params(self):
        return jax.tree_util.tree_map(np.asarray, self.params)

    def set_params(self, params) -> None:
        self._maybe_add_vocab_rules()
        sharding = param_sharding(self.mesh, params, self.param_rules)
        self.params = jax.device_put(params, sharding)

    def set_model_state(self, state) -> None:
        """Install non-trainable model state (e.g. imported BN statistics).
        An explicit empty tree marks the model as deliberately stateless."""
        self.model_state = jax.device_put(
            state, param_sharding(self.mesh, state, self.param_rules))
        self._state_resolved = True

    def _snapshot_tree(self):
        if self.opt_state is None and self.params is not None:
            # saving a compiled-but-never-stepped model: materialize the
            # optimizer state so the checkpoint restores against the same
            # structure a trained snapshot has
            self.opt_state = self._init_opt_state(self.params)
        tree = {
            "params": jax.tree_util.tree_map(np.asarray, self.params),
            "opt_state": jax.tree_util.tree_map(np.asarray, self.opt_state),
            "model_state": jax.tree_util.tree_map(np.asarray, self.model_state),
            "meta": {"global_step": self.global_step, "epoch": self.epoch},
        }
        ts = getattr(self, "_active_train_set", None)
        if ts is not None and hasattr(ts, "data_state"):
            # data-pipeline state: an epoch-end snapshot records the post-epoch
            # RNG (next epoch starts fresh); a mid-epoch one records the
            # epoch-START rng + batches consumed so resume replays the same
            # permutation from the same position. JSON→uint8 so orbax treats
            # it as a plain array leaf.
            if self._epoch_offset >= self._batches_per_epoch:
                rng_json, offset = ts.data_state(), 0
            else:
                rng_json, offset = self._epoch_data_state, self._epoch_offset
            tree["meta"]["data_rng"] = np.frombuffer(
                rng_json.encode(), dtype=np.uint8).copy()
            tree["meta"]["data_offset"] = offset
            tree["meta"]["data_batch"] = self._local_batch
        return tree

    def _save_snapshot(self) -> None:
        """Async snapshot: device→host copy NOW (the only part the train
        loop waits for), serialize+write on the background writer thread.
        ``submit`` fences the previous write first, so at most one snapshot
        is in flight and ordering is preserved. Crash safety: on the
        single-process local path, writes go to a ``.writing`` staging dir
        published by atomic rename, so a crash between copy and write
        leaves the previous snapshot intact; multi-process saves rely on
        orbax's own collective commit protocol, and remote URIs upload via
        a staging dir WITHOUT an atomic publish (object stores can't
        rename atomically) — a crash can tear a remote snapshot, which the
        per-snapshot checksum manifest catches at restore, falling back to
        the next-older valid snapshot. Retention pruning
        (``checkpoint.keep``) runs after each publish on the writer
        thread."""
        path = file_io.join(self._ckpt_dir, f"snapshot-{self.global_step}")
        tree = self._snapshot_tree()  # device fetch, synchronous

        def write_then_prune():
            self._write_snapshot(path, tree)
            self._prune_snapshots()

        self._ckpt_writer.submit(write_then_prune)

    def _snapshot_candidates(self) -> List[Tuple[int, str]]:
        """``(step, path)`` for every published snapshot, ascending by
        step. Only names of the exact ``snapshot-<int>`` form qualify:
        ``.writing`` staging dirs are excluded by a real suffix check (a
        substring test would also hide a valid snapshot whose path merely
        CONTAINS '.writing'), and entries whose step suffix is not an
        integer — foreign dirs, editor droppings — are skipped instead of
        crashing the restore path."""
        if not self._ckpt_dir or not file_io.isdir(self._ckpt_dir):
            return []
        out: List[Tuple[int, str]] = []
        for d in file_io.listdir(self._ckpt_dir):
            if not d.startswith("snapshot-") or d.endswith(".writing"):
                continue
            try:
                step = int(d[len("snapshot-"):])
            except ValueError:
                continue
            out.append((step, file_io.join(self._ckpt_dir, d)))
        out.sort()
        return out

    def _latest_snapshot(self) -> Optional[str]:
        cands = self._snapshot_candidates()
        return cands[-1][1] if cands else None

    def _restore_latest_valid(self) -> Optional[str]:
        """Restore the newest snapshot that passes checksum-manifest and
        structure validation, transparently falling back past torn or
        corrupt newer ones. Returns the restored path, or ``None`` when no
        snapshot survives."""
        for _step, path in reversed(self._snapshot_candidates()):
            try:
                self.load_checkpoint(path)
                return path
            except Exception:
                _M_CKPT_FALLBACK.inc()
                logger.exception(
                    "snapshot %s failed to restore; falling back to the "
                    "next older snapshot", path)
        return None

    def _prune_snapshots(self) -> None:
        """Retention: keep the newest ``checkpoint.keep`` snapshots (the
        fallback candidates torn-newest recovery needs) and delete the
        rest — bounded disk growth without giving up elasticity. Runs on
        the writer thread after each successful publish; multi-process
        pods prune on process 0 only (the dir is shared)."""
        keep = int(global_config().get("checkpoint.keep") or 0)
        if keep <= 0 or (self.ctx.process_count > 1
                         and jax.process_index() != 0):
            return
        cands = self._snapshot_candidates()
        for _step, path in cands[:-keep]:
            try:
                file_io.rmtree(path)
                logger.info("pruned old snapshot %s (checkpoint.keep=%d)",
                            path, keep)
            except Exception:
                logger.exception("failed to prune old snapshot %s", path)

    def save_checkpoint(self, path: str) -> None:
        """Write a snapshot (synchronous public API; the train loop's
        triggered snapshots go through the async writer instead). EVERY
        process must call this: orbax's save is a collective (it barriers
        across ``jax.process_count()`` processes and elects process 0 as
        the writer) — gating it to rank 0 deadlocks the pod at the barrier.
        Remote URIs (``gs://...``) are written via a local staging dir (the
        reference's HDFS-aware save, ``common/Utils.scala:97``)."""
        self._ckpt_writer.wait()  # order behind any in-flight async write
        self._write_snapshot(path, self._snapshot_tree())

    def _write_snapshot(self, path: str, tree) -> None:
        with time_it("ckpt.write"):
            self._write_snapshot_impl(path, tree)

    def _write_snapshot_impl(self, path: str, tree) -> None:
        import orbax.checkpoint as ocp

        # chaos site: a firing injection models the writer dying before
        # any publish — the previous snapshot must stay the newest intact
        faults.inject("ckpt.write")
        write_t0 = time.perf_counter()
        import shutil
        ckptr = ocp.PyTreeCheckpointer()
        if file_io.is_remote(path):
            import tempfile
            tmp = tempfile.mkdtemp(prefix="zoo_snap_")
            try:
                ckptr.save(os.path.join(tmp, "ckpt"), tree, force=True)
                # manifest computed over the local staging tree BEFORE the
                # upload: on object stores (no atomic rename) it is the
                # only way restore can tell a torn upload from a whole one
                _write_manifest(tmp)
                if file_io.isdir(path):
                    # re-writing this step (elastic replay / preemption
                    # colliding with a triggered write): orbax file names
                    # are content-addressed per write, so uploading over
                    # the old objects would leave STALE extras that fail
                    # manifest verification — clear the target first
                    file_io.rmtree(path)
                file_io.put_tree(tmp, path)
            finally:
                shutil.rmtree(tmp, ignore_errors=True)
        else:
            final = os.path.abspath(file_io.local_path(path))
            if self.ctx.process_count > 1:
                # orbax's save is a collective: every process participates
                # and orbax coordinates the write + its own commit
                # atomicity; a per-process stage+rename would race ranks
                ckptr.save(final, tree, force=True)
                # the save is globally complete once it returns (orbax
                # barriers) — each rank now seals its participation; a
                # rank killed in this window leaves a snapshot that
                # FAILS verification, so elastic resume falls back to
                # the previous fully-sealed one instead of trusting it
                rank = jax.process_index()
                stamp = os.path.join(final, _RANK_STAMP_FMT.format(rank))
                with open(stamp, "w") as f:
                    json.dump({"rank": rank,
                               "global_step": self.global_step}, f)
                if rank == 0:  # one writer for the manifest
                    _write_manifest(final, ranks=self.ctx.process_count)
                _M_CKPT_WRITE.observe(time.perf_counter() - write_t0)
                return
            staging = final + ".writing"
            if os.path.exists(staging):  # leftover from a killed writer
                shutil.rmtree(staging)
            ckptr.save(staging, tree, force=True)
            _write_manifest(staging)  # sealed into the same atomic publish
            if os.path.exists(final):
                shutil.rmtree(final)
            os.rename(staging, final)  # atomic publish
        _M_CKPT_WRITE.observe(time.perf_counter() - write_t0)
        # chaos site: tear the snapshot AFTER publish — the checksum
        # manifest must catch it at restore and fall back one older
        if faults.inject("ckpt.corrupt"):
            faults.tear_snapshot(path)

    def load_checkpoint(self, path: str) -> None:
        """Restore a snapshot. Restores are data-only (orbax reads arrays,
        never pickled code — the CheckedObjectInputStream concern from the
        reference, ``common/CheckedObjectInputStream.scala:1``, is designed
        away), but the STRUCTURE is still validated before any state is
        touched so a truncated/foreign checkpoint can't half-install."""
        # fence: an in-flight async write may be producing the newest
        # snapshot (or the very one being restored)
        self._ckpt_writer.wait()
        restore_t0 = time.perf_counter()
        verify = bool(global_config().get("checkpoint.verify"))
        if file_io.is_remote(path):
            with file_io.localized(path, "r") as tmp:
                if verify:
                    _verify_manifest(tmp, path)
                self._load_checkpoint_local(os.path.join(tmp, "ckpt"))
        else:
            local = os.path.abspath(file_io.local_path(path))
            if verify:
                _verify_manifest(local, path)
            self._load_checkpoint_local(local)
        _M_CKPT_RESTORE.observe(time.perf_counter() - restore_t0)

    def _load_checkpoint_local(self, path: str) -> None:
        import orbax.checkpoint as ocp
        self._maybe_add_vocab_rules()
        ckptr = ocp.PyTreeCheckpointer()
        tree = ckptr.restore(path)
        missing = {"params", "opt_state", "model_state", "meta"} - set(tree)
        if missing:
            raise ValueError(
                f"checkpoint at {path} is not an estimator snapshot "
                f"(missing {sorted(missing)})")
        if self.params is not None:
            live = jax.tree_util.tree_structure(
                jax.tree_util.tree_map(lambda x: 0, self.params))
            loaded = jax.tree_util.tree_structure(
                jax.tree_util.tree_map(lambda x: 0, tree["params"]))
            if live != loaded:
                raise ValueError(
                    f"checkpoint param structure does not match the live "
                    f"model: {loaded} vs {live}")
        # orbax returns optax NamedTuple states as plain containers; re-restore
        # with a live template so the optimizer state keeps its structure.
        live_opt = (self.opt_state if self.opt_state is not None
                    else self._init_opt_state(tree["params"]))
        tree = ckptr.restore(path, item={
            "params": tree["params"],
            "opt_state": live_opt,
            "model_state": tree["model_state"],
            "meta": tree["meta"],
        })
        sharding = param_sharding(self.mesh, tree["params"], self.param_rules)
        self.params = jax.device_put(tree["params"], sharding)
        self.model_state = jax.device_put(
            tree["model_state"],
            param_sharding(self.mesh, tree["model_state"], self.param_rules))
        self.opt_state = jax.device_put(
            tree["opt_state"],
            param_sharding(self.mesh, tree["opt_state"], self._opt_rules()))
        self.global_step = int(tree["meta"]["global_step"])
        self.epoch = int(tree["meta"]["epoch"])
        # a restored model_state (even a legitimately empty one) is final —
        # without this a stateless model burns an rng split rebuilding it,
        # diverging the resumed dropout stream from an uninterrupted run
        self._state_resolved = True
        if "data_rng" in tree["meta"]:
            rng_json = bytes(np.asarray(tree["meta"]["data_rng"])).decode()
            self._restore_data = (rng_json,
                                  int(tree["meta"]["data_offset"]),
                                  int(tree["meta"].get("data_batch", 0)))
