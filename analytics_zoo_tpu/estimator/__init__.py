from .estimator import (CheckpointCorruptError, Estimator,  # noqa: F401
                        PreemptedError)
