from .estimator import Estimator  # noqa: F401
