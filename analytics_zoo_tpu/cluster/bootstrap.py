"""Worker-process bootstrap for multi-process (pod) execution.

Runs inside each spawned worker before any user code: installs the
parent-death guard (the reference guards executor-side processes the same
way — ``JVMGuard``/``ProcessMonitor`` in
``pyzoo/zoo/ray/process.py:51`` kill the forked runtime when the driver
dies), configures the JAX platform/virtual-device flags *before* the backend
initializes, joins the ``jax.distributed`` coordination service, and only
then imports and calls the user target.
"""
from __future__ import annotations

import importlib
import json
import os
import signal
import sys
import threading
import time


def _install_parent_guard() -> None:
    """Exit if the launcher dies: PR_SET_PDEATHSIG where available, plus a
    ppid-watch against the LAUNCHER's pid passed via env (``os.getppid()``
    captured here could already be init's pid if the launcher died before
    this ran — comparing against the env-passed pid covers that window)."""
    launcher_pid = int(os.environ.get("ZOO_TPU_PARENT", os.getppid()))
    try:
        import ctypes
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        PR_SET_PDEATHSIG = 1
        libc.prctl(PR_SET_PDEATHSIG, signal.SIGTERM)
    except Exception:
        pass

    def watch():
        import time
        while True:
            if os.getppid() != launcher_pid:
                os._exit(113)  # parent gone: orphaned worker must not linger
            time.sleep(1.0)

    t = threading.Thread(target=watch, daemon=True, name="parent-guard")
    t.start()


def resolve_target(spec: str):
    """``package.module:function`` → callable."""
    mod_name, _, fn_name = spec.partition(":")
    if not fn_name:
        raise ValueError(f"target '{spec}' must be 'module:function'")
    mod = importlib.import_module(mod_name)
    fn = getattr(mod, fn_name)
    if not callable(fn):
        raise TypeError(f"target {spec} is not callable")
    return fn


def read_coordinator(coord_file: str, timeout_s: float = 60.0) -> str:
    """Coordinator-address handoff: poll ``coord_file`` (written
    atomically by the elastic supervisor before each generation's spawn)
    until it yields an address. A file — not a baked env var — because
    every restarted generation needs a FRESH coordinator port while the
    workers' env stays the launch-time one."""
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            with open(coord_file) as f:
                coord = json.load(f).get("coord", "")
            if coord:
                return coord
        except (OSError, ValueError):
            pass  # not written yet / torn mid-replace: retry
        if time.monotonic() > deadline:
            raise RuntimeError(
                f"no coordinator address in {coord_file!r} after "
                f"{timeout_s}s")
        time.sleep(0.05)


def main() -> int:
    _install_parent_guard()
    proc_id = int(os.environ["ZOO_TPU_PROC_ID"])
    nprocs = int(os.environ["ZOO_TPU_NPROCS"])
    target = os.environ["ZOO_TPU_TARGET"]
    args = json.loads(os.environ.get("ZOO_TPU_ARGS", "[]"))
    platform = os.environ.get("ZOO_TPU_PLATFORM", "")
    dev_per_proc = os.environ.get("ZOO_TPU_DEVICES_PER_PROC", "")
    coord_file = os.environ.get("ZOO_TPU_COORD_FILE", "")
    coord = (read_coordinator(coord_file) if coord_file
             else os.environ["ZOO_TPU_COORD"])

    if dev_per_proc:
        # replace (not append) any inherited device-count flag — e.g. the
        # test harness exports an 8-device one; the last flag would win but
        # being explicit avoids depending on parser ordering
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if not f.startswith("--xla_force_host_platform_device_count")]
        flags.append(f"--xla_force_host_platform_device_count={dev_per_proc}")
        os.environ["XLA_FLAGS"] = " ".join(flags)
    lease_spec = os.environ.get("ZOO_TPU_LEASE_STORE", "")
    if lease_spec:
        # membership lease: start heartbeating BEFORE the distributed
        # join so even a hang inside initialize() shows up as a frozen
        # lease. (Must run after the XLA_FLAGS mutation above — the
        # supervisor module's import chain pulls in jax.)
        from .supervisor import LeaseHeartbeat, make_lease_store
        hb_s = os.environ.get("ZOO_TPU_HEARTBEAT_S", "")
        LeaseHeartbeat(
            make_lease_store(lease_spec), rank=proc_id,
            generation=int(os.environ.get("ZOO_TPU_GENERATION", "0")),
            heartbeat_s=float(hb_s) if hb_s else None).start()
    import jax
    if platform:
        # a sitecustomize may have pinned the hardware platform; re-assert
        # before any backend initializes (same recipe as tests/conftest.py)
        jax.config.update("jax_platforms", platform)
    if platform == "cpu":
        # XLA:CPU executes multi-process programs only through a cross-
        # process collectives layer; jaxlib ships gloo but defaults it off,
        # which surfaces as "Multiprocess computations aren't implemented
        # on the CPU backend" at the first sharded device_put
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # older jax: the only built-in impl is already active
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=nprocs, process_id=proc_id)
    fn = resolve_target(target)
    try:
        result = fn(*args)
    except Exception:
        # Die NOW, not after interpreter teardown: the jax.distributed
        # atexit shutdown barrier cannot complete while peers sit in the
        # collective this rank just abandoned, and the launcher's failure
        # detection only fires once this process is actually dead.
        import traceback
        traceback.print_exc()
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(1)
    if isinstance(result, int):
        return result
    return 0


if __name__ == "__main__":
    sys.exit(main())
