"""Multi-process (pod) launcher — the RayOnSpark/RayContext role, TPU-native.

The reference launches a Ray cluster across Spark executors and guards every
spawned process (``pyzoo/zoo/ray/raycontext.py:190``,
``pyzoo/zoo/ray/process.py:51``). A TPU pod is N host processes each driving
its local chips, coordinated by ``jax.distributed``; what the framework owes
the user is (a) spawning/joining those processes with the coordination
service wired up, (b) failure detection — one worker dying must fail the job
fast, not hang the collective — and (c) cleanup, no orphans.

:class:`PodLauncher` does exactly that for N *local* processes (the CI/simulation
story, and the single-host-many-processes story). On a real multi-host pod the
same worker bootstrap runs once per host under the cluster manager (GKE/ssh),
pointed at host 0 as coordinator.
"""
from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class WorkerResult:
    process_id: int
    returncode: int
    log_path: str
    #: how many times this rank was launched (1 = no retry was needed)
    attempts: int = 1
    #: log tail captured from each FAILED attempt, oldest first (the
    #: final attempt's log is still on disk at ``log_path``)
    attempt_tails: List[str] = field(default_factory=list)

    def log_tail(self, n: int = 40) -> str:
        try:
            with open(self.log_path, "r", errors="replace") as f:
                return "".join(f.readlines()[-n:])
        except OSError:
            return "<no log>"


class PodLaunchError(RuntimeError):
    def __init__(self, msg: str, results: Sequence[WorkerResult]):
        super().__init__(msg)
        self.results = list(results)


@dataclass
class PodLauncher:
    """Spawn ``num_processes`` coordinated workers and wait for them.

    Args:
      num_processes: worker count (``jax.process_count()`` inside workers).
      devices_per_process: if set, each worker gets that many *virtual CPU*
        devices (simulation/CI); leave None on real TPU hosts.
      platform: force a JAX platform inside workers ("cpu" for simulation).
      env: extra environment for workers.
      log_dir: where per-worker stdout/stderr logs go (tempdir default).
      fail_fast: on the first nonzero worker exit, terminate the rest.
      restarts: per-worker retry budget — a rank exiting nonzero is
        relaunched (same rank/env, fresh log) up to this many times
        before its failure is final; each failed attempt's log tail is
        kept on ``WorkerResult.attempt_tails``. Note this retries ONE
        rank into the existing coordination service — right for
        single-process pods and pre-collective crashes; a rank that died
        mid-collective needs the whole-generation restart
        :class:`~analytics_zoo_tpu.cluster.supervisor.ElasticSupervisor`
        provides.
    """

    num_processes: int
    devices_per_process: Optional[int] = None
    platform: str = ""
    env: Dict[str, str] = field(default_factory=dict)
    log_dir: Optional[str] = None
    fail_fast: bool = True
    restarts: int = 0

    def run(self, target: str, args: Sequence[Any] = (),
            timeout: Optional[float] = None) -> List[WorkerResult]:
        """Run ``target`` ("module:function", called with ``*args``) in every
        worker; block until all exit. Raises :class:`PodLaunchError` if any
        worker fails (with log tails for diagnosis)."""
        log_dir = self.log_dir or tempfile.mkdtemp(prefix="zoo_pod_")
        os.makedirs(log_dir, exist_ok=True)
        coord = f"127.0.0.1:{_free_port()}"
        procs: List[subprocess.Popen] = []
        logs: List[str] = []
        base_env = dict(os.environ)
        base_env.update(self.env)
        # workers must resolve imports the way the driver does (repo
        # checkouts on sys.path, the user's creator modules, ...) — same
        # contract as Ray's runtime-env path propagation
        inherited = [p for p in base_env.get("PYTHONPATH", "").split(os.pathsep)
                     if p]
        base_env["PYTHONPATH"] = os.pathsep.join(
            dict.fromkeys([p for p in sys.path if p] + inherited))
        base_env.update({
            "ZOO_TPU_COORD": coord,
            "ZOO_TPU_NPROCS": str(self.num_processes),
            "ZOO_TPU_TARGET": target,
            "ZOO_TPU_ARGS": json.dumps(list(args)),
            "ZOO_TPU_PARENT": str(os.getpid()),
        })
        if self.platform:
            base_env["ZOO_TPU_PLATFORM"] = self.platform
        if self.devices_per_process:
            base_env["ZOO_TPU_DEVICES_PER_PROC"] = str(self.devices_per_process)
        def spawn(pid: int, attempt: int):
            env = dict(base_env)
            env["ZOO_TPU_PROC_ID"] = str(pid)
            suffix = "" if attempt == 1 else f".attempt{attempt}"
            log_path = os.path.join(log_dir, f"worker_{pid}{suffix}.log")
            with open(log_path, "w") as logf:  # child keeps its dup'd fd
                proc = subprocess.Popen(
                    [sys.executable, "-m",
                     "analytics_zoo_tpu.cluster.bootstrap"],
                    env=env, stdout=logf, stderr=subprocess.STDOUT,
                    cwd=os.getcwd())
            return proc, log_path

        try:
            for pid in range(self.num_processes):
                proc, log_path = spawn(pid, 1)
                procs.append(proc)
                logs.append(log_path)
            return self._wait(procs, logs, timeout, spawn)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            deadline = time.monotonic() + 5
            for p in procs:
                if p.poll() is None:
                    try:
                        p.wait(timeout=max(0.1, deadline - time.monotonic()))
                    except subprocess.TimeoutExpired:
                        p.kill()

    def _wait(self, procs, logs, timeout, spawn=None) -> List[WorkerResult]:
        deadline = time.monotonic() + timeout if timeout else None
        n = len(procs)
        attempts = [1] * n
        tails: List[List[str]] = [[] for _ in range(n)]
        while True:
            rcs = [p.poll() for p in procs]
            if spawn is not None and self.restarts > 0:
                # per-worker retry: a failed rank with budget left is
                # relaunched in place (tail captured per attempt) before
                # fail-fast gets to judge it
                for i, rc in enumerate(rcs):
                    if rc not in (None, 0) and attempts[i] <= self.restarts:
                        tails[i].append(WorkerResult(i, rc,
                                                     logs[i]).log_tail())
                        attempts[i] += 1
                        procs[i], logs[i] = spawn(i, attempts[i])
                        rcs[i] = None
            if all(rc is not None for rc in rcs):
                break
            if self.fail_fast and any(rc not in (None, 0) for rc in rcs):
                # failure detection: a dead worker leaves the others blocked
                # in a collective — kill the pod now, surface the failure
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                deadline = time.monotonic() + 5  # reap so returncodes are real
                for p in procs:
                    if p.poll() is None:
                        try:
                            p.wait(timeout=max(0.1, deadline - time.monotonic()))
                        except subprocess.TimeoutExpired:
                            p.kill()
                            p.wait()
                break
            if deadline and time.monotonic() > deadline:
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                results = self._results(procs, logs, attempts, tails)
                raise PodLaunchError(
                    f"pod timed out after {timeout}s", results)
            time.sleep(0.2)
        results = self._results(procs, logs, attempts, tails)
        # -SIGTERM exits are workers WE killed in fail-fast — report them as
        # terminated, not as the failure's cause
        failed = [r for r in results
                  if r.returncode not in (0, -signal.SIGTERM, -signal.SIGKILL)]
        killed = [r for r in results
                  if r.returncode in (-signal.SIGTERM, -signal.SIGKILL)]
        if failed or killed:
            tails = "\n".join(
                f"--- worker {r.process_id} (rc={r.returncode}) ---\n"
                f"{r.log_tail()}" for r in failed)
            note = (f" ({len(killed)} healthy workers terminated by "
                    f"fail-fast)" if killed else "")
            raise PodLaunchError(
                f"{len(failed)}/{self.num_processes} workers failed{note}\n"
                f"{tails}", results)
        return results

    def _results(self, procs, logs, attempts=None,
                 tails=None) -> List[WorkerResult]:
        return [WorkerResult(i, p.poll() if p.poll() is not None else -1,
                             logs[i],
                             attempts=attempts[i] if attempts else 1,
                             attempt_tails=list(tails[i]) if tails else [])
                for i, p in enumerate(procs)]


def run_pod(target: str, num_processes: int, args: Sequence[Any] = (),
            devices_per_process: Optional[int] = None, platform: str = "",
            timeout: Optional[float] = None, **kwargs) -> List[WorkerResult]:
    """One-call form: ``run_pod("pkg.mod:train", 4, args=[...])``."""
    return PodLauncher(num_processes=num_processes,
                       devices_per_process=devices_per_process,
                       platform=platform, **kwargs).run(
        target, args=args, timeout=timeout)
