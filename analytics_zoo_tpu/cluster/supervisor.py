"""Elastic pod supervisor: lease-based membership for the training tier,
a demand-driven actuator for the serving tier (docs/cluster.md).

The reference rides a cluster manager that *supervises*: YARN restarts a
dead executor and ``DistriOptimizer`` retries the epoch from the newest
checkpoint within a ``failure.retryTimes`` budget (``Topology.scala:1180``).
Our :class:`~analytics_zoo_tpu.cluster.launcher.PodLauncher` only launches
— one worker dying kills the pod — and the fleet router only *signals*
(``fleet.desired_instances``) without anything acting on it. This module is
the missing supervisor, for both tiers:

- **Training** (:class:`ElasticSupervisor`): every worker registers a
  lease in a shared membership store (file-backed for CI, Redis-backed via
  the same client plumbing as ``serving/queues.py``) and heartbeats on the
  ``cluster.heartbeat_s`` cadence. The supervisor tracks each lease with
  the ``read_health()`` staleness trick — it stamps its OWN
  ``time.monotonic()`` whenever it *observes* a seq change, so expiry is a
  pure monotonic age and an NTP step on any host cannot fake (or mask) a
  death. A worker exiting nonzero OR a lease freezing past
  ``cluster.lease_expiry_s`` (SIGKILLed host; hung process with a live
  pid) triggers the elastic path: hung pids are SIGKILLed, the surviving
  workers are stopped at the restart barrier (they are parked in a
  ``jax.distributed`` collective that can never complete once a member
  died — the whole generation restarts, the cheap and correct form of
  elasticity for an SPMD pod), and after ``cluster.restart_backoff_s``
  the supervisor respawns the next generation against a FRESH coordinator
  port published through the ``ZOO_TPU_COORD_FILE`` handoff. The job
  resumes from the newest snapshot that passes manifest + per-rank seal
  verification (``_restore_latest_valid``) — proven bit-identical to an
  uninterrupted run in ``tests/test_supervisor.py``.
- **Serving** (:class:`FleetSupervisor`): closes the loop on the router's
  ``fleet.desired_instances`` signal by spawning/draining REAL server
  subprocesses. Scale-out registers the new instance's spool with the
  router; scale-in raises a ``DRAIN_<name>`` flag — the server hands its
  unfinished streams back to the front spool (``handoff(to_queue)``) or
  drains and publishes a terminal ``drained`` health state, either way
  the router re-places every request (zero dropped, exactly one
  terminal).

Chaos sites: ``cluster.heartbeat`` (a worker stops beating — hung-host
model), ``cluster.worker_restart`` (a respawn itself fails — backoff and
retry within budget), ``fleet.scale_actuate`` (an actuation tick fails —
retried next tick, never a half-spawn).
"""
from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..common import faults
from ..common import metrics as _metrics
from ..common.config import global_config
from ..common.utils import wall_clock
from ..ops import alerts as ops_alerts
from ..ops import events as ops_events
from ..ops import incident as ops_incident
from .launcher import WorkerResult, _free_port

logger = logging.getLogger("analytics_zoo_tpu.cluster")

_M_LEASES = _metrics.gauge(
    "cluster.leases_alive",
    "Pod workers whose membership lease the supervisor currently "
    "considers live (seq advanced within the expiry window).")
_M_RESTARTS = _metrics.counter(
    "cluster.restarts_total",
    "Elastic pod-generation restarts, by trigger (exit = nonzero worker "
    "exit, lease = expired lease, respawn = failed respawn retried).",
    labels=("reason",))
_M_SCALE_EVENTS = _metrics.counter(
    "fleet.scale_events_total",
    "Fleet supervisor actuations: server subprocesses spawned (out) or "
    "drained (in) to track fleet.desired_instances.",
    labels=("direction",))

#: ops-plane event types (docs/observability.md "Ops plane")
_E_RESTART = ops_events.event_type(
    "cluster.restart",
    "Elastic pod-generation restart (reason=exit|lease|respawn, "
    "generation).")
_E_LEASE = ops_events.event_type(
    "cluster.lease_expired",
    "A worker's membership lease expired with the process still alive "
    "(hung host); the rank was SIGKILLed.")
_E_HANDOFF = ops_events.event_type(
    "cluster.handoff",
    "A fresh coordinator address was published through the coord-file "
    "handoff for the next pod generation.")
_E_SCALE = ops_events.event_type(
    "fleet.scale",
    "Fleet supervisor actuation (direction=out|in, label=instance).")


# -- membership store ---------------------------------------------------------

class FileLeaseStore:
    """Shared-directory lease store (the CI/single-host backend): one
    ``lease-<rank>.json`` per worker, written atomically (tmp + rename) so
    the supervisor never reads a torn lease."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def spec(self) -> str:
        return self.root

    def _path(self, rank: int) -> str:
        return os.path.join(self.root, f"lease-{rank}.json")

    def write(self, rank: int, lease: Dict[str, Any]) -> None:
        tmp = self._path(rank) + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(lease, f)
        os.replace(tmp, self._path(rank))

    def read_all(self) -> Dict[int, Dict[str, Any]]:
        out: Dict[int, Dict[str, Any]] = {}
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if not (name.startswith("lease-") and name.endswith(".json")):
                continue
            try:
                with open(os.path.join(self.root, name)) as f:
                    lease = json.load(f)
                out[int(name[len("lease-"):-len(".json")])] = lease
            except (OSError, ValueError):
                continue  # torn/garbage lease: same as absent
        return out

    def clear(self) -> None:
        for rank in list(self.read_all()):
            try:
                os.unlink(self._path(rank))
            except OSError:
                pass


class RedisLeaseStore:
    """Redis-hash lease store for real multi-host pods — one HSET field
    per rank, same client plumbing as ``serving.queues.RedisQueue``."""

    def __init__(self, host: str = "localhost", port: int = 6379,
                 namespace: str = "zoo:leases", client=None):
        if client is None:
            import redis  # gated dependency (same as RedisQueue)
            client = redis.StrictRedis(host=host, port=port, db=0)
        self.db = client
        self.host, self.port, self.namespace = host, int(port), namespace

    def spec(self) -> str:
        return f"redis://{self.host}:{self.port}/{self.namespace}"

    def write(self, rank: int, lease: Dict[str, Any]) -> None:
        self.db.hset(self.namespace, mapping={str(rank): json.dumps(lease)})

    def read_all(self) -> Dict[int, Dict[str, Any]]:
        out: Dict[int, Dict[str, Any]] = {}
        for k, v in (self.db.hgetall(self.namespace) or {}).items():
            if isinstance(k, bytes):
                k = k.decode()
            if isinstance(v, bytes):
                v = v.decode()
            if not v:
                continue  # tombstone from clear()
            try:
                out[int(k)] = json.loads(v)
            except ValueError:
                continue
        return out

    def clear(self) -> None:
        # no DEL in the minimal client contract — tombstone every field
        ranks = list(self.read_all())
        if ranks:
            self.db.hset(self.namespace,
                         mapping={str(r): "" for r in ranks})


def make_lease_store(spec: str, client=None):
    """``redis://host:port/namespace`` → :class:`RedisLeaseStore`;
    anything else is a shared directory → :class:`FileLeaseStore`."""
    if spec.startswith("redis://"):
        rest = spec[len("redis://"):]
        hostport, _, namespace = rest.partition("/")
        host, _, port = hostport.partition(":")
        return RedisLeaseStore(host or "localhost", int(port or 6379),
                               namespace or "zoo:leases", client=client)
    return FileLeaseStore(spec)


class LeaseHeartbeat:
    """Worker-side lease pump: a daemon thread bumping this rank's lease
    seq every ``cluster.heartbeat_s``. Started by the bootstrap before
    ``jax.distributed.initialize`` so even a hang INSIDE the collective
    join is visible as lease progress stopping."""

    def __init__(self, store, rank: int, generation: int = 0,
                 heartbeat_s: Optional[float] = None):
        self.store = store
        self.rank = int(rank)
        self.generation = int(generation)
        self.heartbeat_s = (float(heartbeat_s) if heartbeat_s is not None
                            else float(global_config()
                                       .get("cluster.heartbeat_s")))
        self._seq = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat_once(self) -> bool:
        """One lease bump. Returns False when the heartbeat must stop —
        the ``cluster.heartbeat`` chaos site fired (hung-host model: the
        process lives on, the lease freezes)."""
        if faults.inject("cluster.heartbeat"):
            logger.warning("lease heartbeat for rank %d frozen by chaos "
                           "site cluster.heartbeat", self.rank)
            return False
        self._seq += 1
        self.store.write(self.rank, {
            "rank": self.rank, "pid": os.getpid(), "seq": self._seq,
            "generation": self.generation,
            # wall stamp is informational (operator debugging); liveness
            # is judged from seq progress on the SUPERVISOR's monotonic
            # clock, never from arithmetic on this field
            "wall": wall_clock(),
        })
        return True

    def start(self) -> "LeaseHeartbeat":
        self.beat_once()  # register immediately: expiry grace starts now

        def pump():
            while not self._stop.wait(self.heartbeat_s):
                if not self.beat_once():
                    return
        self._thread = threading.Thread(target=pump, daemon=True,
                                        name="lease-heartbeat")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.heartbeat_s + 1)
            self._thread = None


class LeaseTracker:
    """Supervisor-side staleness detector. For every rank it remembers
    the last lease seq it SAW and ``time.monotonic()`` at the moment of
    that observation — the same trick as ``read_health()``'s
    ``health_age_s``, but entirely on the supervisor's clock: a rank is
    expired when *our* monotonic clock says its seq has not advanced for
    ``expiry_s``. Workers that never registered get ``grace_s`` from
    construction (spawn + interpreter start is not a death)."""

    def __init__(self, ranks: Sequence[int], expiry_s: float,
                 grace_s: float):
        now = time.monotonic()
        self.expiry_s = float(expiry_s)
        self.grace_s = float(grace_s)
        self._seen: Dict[int, Tuple[int, float]] = {
            int(r): (-1, now) for r in ranks}

    def update(self, leases: Dict[int, Dict[str, Any]],
               generation: int) -> List[int]:
        """Fold in a fresh store read; returns the ranks whose lease is
        expired NOW. Leases from older generations are ignored (a dead
        rank's stale file must not shadow its replacement)."""
        now = time.monotonic()
        expired: List[int] = []
        for rank, (seq, seen_at) in self._seen.items():
            lease = leases.get(rank)
            cur = (int(lease["seq"])
                   if lease and int(lease.get("generation", 0)) == generation
                   else -1)
            if cur > seq:
                self._seen[rank] = (cur, now)
                continue
            limit = self.expiry_s if seq >= 0 else self.grace_s
            if now - seen_at > limit:
                expired.append(rank)
        return expired

    def alive(self) -> int:
        now = time.monotonic()
        n = 0
        for seq, seen_at in self._seen.values():
            limit = self.expiry_s if seq >= 0 else self.grace_s
            if now - seen_at <= limit:
                n += 1
        return n


# -- training tier ------------------------------------------------------------

class PodSupervisorError(RuntimeError):
    """Raised when the restart budget is exhausted (or the job timed
    out); carries the final generation's :class:`WorkerResult` list."""

    def __init__(self, msg: str, results: Sequence[WorkerResult] = ()):
        super().__init__(msg)
        self.results = list(results)


@dataclass
class SupervisorResult:
    """Outcome of a successful elastic run: the SUCCEEDING generation's
    worker results, plus how much elasticity it took to get there."""
    results: List[WorkerResult]
    generations: int
    restarts: int


@dataclass
class ElasticSupervisor:
    """Run ``target`` ("module:function") across ``num_processes``
    lease-heartbeating workers, restarting the pod generation (with
    backoff, within ``cluster.respawns``) whenever a rank dies or its
    lease expires. Each generation joins a fresh coordinator port
    published through the ``ZOO_TPU_COORD_FILE`` handoff, and the target
    is expected to resume from its newest valid snapshot (the estimator's
    ``_restore_latest_valid`` path)."""

    target: str
    num_processes: int
    args: Sequence[Any] = ()
    devices_per_process: Optional[int] = None
    platform: str = ""
    env: Dict[str, str] = field(default_factory=dict)
    workdir: Optional[str] = None
    lease_store: str = ""  # spec; default = <workdir>/leases file store
    heartbeat_s: Optional[float] = None
    lease_expiry_s: Optional[float] = None
    respawns: Optional[int] = None
    restart_backoff_s: Optional[float] = None
    poll_interval_s: float = 0.05
    #: grace for a rank that never beat yet (interpreter + jax import)
    spawn_grace_s: float = 60.0

    def run(self, timeout: Optional[float] = None) -> SupervisorResult:
        cfg = global_config()
        hb_s = (float(self.heartbeat_s) if self.heartbeat_s is not None
                else float(cfg.get("cluster.heartbeat_s")))
        expiry = (float(self.lease_expiry_s)
                  if self.lease_expiry_s is not None
                  else float(cfg.get("cluster.lease_expiry_s")))
        if expiry <= 0:
            expiry = 6.0 * hb_s
        budget = (int(self.respawns) if self.respawns is not None
                  else int(cfg.get("cluster.respawns")))
        backoff = (float(self.restart_backoff_s)
                   if self.restart_backoff_s is not None
                   else float(cfg.get("cluster.restart_backoff_s")))
        workdir = self.workdir or tempfile.mkdtemp(prefix="zoo_pod_")
        os.makedirs(workdir, exist_ok=True)
        store_spec = self.lease_store or os.path.join(workdir, "leases")
        store = make_lease_store(store_spec)
        coord_file = os.path.join(workdir, "coordinator.json")
        deadline = time.monotonic() + timeout if timeout else None

        generation, restarts = 0, 0
        results: List[WorkerResult] = []
        while True:
            try:
                # chaos site: the respawn (or first spawn) itself fails —
                # a scheduler refusal; back off and retry within budget
                faults.inject("cluster.worker_restart")
                procs, logs = self._spawn_generation(
                    generation, store_spec, coord_file, workdir, hb_s)
            except faults.FaultInjected:
                if restarts >= budget:
                    raise PodSupervisorError(
                        f"pod spawn failed and the respawn budget "
                        f"(cluster.respawns={budget}) is exhausted",
                        results)
                restarts += 1
                _M_RESTARTS.labels(reason="respawn").inc()
                _E_RESTART.emit(reason="respawn", generation=generation)
                logger.warning(
                    "generation %d spawn failed (injected); retrying "
                    "after %.2fs (%d/%d restarts)", generation,
                    backoff, restarts, budget)
                time.sleep(backoff)
                continue
            tracker = LeaseTracker(range(self.num_processes), expiry,
                                   max(self.spawn_grace_s, expiry))
            reason = self._watch_generation(
                procs, tracker, store, generation, deadline)
            if reason is None:  # every rank exited 0: success
                results = self._collect(generation, procs, logs)
                _M_LEASES.set(0)
                return SupervisorResult(results=results,
                                        generations=generation + 1,
                                        restarts=restarts)
            # elastic path: SIGKILL hung ranks, stop the survivors at the
            # restart barrier (they are parked in a collective that can
            # never complete), reap everything, then respawn
            self._stop_generation(procs, reason)
            results = self._collect(generation, procs, logs)
            if reason == "timeout":
                raise PodSupervisorError(
                    f"pod timed out after {timeout}s "
                    f"(generation {generation})", results)
            if restarts >= budget:
                tails = "\n".join(
                    f"--- worker {r.process_id} (rc={r.returncode}) ---\n"
                    f"{r.log_tail()}" for r in results
                    if r.returncode != 0)
                raise PodSupervisorError(
                    f"restart budget (cluster.respawns={budget}) "
                    f"exhausted after generation {generation} "
                    f"({reason})\n{tails}", results)
            restarts += 1
            _M_RESTARTS.labels(reason=reason).inc()
            _E_RESTART.emit(reason=reason, generation=generation)
            logger.warning(
                "generation %d lost a worker (%s); respawning generation "
                "%d after %.2fs (%d/%d restarts)", generation, reason,
                generation + 1, backoff, restarts, budget)
            time.sleep(backoff)
            generation += 1

    # -- internals --------------------------------------------------------

    def _spawn_generation(self, generation: int, store_spec: str,
                          coord_file: str, workdir: str,
                          hb_s: float):
        """Publish a fresh coordinator address through the handoff file,
        then spawn every rank of this generation."""
        coord = f"127.0.0.1:{_free_port()}"
        tmp = coord_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"coord": coord, "generation": generation}, f)
        os.replace(tmp, coord_file)
        _E_HANDOFF.emit(coordinator=coord, generation=generation)

        log_dir = os.path.join(workdir, "logs")
        os.makedirs(log_dir, exist_ok=True)
        base_env = dict(os.environ)
        base_env.update(self.env)
        inherited = [p for p in
                     base_env.get("PYTHONPATH", "").split(os.pathsep) if p]
        base_env["PYTHONPATH"] = os.pathsep.join(
            dict.fromkeys([p for p in sys.path if p] + inherited))
        base_env.update({
            "ZOO_TPU_COORD_FILE": coord_file,
            "ZOO_TPU_NPROCS": str(self.num_processes),
            "ZOO_TPU_TARGET": self.target,
            "ZOO_TPU_ARGS": json.dumps(list(self.args)),
            "ZOO_TPU_PARENT": str(os.getpid()),
            "ZOO_TPU_LEASE_STORE": store_spec,
            "ZOO_TPU_GENERATION": str(generation),
            "ZOO_TPU_HEARTBEAT_S": repr(hb_s),
        })
        base_env.pop("ZOO_TPU_COORD", None)  # the file handoff owns it
        if self.platform:
            base_env["ZOO_TPU_PLATFORM"] = self.platform
        if self.devices_per_process:
            base_env["ZOO_TPU_DEVICES_PER_PROC"] = str(
                self.devices_per_process)
        procs: List[subprocess.Popen] = []
        logs: List[str] = []
        for pid in range(self.num_processes):
            env = dict(base_env)
            env["ZOO_TPU_PROC_ID"] = str(pid)
            log_path = os.path.join(log_dir,
                                    f"gen{generation}_worker{pid}.log")
            logs.append(log_path)
            with open(log_path, "w") as logf:
                procs.append(subprocess.Popen(
                    [sys.executable, "-m",
                     "analytics_zoo_tpu.cluster.bootstrap"],
                    env=env, stdout=logf, stderr=subprocess.STDOUT,
                    cwd=os.getcwd()))
        return procs, logs

    def _watch_generation(self, procs, tracker: LeaseTracker, store,
                          generation: int,
                          deadline: Optional[float]) -> Optional[str]:
        """Poll until the generation succeeds (returns None) or needs a
        restart (returns the reason). Marks hung ranks for the caller by
        SIGKILLing them here, where they are detected."""
        while True:
            rcs = [p.poll() for p in procs]
            if all(rc == 0 for rc in rcs):
                return None
            failed = [i for i, rc in enumerate(rcs)
                      if rc is not None and rc != 0]
            expired = tracker.update(store.read_all(), generation)
            _M_LEASES.set(tracker.alive())
            hung = [r for r in expired if rcs[r] is None]
            for rank in hung:
                _E_LEASE.emit(rank=rank, generation=generation)
                logger.warning(
                    "rank %d lease expired with the process still alive "
                    "(hung host) — SIGKILL pid %d", rank,
                    procs[rank].pid)
                try:
                    procs[rank].kill()
                except OSError:
                    pass
            if failed:
                return "exit"
            if hung:
                return "lease"
            if deadline and time.monotonic() > deadline:
                return "timeout"
            time.sleep(self.poll_interval_s)

    def _stop_generation(self, procs, reason: str) -> None:
        """The restart barrier: no rank of the old generation may survive
        into the new one (a survivor would hold the old coordinator and
        the old mesh). SIGTERM, bounded wait, SIGKILL stragglers."""
        for p in procs:
            if p.poll() is None:
                p.terminate()
        reap_deadline = time.monotonic() + 5
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.1,
                                       reap_deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()

    def _collect(self, generation: int, procs, logs) -> List[WorkerResult]:
        return [WorkerResult(i,
                             p.poll() if p.poll() is not None else -1,
                             logs[i], attempts=generation + 1)
                for i, p in enumerate(procs)]


# -- serving tier -------------------------------------------------------------

def _serve_instance(root: str, name: str, factory_spec: str) -> None:
    """Fleet-instance subprocess body. ``factory_spec`` is a
    "module:function" resolving to ``factory(root, name) -> server`` —
    a ClusterServing/GenerativeServing bound to ``instance_queue(root,
    name)`` with its health file at ``<root>/<name>.health.json``.

    Control files under ``root``: ``READY_<name>`` is raised here once
    serving; ``DRAIN_<name>`` triggers scale-in (generative servers hand
    unfinished streams back to the FRONT spool via ``handoff``, one-shot
    servers drain — either way the terminal health state lets the router
    reclaim the spool); ``DONE`` is fleet-wide shutdown. Every terminal
    this instance posts is journaled to ``<root>/audit/<name>.log`` — the
    exactly-one-terminal evidence chaos tests audit at ``put_result``."""
    from .bootstrap import resolve_target
    factory = resolve_target(factory_spec)
    srv = factory(root, name)

    audit_dir = os.path.join(root, "audit")
    os.makedirs(audit_dir, exist_ok=True)
    audit_path = os.path.join(audit_dir, f"{name}.log")
    queue = srv.queue
    orig_put = queue.put_result

    def audited_put(uri, payload):
        orig_put(uri, payload)
        if isinstance(payload, dict) and ("error" in payload
                                          or "value" in payload):
            with open(audit_path, "a") as f:
                f.write(f"{uri}\n")
    queue.put_result = audited_put

    step = getattr(srv, "serve_once", None) or srv.serve_step
    drain_flag = os.path.join(root, f"DRAIN_{name}")
    done_flag = os.path.join(root, "DONE")
    with open(os.path.join(root, f"READY_{name}"), "w") as f:
        f.write(str(os.getpid()))
    while True:
        if os.path.exists(drain_flag) or os.path.exists(done_flag):
            handoff = getattr(srv, "handoff", None)
            if handoff is not None and not os.path.exists(done_flag):
                # scale-in of a generative server: unfinished streams go
                # back to the front spool with their token prefix so an
                # adopter continues them token-identically
                from ..serving.queues import FileQueue
                handoff(FileQueue(root))
            else:
                srv.drain()
            return
        if not step():
            time.sleep(0.005)


class FleetSupervisor:
    """Actuator for the fleet scale signal: reconciles the live set of
    server subprocesses against ``FleetRouter.desired_instances()``
    (clamped to ``[min_instances, max_instances]``), at most one
    spawn/drain per ``fleet.scale_interval_s`` tick so demand spikes
    produce a ramp, not a thundering herd. Drive :meth:`step` from the
    same loop as ``router.route_once()``."""

    def __init__(self, router, root: str, server_factory: str, *,
                 min_instances: int = 1, max_instances: int = 4,
                 slots: int = 1, scale_interval_s: Optional[float] = None,
                 ready_timeout_s: float = 60.0):
        self.router = router
        self.root = root
        self.server_factory = server_factory
        self.min_instances = int(min_instances)
        self.max_instances = int(max_instances)
        self.slots = int(slots)
        self.scale_interval_s = (
            float(scale_interval_s) if scale_interval_s is not None
            else float(global_config().get("fleet.scale_interval_s")))
        self.ready_timeout_s = float(ready_timeout_s)
        self._procs: Dict[str, Any] = {}
        self._draining: Dict[str, Any] = {}
        self._counter = 0
        self._last_actuate = -1e18  # monotonic

    # -- observers --------------------------------------------------------

    def instance_names(self) -> List[str]:
        return sorted(self._procs)

    def alive_count(self) -> int:
        return sum(1 for p in self._procs.values() if p.is_alive())

    def status(self) -> Dict[str, Any]:
        """Supervisor-side operational status: fleet shape plus the ops
        plane's active alert/incident state, the same stamp servers put
        in ``health.json`` so every ``read_health()``-style consumer
        sees it."""
        return {
            "instances": self.instance_names(),
            "alive": self.alive_count(),
            "draining": sorted(self._draining),
            "alerts": sorted(ops_alerts.active_alerts()),
            "incident": ops_incident.last_incident(),
        }

    # -- actuation --------------------------------------------------------

    def step(self) -> Optional[str]:
        """One reconcile tick. Returns ``"out:<name>"`` / ``"in:<name>"``
        when an actuation happened, else None."""
        self._reap()
        now = time.monotonic()
        if now - self._last_actuate < self.scale_interval_s:
            return None
        desired = max(self.min_instances,
                      min(self.max_instances,
                          self.router.desired_instances()))
        live = len(self._procs)
        if desired == live:
            return None
        self._last_actuate = now
        try:
            # chaos site: the actuation itself fails (spawn refusal,
            # control-plane hiccup) — the fleet must stay consistent and
            # the tick retried on the next cadence
            faults.inject("fleet.scale_actuate")
        except faults.FaultInjected:
            logger.warning("fleet scale actuation aborted by chaos site "
                           "fleet.scale_actuate; retrying next tick")
            return None
        if desired > live:
            name = self._spawn_instance()
            if name is None:
                return None
            _M_SCALE_EVENTS.labels(direction="out").inc()
            _E_SCALE.emit(label=name, direction="out")
            logger.info("fleet scale-out: %s (%d -> %d)", name, live,
                        live + 1)
            return f"out:{name}"
        name = sorted(self._procs)[-1]  # newest instance drains first
        proc = self._procs.pop(name)
        self._draining[name] = proc
        with open(os.path.join(self.root, f"DRAIN_{name}"), "w") as f:
            f.write("1")
        _M_SCALE_EVENTS.labels(direction="in").inc()
        _E_SCALE.emit(label=name, direction="in")
        logger.info("fleet scale-in: draining %s (%d -> %d)", name, live,
                    live - 1)
        return f"in:{name}"

    def _spawn_instance(self) -> Optional[str]:
        import multiprocessing as mp

        from ..serving.fleet import FleetInstance, instance_queue
        name = f"inst{self._counter}"
        self._counter += 1
        ctx = mp.get_context("spawn")
        proc = ctx.Process(target=_serve_instance,
                           args=(self.root, name, self.server_factory),
                           daemon=True)
        proc.start()
        ready = os.path.join(self.root, f"READY_{name}")
        deadline = time.monotonic() + self.ready_timeout_s
        while not os.path.exists(ready):
            if not proc.is_alive() or time.monotonic() > deadline:
                logger.error("instance %s died before READY", name)
                if proc.is_alive():
                    proc.terminate()
                proc.join(timeout=10)
                return None
            time.sleep(0.02)
        self._procs[name] = proc
        self.router.register_instance(FleetInstance(
            name, instance_queue(self.root, name),
            os.path.join(self.root, f"{name}.health.json"),
            slots=self.slots))
        return name

    def _reap(self) -> None:
        """Collect exited subprocesses. A DRAINING instance exiting is
        the normal end of scale-in (remove it from the router — its spool
        was already reclaimed via the terminal health state). A LIVE
        instance exiting without a drain flag was killed: drop its record
        so the scale signal can respawn capacity; the router's staleness
        path reclaims its spool and fails its streams over."""
        for name, proc in list(self._draining.items()):
            if not proc.is_alive():
                proc.join(timeout=1)
                del self._draining[name]
                self.router.remove_instance(name)
        for name, proc in list(self._procs.items()):
            if not proc.is_alive():
                proc.join(timeout=1)
                del self._procs[name]
                logger.warning("fleet instance %s exited unexpectedly "
                               "(rc=%s)", name, proc.exitcode)

    def shutdown(self, timeout_s: float = 30.0) -> None:
        """Fleet-wide stop: raise DONE (every instance drains in-flight
        work and exits), then reap; stragglers are terminated."""
        with open(os.path.join(self.root, "DONE"), "w") as f:
            f.write("1")
        deadline = time.monotonic() + timeout_s
        procs = dict(self._procs)
        procs.update(self._draining)
        for name, proc in procs.items():
            proc.join(timeout=max(0.1, deadline - time.monotonic()))
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=5)
            self.router.remove_instance(name)
        self._procs.clear()
        self._draining.clear()
