"""Pod orchestration: multi-process launch, coordination, failure detection
(the reference's RayOnSpark layer, ``pyzoo/zoo/ray/raycontext.py:190``,
re-designed for TPU pods on ``jax.distributed``)."""
from .launcher import (  # noqa: F401
    PodLauncher, PodLaunchError, WorkerResult, run_pod)
from .supervisor import (  # noqa: F401
    ElasticSupervisor, FleetSupervisor, PodSupervisorError,
    SupervisorResult)
from .torch_trainer import TorchTrainer  # noqa: F401
