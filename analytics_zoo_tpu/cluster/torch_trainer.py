"""Data-parallel training of a *foreign-framework* (PyTorch) model across pod
workers — the role MXNet-on-Ray plays in the reference.

The reference's ``MXNetTrainer`` (``pyzoo/zoo/ray/mxnet/mxnet_trainer.py:26``,
``mxnet_runner.py:1``) takes creator functions (model/optimizer/data), spawns
Ray actors as workers, and runs synchronous data-parallel training with a
KVStore. The TPU-native equivalent keeps the creator-function contract but
rides this framework's own orchestration: :class:`~.launcher.PodLauncher`
spawns and guards the workers (parent-death guard, fail-fast reaping), and
gradient sync is a ``torch.distributed`` gloo all-reduce — host-CPU training
for models that live outside the JAX/XLA world, coordinated by the same pod
machinery the JAX path uses.

Creator functions must be picklable (module-level functions) — the same
contract Ray's cloudpickle imposes on the reference's creators.
"""
from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
from typing import Any, Callable, Dict, List, Optional

from .launcher import PodLauncher, _free_port

__all__ = ["TorchTrainer"]


def _worker(spec_path: str) -> int:
    """Pod worker: rank/world come from the launcher's env; rendezvous over
    gloo; synchronous data-parallel SGD with a flat-bucket all-reduce."""
    import torch
    import torch.distributed as dist

    with open(spec_path, "rb") as f:
        spec = pickle.load(f)
    rank = int(os.environ["ZOO_TPU_PROC_ID"])
    world = int(os.environ["ZOO_TPU_NPROCS"])
    # explicit tcp:// rendezvous: an inherited MASTER_ADDR/MASTER_PORT (e.g.
    # from a SLURM/torchrun parent) must not override the port this launch
    # allocated
    dist.init_process_group(
        "gloo",
        init_method=f"tcp://{spec['master_addr']}:{spec['master_port']}",
        rank=rank, world_size=world)
    try:
        torch.manual_seed(spec["seed"])
        model = spec["model_fn"]()
        # every rank starts from rank 0's init so replicas are identical
        for p in model.parameters():
            dist.broadcast(p.data, src=0)
        optimizer = spec["optimizer_fn"](model)
        loss_fn = spec["loss_fn"]()
        history: List[float] = []
        for _ in range(spec["epochs"]):
            data = spec["data_fn"](rank, world)
            total, count = 0.0, 0
            for x, y in data:
                x = torch.as_tensor(x)
                y = torch.as_tensor(y)
                optimizer.zero_grad()
                loss = loss_fn(model(x), y)
                loss.backward()
                # one flat bucket: a single collective per step, not one per
                # parameter (the KVStore-push/pull role)
                grads = [p.grad for p in model.parameters()
                         if p.grad is not None]
                flat = torch.cat([g.reshape(-1) for g in grads])
                dist.all_reduce(flat, op=dist.ReduceOp.SUM)
                flat /= world
                off = 0
                for g in grads:
                    n = g.numel()
                    g.copy_(flat[off:off + n].reshape(g.shape))
                    off += n
                optimizer.step()
                total += float(loss.detach())
                count += 1
            history.append(total / max(count, 1))
        if rank == 0:
            torch.save(model.state_dict(), spec["state_path"])
            with open(spec["result_path"], "w") as f:
                json.dump({"loss_history": history}, f)
    finally:
        dist.destroy_process_group()
    return 0


class TorchTrainer:
    """Synchronous data-parallel trainer for a PyTorch model over pod workers.

    Args:
      model_fn: ``() -> torch.nn.Module`` (module-level function).
      optimizer_fn: ``(model) -> torch.optim.Optimizer``.
      loss_fn: ``() -> callable(pred, target)``.
      data_fn: ``(rank, world_size) -> iterable of (x, y)`` — each worker's
        shard of the data, re-invoked at every epoch boundary.
      num_workers: pod size.
      seed: broadcast-identical init seed.
    """

    def __init__(self, model_fn: Callable[[], Any],
                 optimizer_fn: Callable[[Any], Any],
                 loss_fn: Callable[[], Any],
                 data_fn: Callable[[int, int], Any],
                 num_workers: int = 2, seed: int = 0,
                 log_dir: Optional[str] = None):
        self.spec = dict(model_fn=model_fn, optimizer_fn=optimizer_fn,
                         loss_fn=loss_fn, data_fn=data_fn, seed=seed)
        self.num_workers = num_workers
        self.log_dir = log_dir
        self._state_dict: Optional[Dict[str, Any]] = None
        self.loss_history: List[float] = []

    def train(self, epochs: int = 1,
              timeout: Optional[float] = None) -> List[float]:
        """Run ``epochs`` over the pod; returns rank-0's per-epoch mean loss.
        The trained weights are available as :meth:`state_dict` after."""
        workdir = tempfile.mkdtemp(prefix="zoo_torch_pod_")
        try:
            spec = dict(self.spec, epochs=epochs,
                        master_addr="127.0.0.1", master_port=_free_port(),
                        state_path=os.path.join(workdir, "state.pt"),
                        result_path=os.path.join(workdir, "result.json"))
            spec_path = os.path.join(workdir, "spec.pkl")
            with open(spec_path, "wb") as f:
                pickle.dump(spec, f)
            # platform=cpu: these workers must not contend for the TPU chip,
            # and N>1 processes cannot share it anyway
            launcher = PodLauncher(num_processes=self.num_workers,
                                   platform="cpu", log_dir=self.log_dir)
            launcher.run("analytics_zoo_tpu.cluster.torch_trainer:_worker",
                         args=[spec_path], timeout=timeout)
            import torch
            self._state_dict = torch.load(spec["state_path"],
                                          weights_only=True)
            with open(spec["result_path"]) as f:
                self.loss_history = json.load(f)["loss_history"]
            return self.loss_history
        finally:
            shutil.rmtree(workdir, ignore_errors=True)

    def state_dict(self) -> Dict[str, Any]:
        if self._state_dict is None:
            raise RuntimeError("train() has not completed")
        return self._state_dict

    def load_into(self, model) -> Any:
        """Load the trained weights into a freshly built torch module."""
        model.load_state_dict(self.state_dict())
        return model
