"""``zoo-tpu-submit`` — launch a training script across pod workers.

The reference submits jobs to the cluster with shell wrappers around
spark-submit (``scripts/spark-submit-python-with-zoo.sh``,
``make-dist.sh``); the TPU-native equivalent wraps :class:`PodLauncher`
(``cluster/launcher.py``): N coordinated worker processes, each joining the
``jax.distributed`` coordination service, running the SAME user script — the
standard multi-controller JAX/TPU-pod execution model.

Modes:

- local run (default): spawn ``--nprocs`` workers on this host and wait.
  ``--devices-per-proc`` + ``--platform cpu`` simulate a pod on one machine
  (CI); on real TPU-VM hosts leave them unset.
- ``--emit k8s``: print a GKE-style manifest skeleton (one worker per pod
  replica, the coordination env each container needs) instead of running —
  the deploy story for real clusters, where a scheduler, not this CLI,
  places the processes.
"""
from __future__ import annotations

import argparse
import os
import runpy
import sys
from typing import List, Optional


def _run_script(script: str, argv: List[str]) -> int:
    """Worker target: execute the user script as ``__main__`` (the worker
    already joined jax.distributed via cluster.bootstrap)."""
    sys.argv = [script] + list(argv)
    script_dir = os.path.dirname(os.path.abspath(script))
    if script_dir not in sys.path:
        sys.path.insert(0, script_dir)
    runpy.run_path(script, run_name="__main__")
    return 0


def _emit_k8s(args, script_args: List[str]) -> str:
    """GKE-style manifest skeleton: a headless service for the coordinator
    plus one worker Job per process, wired with the same env contract the
    local launcher uses."""
    image = args.image or "analytics-zoo-tpu:latest"
    cmd = ["python", args.script] + list(script_args)
    lines = [
        "# zoo-tpu-submit --emit k8s skeleton",
        "# worker 0's pod DNS name is the coordinator; every worker gets the",
        "# same env apart from its rank. Adapt resources/selectors to your",
        "# TPU node pools (e.g. cloud.google.com/gke-tpu-topology).",
        "apiVersion: v1",
        "kind: Service",
        "metadata: {name: zoo-tpu-coord}",
        "spec:",
        "  clusterIP: None",
        "  selector: {app: zoo-tpu-worker, rank: '0'}",
        "  ports: [{port: 8476, name: coord}]",
        "---",
    ]
    for rank in range(args.nprocs):
        lines += [
            "apiVersion: batch/v1",
            "kind: Job",
            f"metadata: {{name: zoo-tpu-worker-{rank}}}",
            "spec:",
            "  template:",
            "    metadata:",
            f"      labels: {{app: zoo-tpu-worker, rank: '{rank}'}}",
            "    spec:",
            "      restartPolicy: Never",
            "      containers:",
            "      - name: worker",
            f"        image: {image}",
            f"        command: {cmd!r}",
            "        env:",
            "        - {name: ZOO_TPU_COORD, value: 'zoo-tpu-coord:8476'}",
            f"        - {{name: ZOO_TPU_NPROCS, value: '{args.nprocs}'}}",
            f"        - {{name: ZOO_TPU_PROC_ID, value: '{rank}'}}",
            "---",
        ]
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="zoo-tpu-submit",
        description="Run a script across coordinated pod workers.")
    ap.add_argument("--nprocs", type=int, default=1,
                    help="number of worker processes")
    ap.add_argument("--devices-per-proc", type=int, default=None,
                    help="virtual CPU devices per worker (simulation/CI)")
    ap.add_argument("--platform", default="",
                    help="force JAX platform in workers (e.g. cpu)")
    ap.add_argument("--timeout", type=float, default=None,
                    help="kill the pod after this many seconds")
    ap.add_argument("--log-dir", default=None,
                    help="per-worker log directory (tempdir default)")
    ap.add_argument("--emit", choices=["k8s"], default=None,
                    help="print a deployment manifest instead of running")
    ap.add_argument("--image", default=None,
                    help="container image for --emit k8s")
    ap.add_argument("script", help="python script to run in every worker")
    ap.add_argument("script_args", nargs=argparse.REMAINDER,
                    help="arguments passed to the script")
    args = ap.parse_args(argv)

    if args.emit == "k8s":
        print(_emit_k8s(args, args.script_args))
        return 0

    from .launcher import PodLauncher
    script = os.path.abspath(args.script)
    if not os.path.exists(script):
        ap.error(f"script not found: {args.script}")
    launcher = PodLauncher(num_processes=args.nprocs,
                           devices_per_process=args.devices_per_proc,
                           platform=args.platform,
                           log_dir=args.log_dir)
    results = launcher.run("analytics_zoo_tpu.cluster.submit:_run_script",
                           args=[script, args.script_args],
                           timeout=args.timeout)
    for r in results:
        print(f"worker {r.process_id}: rc={r.returncode} log={r.log_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
