"""NNFrames — ML-pipeline style estimators over DataFrames (reference
``pipeline/nnframes/NNEstimator.scala:198`` fit→``InternalDistriOptimizer``,
``NNModel:635`` transform = distributed predict, ``NNClassifier.scala``,
``NNImageReader.scala``).

TPU shape: pandas DataFrames play the role of Spark DataFrames; ``fit``
lowers feature/label columns into a FeatureSet (the reference's
``getDataSet:382-412`` with cache level) and drives the shared on-device
Estimator; ``transform`` appends a prediction column. The Spark-ML
``Estimator/Transformer`` param-setter surface is preserved."""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Union

import numpy as np

from ..estimator.estimator import Estimator
from ..feature.featureset import FeatureSet, MemoryType, column_matrix
from ..keras import objectives, optimizers as opt_mod

_column_matrix = column_matrix  # local alias kept for readability below


class NNEstimator:
    def __init__(self, model, criterion="mse",
                 features_col: Union[str, Sequence[str]] = "features",
                 label_col: str = "label"):
        self.model = model
        self.criterion = criterion
        self.features_col = features_col
        self.label_col = label_col
        self.batch_size = 32
        self.max_epoch = 1
        self.optimizer = "adam"
        self.learning_rate: Optional[float] = None
        self.cache_level = MemoryType.DRAM
        self.validation: Optional[tuple] = None
        self._tb: Optional[tuple] = None
        self._ckpt: Optional[tuple] = None

    # -- Spark-ML param surface (NNEstimator setters) -------------------------

    def set_batch_size(self, n: int) -> "NNEstimator":
        self.batch_size = n
        return self

    def set_max_epoch(self, n: int) -> "NNEstimator":
        self.max_epoch = n
        return self

    def set_optim_method(self, optimizer) -> "NNEstimator":
        self.optimizer = optimizer
        return self

    def set_learning_rate(self, lr: float) -> "NNEstimator":
        self.learning_rate = lr
        return self

    def set_data_cache_level(self, level: str) -> "NNEstimator":
        self.cache_level = MemoryType[level.upper()] \
            if isinstance(level, str) else level
        return self

    def set_validation(self, df, trigger=None) -> "NNEstimator":
        self.validation = (df, trigger)
        return self

    def set_tensorboard(self, log_dir: str, app_name: str) -> "NNEstimator":
        self._tb = (log_dir, app_name)
        return self

    def set_checkpoint(self, path: str, trigger=None) -> "NNEstimator":
        self._ckpt = (path, trigger)
        return self

    # -- fit ------------------------------------------------------------------

    def _label_array(self, df) -> np.ndarray:
        y = df[self.label_col].to_numpy()
        if len(y) and isinstance(y[0], (list, tuple, np.ndarray)):
            return np.stack([np.asarray(v, np.float32) for v in y])
        return y.astype(np.float32)

    def _make_estimator(self) -> Estimator:
        opt = self.optimizer
        if isinstance(opt, str):
            opt = opt_mod.get(opt, learning_rate=self.learning_rate)
        return Estimator(model=self.model,
                         loss_fn=objectives.get(self.criterion),
                         optimizer=opt)

    def fit(self, df) -> "NNModel":
        x = _column_matrix(df, self.features_col)
        y = self._label_array(df)
        fs = FeatureSet.from_ndarrays(x, y, memory_type=self.cache_level)
        est = self._make_estimator()
        if self._tb:
            est.set_tensorboard(*self._tb)
        if self._ckpt:
            est.set_checkpoint(*self._ckpt)
        val_fs = None
        val_trigger = None
        if self.validation is not None:
            vdf, val_trigger = self.validation
            val_fs = FeatureSet.from_ndarrays(
                _column_matrix(vdf, self.features_col),
                self._label_array(vdf))
        est.train(fs, batch_size=self.batch_size, epochs=self.max_epoch,
                  validation_set=val_fs, validation_trigger=val_trigger)
        return self._make_model(est)

    def _make_model(self, est: Estimator) -> "NNModel":
        return NNModel(self.model, est, self.features_col)


class NNModel:
    """Fitted transformer: ``transform`` appends ``prediction``
    (reference ``NNModel.transform``, NNEstimator.scala:635)."""

    def __init__(self, model, estimator: Estimator,
                 features_col: Union[str, Sequence[str]] = "features",
                 prediction_col: str = "prediction"):
        self.model = model
        self.estimator = estimator
        self.features_col = features_col
        self.prediction_col = prediction_col
        self.batch_size = 32

    def set_batch_size(self, n: int) -> "NNModel":
        self.batch_size = n
        return self

    def set_prediction_col(self, c: str) -> "NNModel":
        self.prediction_col = c
        return self

    def _predict_array(self, df) -> np.ndarray:
        x = _column_matrix(df, self.features_col)
        return np.asarray(self.estimator.predict(x, batch_size=self.batch_size))

    def transform(self, df):
        preds = self._predict_array(df)
        out = df.copy()
        out[self.prediction_col] = (list(preds) if preds.ndim > 1
                                    else preds.tolist())
        return out

    def save(self, path: str) -> None:
        self.estimator.save_checkpoint(path)

    def load_weights(self, path: str) -> None:
        self.estimator.load_checkpoint(path)


class NNClassifier(NNEstimator):
    """Classification sugar: integer labels, softmax argmax predictions
    (reference ``NNClassifier.scala``)."""

    def __init__(self, model, criterion="sparse_categorical_crossentropy",
                 features_col="features", label_col="label"):
        super().__init__(model, criterion, features_col, label_col)

    def _make_model(self, est: Estimator) -> "NNClassifierModel":
        return NNClassifierModel(self.model, est, self.features_col)


class NNClassifierModel(NNModel):
    def transform(self, df):
        probs = self._predict_array(df)
        out = df.copy()
        out[self.prediction_col] = np.argmax(probs, axis=-1).astype(float)
        return out


class NNImageReader:
    """Read an image folder into a DataFrame with decoded image arrays
    (reference ``NNImageReader.scala``: image schema DataFrame)."""

    @staticmethod
    def read_images(path: str, resize_h: Optional[int] = None,
                    resize_w: Optional[int] = None, with_label: bool = False):
        import pandas as pd
        from ..feature.image import ImageSet, Resize
        iset = ImageSet.read(path, with_label=with_label)
        if resize_h and resize_w:
            iset = iset.transform(Resize(resize_h, resize_w))
        data = {"image": [np.asarray(i, np.float32) for i in iset.images],
                "origin": iset.paths}
        if with_label:
            data["label"] = iset.labels
        return pd.DataFrame(data)
