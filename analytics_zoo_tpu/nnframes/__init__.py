from .nn_estimator import (  # noqa: F401
    NNClassifier, NNClassifierModel, NNEstimator, NNImageReader, NNModel)
