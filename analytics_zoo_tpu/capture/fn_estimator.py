"""FnEstimator — the TFEstimator contract (reference
``pyzoo/zoo/tfpark/estimator.py:30,47,116,174,247``): a single ``model_fn``
drives train/evaluate/predict, and data arrives via ``input_fn(mode)``.

JAX shape of the contract: ``model_fn(params, features, labels, mode, rng)``
returns the mode's value — TRAIN/EVAL: scalar loss; PREDICT: predictions.
``init_fn(rng, sample_features) -> params``."""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

import jax
import numpy as np

from ..estimator.estimator import Estimator
from ..feature.featureset import FeatureSet
from ..keras import optimizers as opt_mod
from .fn_layer import FunctionalModel


class ModeKeys:
    TRAIN = "train"
    EVAL = "eval"
    PREDICT = "predict"


class FnEstimator:
    def __init__(self, model_fn: Callable, init_fn: Callable,
                 optimizer="adam", metrics: Optional[Sequence] = None):
        self.model_fn = model_fn
        model = FunctionalModel(
            init_fn=lambda rng, sx: (init_fn(rng, sx), {}),
            apply_fn=lambda p, s, x, training, rng: (
                model_fn(p, x, None, ModeKeys.PREDICT, rng), s),
            name="fn_estimator_model")

        def direct(params, model_state, rng, x, y):
            return self.model_fn(params, x, y, ModeKeys.TRAIN, rng), model_state

        def direct_eval(params, model_state, rng, x, y):
            return self.model_fn(params, x, y, ModeKeys.EVAL, rng), model_state

        self.estimator = Estimator(
            model=model, loss_fn=lambda y, yp: 0.0,
            optimizer=opt_mod.get(optimizer), metrics=metrics,
            direct_loss_fn=direct, direct_eval_loss_fn=direct_eval)

    def _featureset(self, input_fn: Callable, mode: str) -> FeatureSet:
        data = input_fn(mode)
        from ..feature.featureset import HostDataset
        if isinstance(data, HostDataset):
            return data
        if mode == ModeKeys.PREDICT:
            # contract: PREDICT input_fn returns features only — a LIST for
            # multi-input models; a 2-TUPLE is read as (features, labels)
            # from a mode-shared input_fn and the labels are dropped
            if type(data) is tuple and len(data) == 2:
                data = data[0]
            # predictions must cover every row on every host — no sharding
            return FeatureSet.from_ndarrays(data, None, shuffle=False,
                                            shard=False)
        if isinstance(data, tuple) and len(data) == 2:
            return FeatureSet.from_ndarrays(*data)
        return FeatureSet.from_ndarrays(data, None, shuffle=False)

    def train(self, input_fn: Callable, batch_size: int = 32,
              epochs: int = 1, **kwargs) -> Dict[str, Any]:
        fs = self._featureset(input_fn, ModeKeys.TRAIN)
        return self.estimator.train(fs, batch_size=batch_size, epochs=epochs,
                                    **kwargs)

    def evaluate(self, input_fn: Callable, batch_size: int = 32
                 ) -> Dict[str, float]:
        fs = self._featureset(input_fn, ModeKeys.EVAL)
        return self.estimator.evaluate(fs, batch_size=batch_size)

    def predict(self, input_fn: Callable, batch_size: int = 32) -> np.ndarray:
        fs = self._featureset(input_fn, ModeKeys.PREDICT)
        return self.estimator.predict(fs, batch_size=batch_size)
