"""GANEstimator — alternating generator/discriminator training (reference
``pyzoo/zoo/tfpark/gan/gan_estimator.py`` + ``GanOptimMethod.scala``: the
Scala side interleaves d_steps/g_steps inside one BigDL optimizer).

TPU design: one jitted ``gan_step`` runs ``d_steps`` discriminator updates
then ``g_steps`` generator updates via ``lax.fori_loop`` — the whole
alternation is a single XLA program per batch, no host ping-pong."""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..common.context import get_context
from ..feature.featureset import FeatureSet
from ..feature.device_feed import DeviceFeed
from ..keras import optimizers as opt_mod
from ..parallel.mesh import replicated


class GANEstimator:
    """``generator_fn(g_params, noise)``; ``discriminator_fn(d_params, x)``;
    loss fns follow tf.gan conventions:
    ``generator_loss_fn(fake_logits)``,
    ``discriminator_loss_fn(real_logits, fake_logits)``."""

    def __init__(self, generator_fn: Callable, discriminator_fn: Callable,
                 generator_loss_fn: Callable, discriminator_loss_fn: Callable,
                 generator_init_fn: Callable, discriminator_init_fn: Callable,
                 generator_optimizer="adam", discriminator_optimizer="adam",
                 noise_dim: int = 32, d_steps: int = 1, g_steps: int = 1,
                 seed: int = 0):
        self.generator_fn = generator_fn
        self.discriminator_fn = discriminator_fn
        self.generator_loss_fn = generator_loss_fn
        self.discriminator_loss_fn = discriminator_loss_fn
        self.generator_init_fn = generator_init_fn
        self.discriminator_init_fn = discriminator_init_fn
        self.g_opt = opt_mod.get(generator_optimizer)
        self.d_opt = opt_mod.get(discriminator_optimizer)
        self.noise_dim = noise_dim
        self.d_steps = d_steps
        self.g_steps = g_steps
        self.ctx = get_context()
        self.mesh = self.ctx.mesh
        self.rng = jax.random.PRNGKey(seed)
        self.g_params = None
        self.d_params = None
        self._step_fn = None
        self.global_step = 0

    def _ensure_initialized(self, sample_x):
        if self.g_params is not None:
            return
        self.rng, gk, dk = jax.random.split(self.rng, 3)
        batch = np.asarray(sample_x).shape[0]
        noise = jnp.zeros((batch, self.noise_dim))
        self.g_params = jax.device_put(self.generator_init_fn(gk, noise),
                                       replicated(self.mesh))
        fake = self.generator_fn(self.g_params, noise)
        self.d_params = jax.device_put(self.discriminator_init_fn(dk, fake),
                                       replicated(self.mesh))
        self.g_opt_state = self.g_opt.init(self.g_params)
        self.d_opt_state = self.d_opt.init(self.d_params)

    def _build_step(self):
        gen, disc = self.generator_fn, self.discriminator_fn
        g_loss_fn, d_loss_fn = self.generator_loss_fn, self.discriminator_loss_fn
        g_opt, d_opt = self.g_opt, self.d_opt
        d_steps, g_steps, noise_dim = self.d_steps, self.g_steps, self.noise_dim

        def one_d_update(i, carry):
            g_p, d_p, g_os, d_os, rng, real, _, gl = carry
            rng, nk = jax.random.split(rng)
            noise = jax.random.normal(nk, (real.shape[0], noise_dim))

            def d_loss(dp):
                fake = gen(g_p, noise)
                return d_loss_fn(disc(dp, real), disc(dp, fake))

            dl, grads = jax.value_and_grad(d_loss)(d_p)
            updates, d_os = d_opt.update(grads, d_os, d_p)
            d_p = optax.apply_updates(d_p, updates)
            return (g_p, d_p, g_os, d_os, rng, real, dl, gl)

        def one_g_update(i, carry):
            g_p, d_p, g_os, d_os, rng, real, dl, _ = carry
            rng, nk = jax.random.split(rng)
            noise = jax.random.normal(nk, (real.shape[0], noise_dim))

            def g_loss(gp):
                return g_loss_fn(disc(d_p, gen(gp, noise)))

            gl, grads = jax.value_and_grad(g_loss)(g_p)
            updates, g_os = g_opt.update(grads, g_os, g_p)
            g_p = optax.apply_updates(g_p, updates)
            return (g_p, d_p, g_os, d_os, rng, real, dl, gl)

        def gan_step(g_p, d_p, g_os, d_os, rng, real):
            carry = (g_p, d_p, g_os, d_os, rng, real,
                     jnp.float32(0), jnp.float32(0))
            carry = jax.lax.fori_loop(0, d_steps, one_d_update, carry)
            carry = jax.lax.fori_loop(0, g_steps, one_g_update, carry)
            g_p, d_p, g_os, d_os, _, _, dl, gl = carry
            return g_p, d_p, g_os, d_os, dl, gl

        return jax.jit(gan_step, donate_argnums=(0, 1, 2, 3))

    def train(self, x, batch_size: int = 32, steps: int = 100
              ) -> Dict[str, Any]:
        from ..feature.featureset import HostDataset
        fs = x if isinstance(x, HostDataset) else \
            FeatureSet.from_ndarrays(np.asarray(x, np.float32))
        local_batch = self.ctx.local_batch(batch_size)
        it = fs.train_iterator(local_batch)
        feed = DeviceFeed(it, self.mesh)
        pending = []  # device loss scalars, drained periodically: keeps
        d_hist, g_hist = [], []  # dispatch async but bounds live buffers and
        drain_every = 100        # surfaces async failures promptly

        def drain():
            for d, g in jax.device_get(pending):
                d_hist.append(float(d))
                g_hist.append(float(g))
            pending.clear()

        try:
            for _ in range(steps):
                real, _ = next(feed)
                self._ensure_initialized(real)
                if self._step_fn is None:
                    self._step_fn = self._build_step()
                self.rng, step_rng = jax.random.split(self.rng)
                (self.g_params, self.d_params, self.g_opt_state,
                 self.d_opt_state, dl, gl) = self._step_fn(
                    self.g_params, self.d_params, self.g_opt_state,
                    self.d_opt_state, step_rng, real)
                self.global_step += 1
                pending.append((dl, gl))
                if len(pending) >= drain_every:
                    drain()
        finally:
            feed.close()  # train stops mid-iterator; stop the producer thread
        drain()
        return {"d_loss_history": d_hist, "g_loss_history": g_hist,
                "iterations": self.global_step}

    def generate(self, n: int = 16) -> np.ndarray:
        if self.g_params is None:
            raise RuntimeError("train first")
        self.rng, nk = jax.random.split(self.rng)
        noise = jax.random.normal(nk, (n, self.noise_dim))
        return np.asarray(self.generator_fn(self.g_params, noise))
