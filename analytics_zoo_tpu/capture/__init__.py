"""Capture-style training APIs — the TFPark equivalent (SURVEY §2.5).

The reference captures arbitrary TF1 graphs (``tf_optimizer.py:342``
``from_loss``/``from_keras``/``from_train_op``; ``estimator.py:30``
``TFEstimator``; ``model.py:34`` tfpark ``KerasModel``). On TPU nothing needs
"capturing": a JAX function *is* the graph. This package keeps the same
user contracts over plain functions / flax / haiku models:

- :class:`GraphModel` — ``from_loss`` (user loss fn), ``from_forward``
  (user forward fn + named loss), ``from_flax`` / ``from_haiku`` (module
  capture), each driving the shared on-device Estimator loop.
- :class:`FnEstimator` — ``model_fn(params, features, labels, mode, rng)``
  with TRAIN/EVAL/PREDICT modes and ``input_fn(mode)`` datasets
  (≙ ``TFEstimator``).
- :class:`GANEstimator` — alternating generator/discriminator optimization
  (≙ ``gan_estimator.py`` + ``GanOptimMethod.scala``).
- text estimators: :class:`BERTClassifier` etc. over the native BERT layer
  (≙ ``tfpark/text/estimator``).
"""
from .graph_model import GraphModel  # noqa: F401
from .fn_estimator import FnEstimator, ModeKeys  # noqa: F401
from .gan import GANEstimator  # noqa: F401
from .text import BERTClassifier, BERTNER, BERTSQuAD  # noqa: F401
from .lm import TransformerLM  # noqa: F401
