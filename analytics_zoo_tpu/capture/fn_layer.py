"""Adapter: (init_fn, apply_fn) pairs as Estimator-compatible model objects.

The Estimator's contract is the Keras ``Layer`` protocol (``build`` →
(params, state), pure ``call``). A :class:`FunctionalModel` satisfies it for
any functional model — hand-written JAX, flax ``Module.init/apply``, haiku
``transform`` — so captured models reuse the whole distributed loop,
checkpointing, elasticity and metrics without translation (the reference
needed ``TFTrainingHelper`` to fake a BigDL Layer around a TF graph;
here the adapter is ~60 lines because the contracts already align)."""
from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import numpy as np


def _zeros_from_shape(shape):
    """Batch-1 concrete zeros for a (None, ...) shape spec (init needs
    concrete arrays, shapes come from the Estimator)."""
    import jax.numpy as jnp
    if isinstance(shape, list):
        return [_zeros_from_shape(s) for s in shape]
    if isinstance(shape, dict):
        return {k: _zeros_from_shape(v) for k, v in shape.items()}
    return jnp.zeros(tuple(1 if d is None else d for d in shape))


class FunctionalModel:
    """``init_fn(rng, sample_x) -> (params, state)``;
    ``apply_fn(params, state, x, training, rng) -> (y, new_state)``."""

    def __init__(self, init_fn: Callable, apply_fn: Callable,
                 name: str = "functional_model"):
        self.init_fn = init_fn
        self.apply_fn = apply_fn
        self.name = name

    def build(self, rng, input_shape) -> Tuple[Any, Any]:
        return self.init_fn(rng, _zeros_from_shape(input_shape))

    def call(self, params, state, inputs, *, training: bool = False,
             rng: Optional[jax.Array] = None):
        return self.apply_fn(params, state, inputs, training, rng)


def from_flax_module(module, method=None) -> FunctionalModel:
    """Wrap a ``flax.linen.Module``. Mutable collections (e.g. batch_stats)
    ride the Estimator's model_state."""

    def init_fn(rng, sample_x):
        variables = module.init(rng, sample_x)
        params = variables.get("params", {})
        state = {k: v for k, v in variables.items() if k != "params"}
        return params, state

    def apply_fn(params, state, x, training, rng):
        variables = {"params": params, **state}
        mutable = list(state.keys()) if training and state else False
        kwargs = {}
        if rng is not None:
            kwargs["rngs"] = {"dropout": rng}
        out = module.apply(variables, x, mutable=mutable, method=method,
                           **kwargs)
        if mutable:
            y, new_state = out
            return y, dict(new_state)
        return out, state

    return FunctionalModel(init_fn, apply_fn, name=type(module).__name__)


def from_haiku_transformed(transformed) -> FunctionalModel:
    """Wrap a ``haiku.transform``/``transform_with_state`` result."""
    import haiku as hk
    with_state = isinstance(transformed, hk.TransformedWithState)

    def init_fn(rng, sample_x):
        out = transformed.init(rng, sample_x)
        if with_state:
            return out  # (params, state)
        return out, {}

    def apply_fn(params, state, x, training, rng):
        if with_state:
            return transformed.apply(params, state, rng, x)
        return transformed.apply(params, rng, x), state

    return FunctionalModel(init_fn, apply_fn, name="haiku_model")
