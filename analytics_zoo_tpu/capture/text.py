"""BERT task estimators (reference ``pyzoo/zoo/tfpark/text/estimator/``:
``bert_base.py:108`` BERTBaseEstimator, ``bert_classifier.py:57``,
``bert_ner.py:49``, ``bert_squad.py:77``) rebuilt over the native BERT layer.

Each wraps BERT + a task head into a compiled Keras model whose inputs are
the standard 4-tensor pack [token_ids, token_type_ids, position_ids,
attention_mask]."""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

from ..keras import Sequential
from ..keras.engine import Layer
from ..keras.layers import BERT, Dense, Dropout, Lambda


def bert_input_pack(token_ids: np.ndarray,
                    token_type_ids: Optional[np.ndarray] = None,
                    attention_mask: Optional[np.ndarray] = None):
    """Build the 4-array BERT input: defaults type ids to 0, positions to
    arange, mask to nonzero-token."""
    token_ids = np.asarray(token_ids)
    b, s = token_ids.shape
    if token_type_ids is None:
        token_type_ids = np.zeros((b, s), np.int32)
    if attention_mask is None:
        attention_mask = (token_ids != 0).astype(np.float32)
    positions = np.broadcast_to(np.arange(s, dtype=np.int32), (b, s)).copy()
    return [token_ids.astype(np.int32), np.asarray(token_type_ids, np.int32),
            positions, np.asarray(attention_mask, np.float32)]


class _BERTTask(Sequential):
    """Sequential over [BERT, head...] that still takes the 4-input pack."""


def _make_bert(bert_config: Dict[str, Any]) -> BERT:
    defaults = dict(vocab=30522, hidden_size=768, n_block=12, n_head=12,
                    max_position_len=512, intermediate_size=3072,
                    output_all_block=False)
    defaults.update(bert_config or {})
    defaults["output_all_block"] = False
    return BERT(**defaults)


class BERTClassifier:
    """Sequence classification over the pooled output
    (≙ ``BERTClassifier``, bert_classifier.py:57)."""

    def __init__(self, num_classes: int, bert_config: Optional[Dict] = None,
                 dropout: float = 0.1, optimizer="adam"):
        bert = _make_bert(bert_config)
        self.model = _BERTTask([
            bert,
            Lambda(lambda outs: outs[-1], name="take_pooled"),
            Dropout(dropout),
            Dense(num_classes, activation="softmax", name="classifier"),
        ])
        self.model.compile(optimizer, "sparse_categorical_crossentropy",
                           metrics=["accuracy"])

    def fit(self, token_ids, labels, batch_size=32, epochs=1, **bert_inputs):
        x = bert_input_pack(token_ids, bert_inputs.get("token_type_ids"),
                            bert_inputs.get("attention_mask"))
        return self.model.fit(x, np.asarray(labels, np.float32),
                              batch_size=batch_size, nb_epoch=epochs)

    def predict(self, token_ids, batch_size=32, **bert_inputs):
        x = bert_input_pack(token_ids, bert_inputs.get("token_type_ids"),
                            bert_inputs.get("attention_mask"))
        return self.model.predict(x, batch_size=batch_size)

    def evaluate(self, token_ids, labels, batch_size=32, **bert_inputs):
        x = bert_input_pack(token_ids, bert_inputs.get("token_type_ids"),
                            bert_inputs.get("attention_mask"))
        return self.model.evaluate(x, np.asarray(labels, np.float32),
                                   batch_size=batch_size)


class BERTNER:
    """Token-level tagging over the last block states
    (≙ ``BERTNER``, bert_ner.py:49)."""

    def __init__(self, num_entities: int, bert_config: Optional[Dict] = None,
                 dropout: float = 0.1, optimizer="adam"):
        bert = _make_bert(bert_config)
        self.model = _BERTTask([
            bert,
            Lambda(lambda outs: outs[0], name="take_states"),
            Dropout(dropout),
            Dense(num_entities, activation="softmax", name="tagger"),
        ])
        self.model.compile(optimizer, "sparse_categorical_crossentropy")

    def fit(self, token_ids, tag_ids, batch_size=32, epochs=1, **bert_inputs):
        x = bert_input_pack(token_ids, bert_inputs.get("token_type_ids"),
                            bert_inputs.get("attention_mask"))
        return self.model.fit(x, np.asarray(tag_ids, np.float32),
                              batch_size=batch_size, nb_epoch=epochs)

    def predict(self, token_ids, batch_size=32, **bert_inputs):
        x = bert_input_pack(token_ids, bert_inputs.get("token_type_ids"),
                            bert_inputs.get("attention_mask"))
        return self.model.predict(x, batch_size=batch_size)


class _SQuADHead(Layer):
    """Start/end span logits from sequence states: Dense(2) split."""

    def __init__(self, name=None):
        super().__init__(name)

    def build(self, rng, input_shape):
        import jax
        hidden = input_shape[-1]
        k = jax.random.normal(rng, (hidden, 2)) * 0.02
        import jax.numpy as jnp
        return {"kernel": k, "bias": jnp.zeros((2,))}, {}

    def call(self, params, state, inputs, *, training=False, rng=None):
        import jax.numpy as jnp
        logits = inputs @ params["kernel"] + params["bias"]  # [b, s, 2]
        start, end = logits[..., 0], logits[..., 1]
        return [jnp.asarray(start), jnp.asarray(end)], state

    def compute_output_shape(self, input_shape):
        return [(input_shape[0], input_shape[1])] * 2


class BERTSQuAD:
    """Extractive QA span prediction (≙ ``BERTSQuAD``, bert_squad.py:77).
    Labels: [start_positions, end_positions]."""

    def __init__(self, bert_config: Optional[Dict] = None, optimizer="adam"):
        bert = _make_bert(bert_config)
        self.model = _BERTTask([
            bert,
            Lambda(lambda outs: outs[0], name="take_states"),
            _SQuADHead(name="squad_head"),
        ])

        def span_loss(y, y_pred):
            import jax.numpy as jnp
            from ..keras.objectives import (
                sparse_categorical_crossentropy_from_logits as ce)
            start_logits, end_logits = y_pred
            start_y, end_y = y[:, 0], y[:, 1]
            return 0.5 * (ce(start_y, start_logits) + ce(end_y, end_logits))

        self.model.compile(optimizer, span_loss)

    def fit(self, token_ids, spans, batch_size=32, epochs=1, **bert_inputs):
        x = bert_input_pack(token_ids, bert_inputs.get("token_type_ids"),
                            bert_inputs.get("attention_mask"))
        return self.model.fit(x, np.asarray(spans, np.float32),
                              batch_size=batch_size, nb_epoch=epochs)

    def predict(self, token_ids, batch_size=32, **bert_inputs):
        """Returns (start_logits, end_logits)."""
        x = bert_input_pack(token_ids, bert_inputs.get("token_type_ids"),
                            bert_inputs.get("attention_mask"))
        return self.model.predict(x, batch_size=batch_size)
