"""GraphModel — capture a user-defined training computation.

Parity map (reference ``pyzoo/zoo/tfpark/tf_optimizer.py``):
- ``from_loss`` ≙ ``TFOptimizer.from_loss:493`` — user supplies the whole
  loss function; grads/optimizer/allreduce happen in the shared loop.
- ``from_forward`` ≙ ``TFOptimizer.from_keras:578`` — forward fn + named
  objective.
- ``from_flax``/``from_haiku`` ≙ tfpark ``KerasModel.fit`` (model.py:88) —
  framework-module capture.
- a user-supplied optax transform ≙ ``from_train_op:455`` — the
  ``TFTrainingHelperV2``/``ZooOptimizer`` contract (grads are averaged
  across replicas, then the *user's* optimizer applies them) holds by
  construction: XLA inserts the psum, the optax chain is the train op.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import numpy as np

from ..estimator.estimator import Estimator
from ..feature.featureset import FeatureSet
from ..keras import objectives, optimizers as opt_mod
from .fn_layer import FunctionalModel, from_flax_module, from_haiku_transformed


class GraphModel:
    """fit/evaluate/predict over a captured functional model."""

    def __init__(self, estimator: Estimator):
        self.estimator = estimator

    # -- constructors ---------------------------------------------------------

    @classmethod
    def from_loss(cls, loss_fn: Callable, init_params_fn: Callable,
                  optimizer="adam", metrics: Optional[Sequence] = None,
                  forward_fn: Optional[Callable] = None,
                  per_example_loss_fn: Optional[Callable] = None
                  ) -> "GraphModel":
        """``loss_fn(params, x, y) -> scalar``;
        ``init_params_fn(rng, sample_x) -> params``. Supply ``forward_fn``
        (``forward(params, x) -> y_pred``) to enable predict/metric
        evaluation — the loss alone doesn't define predictions. Supply
        ``per_example_loss_fn(params, x, y) -> [batch]`` to make padded
        multi-host evaluation exact (pad rows masked out of the sum);
        without it, tail batches carry a documented O(pad/batch) bias."""

        def no_forward(p, s, x, training, rng):
            raise NotImplementedError(
                "GraphModel.from_loss captured only the loss; pass "
                "forward_fn=... to enable predict()/metric evaluate()")

        apply_fn = (no_forward if forward_fn is None else
                    (lambda p, s, x, training, rng: (forward_fn(p, x), s)))
        model = FunctionalModel(
            init_fn=lambda rng, sx: (init_params_fn(rng, sx), {}),
            apply_fn=apply_fn, name="loss_capture")

        def direct(params, model_state, rng, x, y):
            return loss_fn(params, x, y), model_state

        per_example = None
        if per_example_loss_fn is not None:
            def per_example(params, model_state, rng, x, y):
                return per_example_loss_fn(params, x, y)

        est = Estimator(model=model, loss_fn=lambda y, yp: 0.0,
                        optimizer=opt_mod.get(optimizer),
                        metrics=metrics, direct_loss_fn=direct,
                        direct_eval_per_example_fn=per_example)
        return cls(est)

    @classmethod
    def from_forward(cls, forward_fn: Callable, init_params_fn: Callable,
                     loss="mse", optimizer="adam",
                     metrics: Optional[Sequence] = None) -> "GraphModel":
        """``forward_fn(params, x) -> y_pred`` + a named/callable objective."""
        model = FunctionalModel(
            init_fn=lambda rng, sx: (init_params_fn(rng, sx), {}),
            apply_fn=lambda p, s, x, training, rng: (forward_fn(p, x), s),
            name="forward_capture")
        est = Estimator(model=model, loss_fn=objectives.get(loss),
                        optimizer=opt_mod.get(optimizer), metrics=metrics)
        return cls(est)

    @classmethod
    def from_flax(cls, module, loss="mse", optimizer="adam",
                  metrics: Optional[Sequence] = None) -> "GraphModel":
        est = Estimator(model=from_flax_module(module),
                        loss_fn=objectives.get(loss),
                        optimizer=opt_mod.get(optimizer), metrics=metrics)
        return cls(est)

    @classmethod
    def from_haiku(cls, transformed, loss="mse", optimizer="adam",
                   metrics: Optional[Sequence] = None) -> "GraphModel":
        est = Estimator(model=from_haiku_transformed(transformed),
                        loss_fn=objectives.get(loss),
                        optimizer=opt_mod.get(optimizer), metrics=metrics)
        return cls(est)

    # -- the tfpark user surface ----------------------------------------------

    def fit(self, x, y=None, batch_size: int = 32, epochs: int = 1,
            validation_data=None, featureset: Optional[FeatureSet] = None,
            **kwargs):
        if featureset is None:
            featureset = FeatureSet.from_ndarrays(x, y)
        from ..feature.featureset import HostDataset
        if validation_data is not None and not isinstance(validation_data,
                                                          HostDataset):
            validation_data = FeatureSet.from_ndarrays(*validation_data)
        return self.estimator.train(featureset, batch_size=batch_size,
                                    epochs=epochs,
                                    validation_set=validation_data, **kwargs)

    def evaluate(self, x, y=None, batch_size: int = 32,
                 featureset: Optional[FeatureSet] = None):
        if featureset is None:
            featureset = FeatureSet.from_ndarrays(x, y)
        return self.estimator.evaluate(featureset, batch_size=batch_size)

    def predict(self, x, batch_size: int = 32):
        return self.estimator.predict(x, batch_size=batch_size)

    def get_weights(self):
        """≙ ``get_weights_to_python`` (tf_optimizer.py:90) — weights leave
        the distributed loop as host numpy pytrees."""
        return self.estimator.get_params()

    def set_weights(self, params) -> None:
        self.estimator.set_params(params)

    def save_checkpoint(self, path: str) -> None:
        self.estimator.save_checkpoint(path)

    def load_checkpoint(self, path: str) -> None:
        self.estimator.load_checkpoint(path)
