"""TransformerLM — decoder-only language model with cached generation.

Beyond-reference capability (the reference's only generator is the RNN
Seq2seq chatbot path): a pure-functional transformer decoder whose
TRAINING step runs causal flash attention (pallas on TPU) and whose
GENERATION runs the static-shape KV cache (``ops/decode.py``) with the
whole decode in one ``lax.scan`` dispatch. Training plugs into the
capture-style ``GraphModel.from_loss`` contract, so fit/evaluate ride the
same Estimator loop as every other captured model.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..keras.layers.attention import _layer_norm, _layer_norm_params
from ..ops.attention import (flash_attention, fused_short_applicable,
                             fused_short_attention, masked_context)
from ..ops.decode import (beam_generate, cached_attention,
                          greedy_generate, init_kv_cache, init_paged_pool,
                          init_slot_cache, paged_attention, paged_insert,
                          paged_verify_attention, sample_generate,
                          slot_attention, slot_insert, speculative_generate)

#: prefill length buckets: prompts are right-padded to the smallest bucket
#: that fits, so ONE compiled prefill program per bucket covers every
#: prompt length — both for ``generate()`` and for slot joins in the
#: continuous-batching scheduler (serving/server.py)
PREFILL_BUCKETS = (16, 32, 64, 128, 256, 512)


def prefill_bucket(length: int, max_len: int) -> int:
    """Smallest prefill bucket >= ``length`` (capped at ``max_len``)."""
    for b in PREFILL_BUCKETS:
        if length <= b <= max_len:
            return b
    return max_len


class TransformerLM:
    """Decoder-only LM: tied-embedding logits, pre-LN blocks, causal
    attention. ``fit(tokens)`` trains next-token prediction;
    ``generate(prompt, max_new_tokens)`` decodes greedily off the KV
    cache."""

    def __init__(self, vocab_size: int, hidden: int = 256, n_block: int = 4,
                 n_head: int = 4, max_len: int = 512,
                 intermediate: Optional[int] = None, optimizer="adam",
                 mesh=None, tensor_parallel: bool = False,
                 pipeline_stages: Optional[int] = None,
                 pipeline_microbatches: Optional[int] = None,
                 seed: int = 0):
        if hidden % n_head:
            raise ValueError(f"hidden {hidden} not divisible by "
                             f"heads {n_head}")
        self.vocab_size = vocab_size
        self.hidden = hidden
        self.n_block = n_block
        self.n_head = n_head
        self.max_len = max_len
        self.intermediate = intermediate or 4 * hidden
        self._head_dim = hidden // n_head
        from ..common.config import global_config
        cfg = global_config()
        if pipeline_stages is None:
            pipeline_stages = int(cfg.get("parallel.pipeline_stages"))
        if pipeline_microbatches is None:
            pipeline_microbatches = int(
                cfg.get("parallel.pipeline_microbatches"))
        self.mesh = mesh
        self.tensor_parallel = bool(tensor_parallel)
        self._pipe_stages = int(pipeline_stages)
        self._pipe_micro = int(pipeline_microbatches)
        self._pipe_loss_cache: Dict[int, Any] = {}
        if self._pipe_stages:
            from ..parallel.pipeline import PIPE_AXIS, note_pipeline_build
            if self.n_block % self._pipe_stages:
                raise ValueError(
                    f"n_block {self.n_block} not divisible by "
                    f"pipeline_stages {self._pipe_stages}")
            if self.mesh is None:
                from jax.sharding import Mesh
                devs = jax.devices()
                if len(devs) < self._pipe_stages:
                    raise ValueError(
                        f"pipeline_stages={self._pipe_stages} needs that "
                        f"many devices; have {len(devs)}")
                self.mesh = Mesh(np.asarray(devs[:self._pipe_stages]),
                                 (PIPE_AXIS,))
            # profiler gauge: the schedule's idle fraction is known at
            # build time (bytes-per-hop lands when fit sees the batch)
            note_pipeline_build(self._pipe_stages, self._pipe_micro)
        from .graph_model import GraphModel
        self._graph = GraphModel.from_loss(
            self._loss_pipelined if self._pipe_stages else self._loss,
            self._init_params, optimizer=optimizer,
            forward_fn=self._forward)
        # thread the seed into the Estimator's init rng
        self._graph.estimator.root_rng = jax.random.PRNGKey(seed)
        if self._pipe_stages:
            # params/opt state must live on the pipe mesh's devices
            # (replicated there; the shard_map in the loss stage-shards
            # the stacked blocks at dispatch)
            self._graph.estimator.mesh = self.mesh
        if self.tensor_parallel:
            from ..parallel.tensor import transformer_tp_rules
            axis = str(cfg.get("parallel.tensor_axis"))
            if self.mesh is None:
                from jax.sharding import Mesh
                self.mesh = Mesh(np.asarray(jax.devices()), (axis,))
            if axis not in self.mesh.axis_names:
                raise ValueError(
                    f"tensor_parallel needs a mesh with a '{axis}' axis; "
                    f"got {self.mesh.axis_names}")
            n = dict(zip(self.mesh.axis_names,
                         self.mesh.devices.shape))[axis]
            # qkv column sharding splits heads across the axis; fc1 splits
            # the FFN hidden dim — both must divide for equal shards
            if self.n_head % n or self.intermediate % n:
                raise ValueError(
                    f"n_head {self.n_head} and intermediate "
                    f"{self.intermediate} must both be divisible by the "
                    f"'{axis}' axis size {n}")
            est = self._graph.estimator
            est.mesh = self.mesh
            est.param_rules = (list(est.param_rules or [])
                               + transformer_tp_rules(axis))

    # -- parameters -----------------------------------------------------------

    def _init_params(self, rng, sample_x) -> Dict[str, Any]:
        del sample_x
        d, inter, v = self.hidden, self.intermediate, self.vocab_size
        keys = jax.random.split(rng, 2 + 4 * self.n_block)
        init = jax.nn.initializers.normal(0.02)

        def dense(key, fan_in, fan_out):
            return {"kernel": init(key, (fan_in, fan_out), jnp.float32),
                    "bias": jnp.zeros((fan_out,))}

        def ln():
            return _layer_norm_params(d)

        blocks = []
        for i in range(self.n_block):
            k = jax.random.split(keys[2 + i], 4)
            blocks.append({
                "ln1": ln(), "qkv": dense(k[0], d, 3 * d),
                "attn_out": dense(k[1], d, d),
                "ln2": ln(), "fc1": dense(k[2], d, inter),
                "fc2": dense(k[3], inter, d),
            })
        return {"embed": init(keys[0], (v, d), jnp.float32),
                "pos": init(keys[1], (self.max_len, d), jnp.float32),
                "blocks": blocks, "ln_f": ln()}

    # -- training-time forward (full sequence, flash attention) --------------

    def _split_heads(self, x):
        b, s, _ = x.shape
        return x.reshape(b, s, self.n_head, self._head_dim).transpose(
            0, 2, 1, 3)

    def _block(self, p, x, kv_fn):
        h = _layer_norm(p["ln1"], x)
        qkv = h @ p["qkv"]["kernel"] + p["qkv"]["bias"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        ctx = kv_fn(self._split_heads(q), self._split_heads(k),
                    self._split_heads(v))
        b, _, s, _ = ctx.shape
        ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, self.hidden)
        x = x + ctx @ p["attn_out"]["kernel"] + p["attn_out"]["bias"]
        h = _layer_norm(p["ln2"], x)
        h = jax.nn.gelu(h @ p["fc1"]["kernel"] + p["fc1"]["bias"])
        return x + h @ p["fc2"]["kernel"] + p["fc2"]["bias"]

    def _forward(self, params, tokens) -> jax.Array:
        tokens = tokens.astype(jnp.int32)
        s = tokens.shape[1]
        x = params["embed"][tokens] + params["pos"][None, :s]
        for p in params["blocks"]:
            x = self._block(
                p, x, lambda q, k, v: flash_attention(q, k, v, causal=True))
        x = _layer_norm(params["ln_f"], x)
        return x @ params["embed"].T  # tied logits [B, S, V]

    def _loss(self, params, x, y=None):
        tokens = x.astype(jnp.int32)
        logits = self._forward(params, tokens[:, :-1])
        targets = tokens[:, 1:]
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
        return jnp.mean(nll)

    # -- pipelined training (1F1B over the pipe mesh axis) --------------------

    def _pipe_stage_fn(self, local, x):
        """One pipeline stage: this device's ``n_block/P`` transformer
        blocks, applied in order. ``local`` is the device's slice of the
        ``[P, blocks_per_stage, ...]`` stage-stacked tree."""
        blocks = jax.tree_util.tree_map(lambda l: l[0], local)
        for i in range(self.n_block // self._pipe_stages):
            p = jax.tree_util.tree_map(lambda l: l[i], blocks)
            x = self._block(
                p, x, lambda q, k, v: flash_attention(q, k, v, causal=True))
        return x

    def _pipe_head_loss(self, head, out, targets):
        """Last-stage head: final LN + tied logits + next-token NLL for one
        microbatch — the same arithmetic as ``_loss`` after the trunk."""
        x = _layer_norm(head["ln_f"], out)
        logits = x @ head["embed"].T
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
        return jnp.mean(nll)

    def _pipe_loss_for(self, batch: int):
        """The compiled 1F1B loss for a given batch size: microbatch count
        is ``gcd(batch, pipeline_microbatches)`` so tail batches (smaller,
        separately compiled shapes anyway) still divide evenly."""
        import math
        from ..parallel.pipeline import make_pipeline_loss
        m = math.gcd(batch, self._pipe_micro) or 1
        fn = self._pipe_loss_cache.get(m)
        if fn is None:
            fn = make_pipeline_loss(self._pipe_stage_fn,
                                    self._pipe_head_loss, self.mesh,
                                    n_microbatches=m)
            self._pipe_loss_cache[m] = fn
        return fn

    def _loss_pipelined(self, params, x, y=None):
        """``_loss`` with the block trunk running the 1F1B pipeline schedule
        over ``mesh['pipe']``: embedding and the tied head stay outside the
        custom_vjp (so the embedding-gather gradient rides the returned
        ``dx``, summing with the head's tied-weight gradient), while the
        blocks are stage-stacked and sharded one group per device.
        Microbatch means average to the global mean at equal sizes, so
        parity vs ``_loss`` is float32 tolerance, not bitwise (documented
        in docs/parallelism.md)."""
        from ..parallel.pipeline import stack_stage_params
        tokens = x.astype(jnp.int32)
        inp, targets = tokens[:, :-1], tokens[:, 1:]
        s = inp.shape[1]
        xe = params["embed"][inp] + params["pos"][None, :s]
        bps = self.n_block // self._pipe_stages
        stacked = stack_stage_params(
            [stack_stage_params(params["blocks"][i * bps:(i + 1) * bps])
             for i in range(self._pipe_stages)])
        head = {"ln_f": params["ln_f"], "embed": params["embed"]}
        return self._pipe_loss_for(xe.shape[0])(stacked, head, xe, targets)

    # -- generative prefill + slot decode (continuous batching) ---------------

    def _prefill_attn(self, q, k, v):
        """Causal attention for the prefill forward: the fused short-seq
        kernel when the shape qualifies (TPU, bucketed length <= 512), the
        flash path otherwise — the same cutover the training step uses."""
        if fused_short_applicable(q.shape[-2], k.shape[-2], True):
            return fused_short_attention(q, k, v, causal=True)
        return flash_attention(q, k, v, causal=True)

    def prefill_kv(self, params, tokens):
        """Causal forward over a right-padded prompt block ``[B, Tb]``
        capturing every block's K/V projections ``[B, H, Tb, D]``.

        This is THE prefill path: ``generate()`` and the slot scheduler
        both call it with bucket-padded prompts, so a prompt prefilled
        serially and one joining a slot run the identical compiled program
        and land bit-identical K/V. Causality keeps real positions
        independent of the right-padding; the padded tail's K/V is written
        but never visible (decode masks by per-slot length and overwrites
        it token by token)."""
        tokens = tokens.astype(jnp.int32)
        s = tokens.shape[1]
        x = params["embed"][tokens] + params["pos"][None, :s]
        kvs = []
        for p in params["blocks"]:
            holder = {}

            def kv_fn(q, k, v, holder=holder):
                holder["kv"] = (k, v)
                return self._prefill_attn(q, k, v)
            x = self._block(p, x, kv_fn)
            kvs.append(holder["kv"])
        return kvs

    def init_slot_caches(self, slots: int):
        """One slot-batched K/V cache per block (float32 — decode parity
        with the serial ``generate()`` caches)."""
        return [init_slot_cache(slots, self.n_head, self.max_len,
                                self._head_dim, jnp.float32)
                for _ in range(self.n_block)]

    def slot_step(self, params, tokens, lengths, caches):
        """One decode step over ALL slots: feed ``tokens`` [S] (one per
        slot), write each slot's K/V at its own ``lengths[s]`` position and
        attend against its visible prefix. Returns ``(next-token logits
        [S, V], updated caches)``. Pure and shape-static: slot occupancy
        and lengths are DATA, so the scheduler jits this once and never
        recompiles as streams join and leave."""
        tokens = jnp.asarray(tokens, jnp.int32)
        x = (params["embed"][tokens][:, None]
             + params["pos"][lengths][:, None])
        new_caches = []
        for p, cache in zip(params["blocks"], caches):
            holder = {}

            def kv_fn(q, k, v, cache=cache, holder=holder):
                ctx, holder["cache"] = slot_attention(q, k, v, cache,
                                                      lengths)
                return ctx
            x = self._block(p, x, kv_fn)
            new_caches.append(holder["cache"])
        x = _layer_norm(params["ln_f"], x)
        return (x[:, -1] @ params["embed"].T), new_caches

    # -- paged decode + speculative verify ------------------------------------

    def init_paged_caches(self, num_pages: int, page_len: int,
                          int8: bool = False):
        """One paged KV pool per block (page 0 is the shared null page)."""
        if self.max_len % page_len:
            raise ValueError(f"page_len {page_len} must divide "
                             f"max_len {self.max_len}")
        return [init_paged_pool(num_pages, self.n_head, page_len,
                                self._head_dim, jnp.float32, int8=int8)
                for _ in range(self.n_block)]

    def paged_slot_step(self, params, tokens, lengths, table, caches):
        """``slot_step`` against the paged pool: same contract, but each
        slot's K/V lives in the pages its ``table`` row names instead of a
        private ``max_len`` rectangle. Bit-identical to ``slot_step`` (the
        gathered buffer differs from the contiguous one only at
        masked-to-exact-zero positions)."""
        tokens = jnp.asarray(tokens, jnp.int32)
        x = (params["embed"][tokens][:, None]
             + params["pos"][lengths][:, None])
        new_caches = []
        for p, cache in zip(params["blocks"], caches):
            holder = {}

            def kv_fn(q, k, v, cache=cache, holder=holder):
                ctx, holder["cache"] = paged_attention(
                    q, k, v, cache, table, lengths, self.max_len)
                return ctx
            x = self._block(p, x, kv_fn)
            new_caches.append(holder["cache"])
        x = _layer_norm(params["ln_f"], x)
        return (x[:, -1] @ params["embed"].T), new_caches

    def verify_step(self, params, blocks, lengths, table, caches):
        """Speculative verify: feed ``blocks`` [S, T] (last committed token
        + T-1 drafts per slot) through the paged cache in ONE batched pass
        and return FULL logits [S, T, V] plus updated caches. Row ``j``
        attends causally at position ``lengths + j``; K/V is written at the
        same positions, so a later round's re-write over rejected drafts
        lands at identical offsets (no rollback copy needed)."""
        blocks = jnp.asarray(blocks, jnp.int32)
        t = blocks.shape[1]
        positions = lengths[:, None] + jnp.arange(t, dtype=jnp.int32)[None]
        x = (params["embed"][blocks]
             + params["pos"][jnp.minimum(positions, self.max_len - 1)])
        new_caches = []
        for p, cache in zip(params["blocks"], caches):
            holder = {}

            def kv_fn(q, k, v, cache=cache, holder=holder):
                ctx, holder["cache"] = paged_verify_attention(
                    q, k, v, cache, table, lengths)
                return ctx
            x = self._block(p, x, kv_fn)
            new_caches.append(holder["cache"])
        x = _layer_norm(params["ln_f"], x)
        return (x @ params["embed"].T), new_caches

    def prefill_kv_suffix(self, params, tokens, prefix_kvs, prefix_len):
        """Causal forward over a right-padded SUFFIX block [B, Tb] whose
        positions start at static ``prefix_len``, attending over the
        already-materialised prefix K/V (``prefix_kvs``: per-block
        ``(k, v)`` [B, H, prefix_len, D]) plus the causal suffix. This is
        the shared-prefix join path: the common prompt's K/V comes from
        refcounted pages prefilled once, and only the divergent suffix
        burns a prefill forward."""
        tokens = tokens.astype(jnp.int32)
        s = tokens.shape[1]
        x = (params["embed"][tokens]
             + params["pos"][None, prefix_len:prefix_len + s])
        row_pos = jnp.arange(s, dtype=jnp.int32)
        kvs = []
        for p, (pk, pv) in zip(params["blocks"], prefix_kvs):
            holder = {}

            def kv_fn(q, k, v, pk=pk, pv=pv, holder=holder):
                holder["kv"] = (k, v)
                k_buf = jnp.concatenate([pk.astype(k.dtype), k], axis=2)
                v_buf = jnp.concatenate([pv.astype(v.dtype), v], axis=2)
                key_pos = jnp.arange(prefix_len + s, dtype=jnp.int32)
                visible = (key_pos[None, None, None, :]
                           <= prefix_len + row_pos[None, None, :, None])
                scale = 1.0 / (q.shape[-1] ** 0.5)
                return masked_context(q, k_buf, v_buf, visible, scale)
            x = self._block(p, x, kv_fn)
            kvs.append(holder["kv"])
        return kvs

    # -- public surface -------------------------------------------------------

    def fit(self, tokens, batch_size: int = 32, epochs: int = 1, **kw):
        """``tokens``: [N, S] int sequences; trains next-token NLL."""
        tokens = np.asarray(tokens, np.float32)
        if self._pipe_stages:
            # per-hop ppermute traffic is known once the batch shape is:
            # one [mb, S-1, hidden] float32 activation per tick per ring
            from ..parallel.pipeline import note_pipeline_build
            import math
            m = math.gcd(batch_size, self._pipe_micro) or 1
            micro_bytes = (batch_size // m) * (tokens.shape[1] - 1) \
                * self.hidden * 4
            note_pipeline_build(self._pipe_stages, m,
                                micro_bytes=micro_bytes)
        return self._graph.fit(tokens, batch_size=batch_size,
                               epochs=epochs, **kw)

    def logits(self, tokens, batch_size: int = 32):
        return self._graph.predict(np.asarray(tokens, np.float32),
                                   batch_size=batch_size)

    @property
    def params(self):
        params = self._graph.estimator.params
        if params is None:
            raise RuntimeError(
                "TransformerLM has no parameters yet: call fit() (or "
                "restore a checkpoint through the estimator) first")
        return params

    def generate(self, prompt, max_new_tokens: int,
                 eos_id: Optional[int] = None,
                 beam_size: int = 1,
                 temperature: Optional[float] = None,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 seed: Optional[int] = None) -> np.ndarray:
        """Continuation of ``prompt`` [B, S]: prefill the prompt minus its
        last token through the per-block KV caches, then decode
        ``max_new_tokens`` in one scan dispatch — greedy by default, beam
        search (best sequence returned) with ``beam_size > 1``, or sampled
        when ``temperature``/``top_k``/``top_p`` is given. Sampling draws
        fresh entropy per call; pass ``seed`` for reproducible draws."""
        sampling = (temperature is not None or top_k is not None
                    or top_p is not None)
        if sampling and beam_size > 1:
            raise ValueError("choose either beam_size > 1 or sampling "
                             "(temperature/top_k/top_p), not both")
        prompt = jnp.asarray(np.asarray(prompt), jnp.int32)
        b, s = prompt.shape
        if s + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
                f"max_len={self.max_len}")
        params = self.params
        caches = [init_kv_cache(b, self.n_head, self.max_len,
                                self._head_dim, jnp.float32)
                  for _ in range(self.n_block)]

        def run(params, tokens, caches):
            """Feed ``tokens`` [B, T] through all blocks with caches;
            returns (next-token logits [B, V], caches)."""
            start = caches[0]["length"]
            x = params["embed"][tokens] + jax.lax.dynamic_slice(
                params["pos"], (start, 0),
                (tokens.shape[1], self.hidden))[None]
            new_caches = []
            for p, cache in zip(params["blocks"], caches):
                holder = {}

                def kv_fn(q, k, v, cache=cache, holder=holder):
                    ctx, holder["cache"] = cached_attention(q, k, v, cache)
                    return ctx
                x = self._block(p, x, kv_fn)
                new_caches.append(holder["cache"])
            x = _layer_norm(params["ln_f"], x)
            return (x[:, -1] @ params["embed"].T), new_caches

        if s > 1:
            # prefill everything except the last prompt token through the
            # SAME bucketed causal-forward path the continuous-batching
            # scheduler uses (fused short-seq kernel on TPU) — one compile
            # per length bucket instead of re-attending the whole prompt
            # through the incremental cache per request
            tb = prefill_bucket(s - 1, self.max_len)
            padded = jnp.zeros((b, tb), jnp.int32)
            padded = jax.lax.dynamic_update_slice(padded, prompt[:, :-1],
                                                  (0, 0))
            kvs = self.prefill_kv(params, padded)
            caches = [{"k": c["k"].at[:, :, :tb, :].set(
                           k.astype(c["k"].dtype)),
                       "v": c["v"].at[:, :, :tb, :].set(
                           v.astype(c["v"].dtype)),
                       "length": jnp.asarray(s - 1, jnp.int32)}
                      for c, (k, v) in zip(caches, kvs)]

        def step_fn(params, token, caches):
            return run(params, token[:, None], caches)

        if beam_size > 1:
            seqs, _ = beam_generate(step_fn, params, caches, prompt[:, -1],
                                    max_new_tokens, beam_size,
                                    eos_id=eos_id)
            return np.asarray(seqs[:, 0])  # best beam
        if sampling:
            if seed is None:  # fresh entropy: repeated calls differ
                seed = int(np.random.SeedSequence().entropy % (2 ** 31))
            return np.asarray(sample_generate(
                step_fn, params, caches, prompt[:, -1], max_new_tokens,
                jax.random.PRNGKey(seed),
                temperature=temperature if temperature is not None else 1.0,
                top_k=top_k, top_p=top_p, eos_id=eos_id))
        return np.asarray(greedy_generate(
            step_fn, params, caches, prompt[:, -1], max_new_tokens,
            eos_id=eos_id))

    def generate_speculative(self, prompt, draft_lm: "TransformerLM",
                             max_new_tokens: int, spec_k: int = 4,
                             eos_id: Optional[int] = None,
                             temperature: Optional[float] = None,
                             top_k: Optional[int] = None,
                             top_p: Optional[float] = None,
                             seed: Optional[int] = None,
                             page_len: int = 16) -> np.ndarray:
        """Speculative continuation of ``prompt`` [B, S] through the PAGED
        target cache: ``draft_lm`` proposes ``spec_k`` tokens per round off
        its contiguous slot cache, the target verifies the whole block in
        one batched ``verify_step``, and the standard accept rule keeps the
        longest agreeing run. Greedy output is token-identical to
        ``generate()``; sampled output follows the Leviathan accept/resample
        rule (exact target distribution). Both prompts are prefilled through
        the same bucketed path as ``generate()``."""
        sampling = (temperature is not None or top_k is not None
                    or top_p is not None)
        prompt = jnp.asarray(np.asarray(prompt), jnp.int32)
        b, s = prompt.shape
        if spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        if s + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
                f"max_len={self.max_len}")
        if s + max_new_tokens + spec_k > draft_lm.max_len:
            raise ValueError(
                f"draft max_len={draft_lm.max_len} too short for prompt "
                f"({s}) + max_new_tokens ({max_new_tokens}) + spec_k "
                f"({spec_k}) transient draft positions")
        if self.max_len % page_len:
            raise ValueError(f"page_len {page_len} must divide "
                             f"max_len {self.max_len}")
        params, dparams = self.params, draft_lm.params
        pl = page_len
        # statically assigned private pages per row, wide enough for the
        # prompt, the budget, and the transient spec_k overshoot
        per_row = (s + max_new_tokens + spec_k + pl - 1) // pl
        width = (self.max_len + spec_k + pl - 1) // pl
        table_host = np.zeros((b, width), np.int32)
        for r in range(b):
            table_host[r, :per_row] = 1 + r * per_row + np.arange(per_row)
        table = jnp.asarray(table_host)
        caches = self.init_paged_caches(b * per_row + 1, pl)
        dcaches = draft_lm.init_slot_caches(b)
        lengths0 = jnp.full((b,), s - 1, jnp.int32)
        if s > 1:
            tb = prefill_bucket(s - 1, self.max_len)
            padded = jnp.zeros((b, tb), jnp.int32)
            padded = jax.lax.dynamic_update_slice(padded, prompt[:, :-1],
                                                  (0, 0))
            kvs = self.prefill_kv(params, padded)
            for r in range(b):
                caches = [paged_insert(c, table[r], k[r], v[r])
                          for c, (k, v) in zip(caches, kvs)]
            dtb = prefill_bucket(s - 1, draft_lm.max_len)
            dpadded = jnp.zeros((b, dtb), jnp.int32)
            dpadded = jax.lax.dynamic_update_slice(dpadded, prompt[:, :-1],
                                                   (0, 0))
            dkvs = draft_lm.prefill_kv(dparams, dpadded)
            for r in range(b):
                dcaches = [slot_insert(c, r, k[r], v[r])
                           for c, (k, v) in zip(dcaches, dkvs)]

        def draft_step_fn(dp, toks, ln, dc):
            return draft_lm.slot_step(dp, toks, ln, dc)

        def verify_fn(tp, block, ln, tc):
            return self.verify_step(tp, block, ln, table, tc)

        rng = None
        if sampling:
            if seed is None:
                seed = int(np.random.SeedSequence().entropy % (2 ** 31))
            rng = jax.random.PRNGKey(seed)
        out = speculative_generate(
            draft_step_fn, verify_fn, dparams, params, dcaches, caches,
            prompt[:, -1], lengths0, max_new_tokens, spec_k, eos_id=eos_id,
            rng=rng,
            temperature=temperature if temperature is not None else 1.0,
            top_k=top_k, top_p=top_p)
        return np.asarray(out)
