"""zoolint pass ``monotonic-clock``: no ``time.time()`` in scheduling math.

``time.time()`` is wall-clock: NTP slews and steps move it backwards or
jump it forward, so any interval arithmetic built on it — retry windows,
lease expiry, watchdog deadlines, latency measurement — misfires exactly
when the fleet's clocks are being corrected, which on a multi-host TPU pod
is routine. The rules:

* **intervals and deadlines measured within one process** use
  ``time.monotonic()`` (or ``perf_counter`` for micro-timing);
* **stamps that cross process boundaries** (queue leases, request
  ``enqueue_t``, ``health.json``, client-supplied deadlines) genuinely
  need wall-clock — route them through
  :func:`analytics_zoo_tpu.common.utils.wall_clock`, the single audited
  call site, so intent is explicit and grep-able;
* TensorBoard event ``wall_time`` is a file-format contract (waived
  inline where it is written).

The pass flags every ``time.time`` / ``time.time_ns`` call in the package
(resolved through import aliases; tests and ``bench.py`` are out of
scope — benches already use ``perf_counter``).

It additionally flags **mixed-clock arithmetic**: any one expression
(``-``/``+``/comparison) combining a monotonic-domain read
(``time.monotonic`` / ``perf_counter``) with a wall-domain read
(``wall_clock()`` or a bare ``time.time``). This is exactly the
lease/heartbeat bug class the elastic supervisor must avoid: subtracting
a worker's wall-clock lease stamp from the supervisor's monotonic clock
produces a number that means nothing, yet "works" until the first NTP
step — the supervisor instead stamps its OWN monotonic clock when it
*observes* a lease seq change (``cluster/supervisor.py`` LeaseTracker).
"""
from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List

from ..core import (Finding, LintPass, Project, REPO_ROOT, get_project,
                    register_pass)

_WALL = {"time.time", "time.time_ns"}
#: monotonic-domain reads for the mixed-arithmetic check
_MONO = {"time.monotonic", "time.monotonic_ns", "time.perf_counter",
         "time.perf_counter_ns"}


def _wall_domain(dotted: str) -> bool:
    """Wall-domain reads: bare time.time AND the audited wall_clock()
    (legit on its own for cross-process stamps, but never in the same
    arithmetic expression as a monotonic read)."""
    return (dotted in _WALL or dotted == "wall_clock"
            or dotted.endswith(".wall_clock"))


def _import_map(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            for a in node.names:
                if a.name != "*":
                    out[a.asname or a.name] = f"{node.module or ''}.{a.name}"
    return out


def _dotted(expr, imports: Dict[str, str]) -> str:
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if not isinstance(expr, ast.Name):
        return ""
    return ".".join([imports.get(expr.id, expr.id)]
                    + list(reversed(parts)))


def _clock_domains(node: ast.AST, imports: Dict[str, str]):
    """Which clock domains the expression under ``node`` reads from."""
    mono = wall = False
    for sub in ast.walk(node):
        if not isinstance(sub, ast.Call):
            continue
        d = _dotted(sub.func, imports)
        if d in _MONO:
            mono = True
        elif _wall_domain(d):
            wall = True
    return mono, wall


def findings(project=None) -> List[Finding]:
    project = project or get_project()
    out: List[Finding] = []
    for path in project.package_files():
        tree = project.ast_for(path)
        imports = _import_map(tree)
        mixed_lines = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.BinOp, ast.Compare)):
                mono, wall = _clock_domains(node, imports)
                if mono and wall and node.lineno not in mixed_lines:
                    mixed_lines.add(node.lineno)
                    out.append(Finding(
                        path, node.lineno, MonotonicClockPass.id,
                        "expression mixes monotonic- and wall-clock "
                        "reads — the difference of two different clocks "
                        "is meaningless (lease/heartbeat math must stay "
                        "in ONE domain)",
                        "compare like with like: stamp your own "
                        "monotonic clock when you OBSERVE a cross-"
                        "process value change, as LeaseTracker does"))
                continue
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func, imports)
            if d in _WALL or d in ("time.time.time", "time.time.time_ns"):
                out.append(Finding(
                    path, node.lineno, MonotonicClockPass.id,
                    f"{d}() is wall-clock — NTP steps break interval/"
                    f"deadline arithmetic built on it",
                    "use time.monotonic() for in-process intervals, or "
                    "common.utils.wall_clock() for cross-process stamps"))
    return out


def check() -> List[str]:
    """Human-readable violations; empty = clean."""
    return [f.message for f in findings()]


@register_pass
class MonotonicClockPass(LintPass):
    id = "monotonic-clock"
    title = "wall-clock reads quarantined out of scheduling arithmetic"
    rationale = (
        "retry windows, leases and watchdogs built on time.time() "
        "misfire exactly when NTP corrects a host — monotonic clocks for "
        "intervals, one audited wall_clock() for cross-process stamps")

    def run(self, project: Project) -> List[Finding]:
        return findings(project)


def main() -> int:
    problems = check()
    if not problems:
        print("monotonic-clock lint: clean")
        return 0
    for p in problems:
        print(p, file=sys.stderr)
    return 1
