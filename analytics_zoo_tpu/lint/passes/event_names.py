"""zoolint pass ``event-names``: ops-plane event types stay canonical.

Mirror of ``metric-names`` for the structured event log
(``analytics_zoo_tpu/ops/events.py``). Incident timelines are only
readable if event types don't rot: a type registered twice makes two
modules claim the same transition, an off-convention name breaks every
``subsystem.*`` timeline filter, and an undocumented type is invisible
to whoever reads the bundle. Rules:

1. every registration call (``events.event_type(...)`` on an events-
   module alias) passes a string LITERAL name — a computed name defeats
   both this lint and grep;
2. every event type is registered exactly ONCE across the codebase — one
   transition, one owning module;
3. names follow the ``subsystem.noun`` convention (lower_snake, one
   dot), the same shape the metric plane uses;
4. every registered type is documented in the event table of
   ``docs/observability.md`` (the operator's timeline vocabulary).
"""
from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Tuple

from ..core import (Finding, LintPass, Project, REPO_ROOT, get_project,
                    register_pass)

_DOCS = os.path.join(REPO_ROOT, "docs", "observability.md")

#: ops/events.py itself is excluded (it defines the registry and calls
#: ``event_type`` in its own doctests/plumbing)
_EXCLUDE = (os.path.join("ops", "events.py"),)

_NAME_RE = re.compile(r"^[a-z][a-z0-9]*\.[a-z][a-z0-9_]*$")


def _is_registration(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "event_type"
            and isinstance(f.value, ast.Name)
            and (f.value.id == "events" or f.value.id.endswith("_events")))


def registrations(project=None) -> Tuple[Dict[str, List[str]],
                                         List[Tuple[str, int, str]]]:
    """``{name: [file:line, ...]}`` over all scanned files, plus
    violations for non-literal name arguments."""
    project = project if project is not None else get_project()
    regs: Dict[str, List[str]] = {}
    bad: List[Tuple[str, int, str]] = []
    files = project.package_files()
    if os.path.exists(project.bench_file()):
        files = files + [project.bench_file()]
    for path in sorted(files):
        rel = os.path.relpath(path, project.root)
        if any(rel.endswith(e) for e in _EXCLUDE):
            continue
        tree = project.ast_for(path)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and _is_registration(node)):
                continue
            if (not node.args
                    or not isinstance(node.args[0], ast.Constant)
                    or not isinstance(node.args[0].value, str)):
                bad.append((path, node.lineno,
                            "event type name must be one string literal"))
                continue
            regs.setdefault(node.args[0].value, []).append(
                f"{rel}:{node.lineno}")
    return regs, bad


def undocumented(names, docs_path: str = _DOCS) -> List[str]:
    """Registered types with no `` `name` `` mention in the docs."""
    try:
        with open(docs_path) as fh:
            text = fh.read()
    except OSError:
        return sorted(names)
    return sorted(n for n in names if f"`{n}`" not in text)


def _locate(regs: Dict[str, List[str]], name: str,
            root: str) -> Tuple[str, int]:
    rel, _, line = regs[name][0].rpartition(":")
    return os.path.join(root, rel), int(line)


def check() -> List[str]:
    """Human-readable violations; empty = clean."""
    return [f.message for f in findings()]


def findings(project=None) -> List[Finding]:
    project = project if project is not None else get_project()
    root = project.root
    regs, bad = registrations(project)
    out: List[Finding] = []
    for p, line, what in bad:
        out.append(Finding(p, line, EventNamesPass.id,
                           f"{os.path.relpath(p, root)}:{line}: {what}",
                           "pass the event type name as one string literal"))
    for name, places in sorted(regs.items()):
        path, line = _locate(regs, name, root)
        if len(places) > 1:
            out.append(Finding(
                path, line, EventNamesPass.id,
                f"event type {name!r} registered at {len(places)} sites "
                f"({', '.join(places)}); each type must be registered "
                f"exactly once",
                "keep one owning module per event type"))
        if not _NAME_RE.match(name):
            out.append(Finding(
                path, line, EventNamesPass.id,
                f"event type {name!r} ({places[0]}) breaks the "
                f"'subsystem.noun' convention (lower_snake, one dot)",
                "rename to subsystem.noun"))
    docs = os.path.join(root, "docs", "observability.md")
    for name in undocumented(regs, docs):
        path, line = _locate(regs, name, root)
        out.append(Finding(
            path, line, EventNamesPass.id,
            f"event type {name!r} is registered but undocumented — add a "
            f"row to the event table in docs/observability.md",
            "document every event type a timeline can contain"))
    return out


@register_pass
class EventNamesPass(LintPass):
    id = "event-names"
    title = "event-log type naming/uniqueness/documentation contract"
    rationale = (
        "incident timelines only stay readable if event types stay "
        "literal, unique, canonical and documented — drift is invisible "
        "to behavioral tests")

    def run(self, project: Project) -> List[Finding]:
        return findings(project)


def main() -> int:
    problems = check()
    if not problems:
        print(f"event-name lint: clean ({len(registrations()[0])} event "
              f"types, all literal, unique, canonical and documented)")
        return 0
    for p in problems:
        print(p, file=sys.stderr)
    return 1
