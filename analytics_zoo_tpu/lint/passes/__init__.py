"""zoolint built-in passes. Importing this package registers every pass
with :func:`analytics_zoo_tpu.lint.core.register_pass`; third-party or
repo-local passes can do the same — subclass ``LintPass``, decorate with
``@register_pass``, and import the module before calling ``run_passes``.
"""
from . import (config_keys, event_names, fault_sites,  # noqa: F401
               hot_path, jit_boundary, metric_names, monotonic_clock,
               retry_discipline)

__all__ = ["config_keys", "event_names", "fault_sites", "hot_path",
           "jit_boundary", "metric_names", "monotonic_clock",
           "retry_discipline"]
