"""zoolint pass ``config-keys``: the config registry stays a closed ledger.

The layered config (``analytics_zoo_tpu/common/config.py``) is the
platform's operator API: every ``"x.y"`` key is settable from env vars,
``conf={...}`` overrides, and the defaults layer. That API only stays
trustworthy if the registry is bijective with reality:

1. key arguments to ``register``/``get``/``set``/``unset`` on a config
   receiver are string LITERALS (a computed key defeats this lint, grep,
   and the docs); dynamic plumbing that forwards ``(k, v)`` pairs —
   e.g. applying a ``conf`` dict — is exempt (non-literal keys are
   simply not analyzable, and registration still validates them at
   runtime);
2. keys follow the dotted ``section.name`` convention (lower_snake
   segments, at least one dot) so env-var mapping (``ZOO_TPU_SECTION_
   NAME``) stays mechanical;
3. each key is registered exactly ONCE — one owning module (today:
   ``common/config.py``); a second registration would silently change
   defaults/docs depending on import order;
4. every ``get``/``set``/``unset`` of a literal key refers to a
   REGISTERED key (a typo'd read returns the miss default forever);
5. every registered key is READ somewhere in the package — a registered-
   but-never-consumed key is dead operator surface that silently does
   nothing when set;
6. every registered key has a row in ``docs/configuration.md`` and the
   table has no stale rows for unregistered keys.

Config receivers are resolved, not guessed by name: ``_global_config``
inside ``common/config.py``, direct ``global_config().op(...)`` chains,
and any local name assigned from ``global_config()`` in the same file.
``dict.get("...")`` calls elsewhere never match.
"""
from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Set, Tuple

from ..core import (Finding, LintPass, Project, REPO_ROOT, get_project,
                    register_pass)

_CONFIG_PY = os.path.join(REPO_ROOT, "analytics_zoo_tpu", "common",
                          "config.py")
_DOCS = os.path.join(REPO_ROOT, "docs", "configuration.md")

_OPS = ("register", "get", "set", "unset")
_KEY_RE = re.compile(r"^[a-z][a-z0-9_]*(\.[a-z][a-z0-9_]*)+$")
_DOC_ROW_RE = re.compile(r"^\|\s*`([^`]+)`")


def _config_receivers(tree: ast.Module, path: str) -> Set[str]:
    """Names that hold the global config in this file: assigned from a
    ``global_config()`` call (any alias import) or, in config.py itself,
    the module-level ``_global_config`` instance."""
    names: Set[str] = set()
    if os.path.abspath(path) == os.path.abspath(_CONFIG_PY):
        names.add("_global_config")
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        v = node.value
        if (isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
                and v.func.id == "global_config"):
            names.add(node.targets[0].id)
    return names


def _config_op(node: ast.Call, receivers: Set[str]) -> str:
    """The op name if this call is ``<config>.<op>(...)``, else ''."""
    f = node.func
    if not (isinstance(f, ast.Attribute) and f.attr in _OPS):
        return ""
    base = f.value
    if isinstance(base, ast.Name) and base.id in receivers:
        return f.attr
    if (isinstance(base, ast.Call) and isinstance(base.func, ast.Name)
            and base.func.id == "global_config"):
        return f.attr
    return ""


def registrations(project=None
                  ) -> Tuple[Dict[str, List[str]], List[Tuple[str, int]]]:
    """``{key: [file:line, ...]}`` registrations plus non-literal
    ``register`` sites."""
    project = project or get_project()
    regs: Dict[str, List[str]] = {}
    bad: List[Tuple[str, int]] = []
    for path in project.package_files():
        tree = project.ast_for(path)
        receivers = _config_receivers(tree, path)
        rel = os.path.relpath(path, project.root)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _config_op(node, receivers) != "register":
                continue
            if (not node.args or not isinstance(node.args[0], ast.Constant)
                    or not isinstance(node.args[0].value, str)):
                bad.append((path, node.lineno))
                continue
            regs.setdefault(node.args[0].value, []).append(
                f"{rel}:{node.lineno}")
    return regs, bad


def reads(project=None) -> Dict[str, List[str]]:
    """``{key: [file:line, ...]}`` for literal get/set/unset sites across
    the package and bench.py."""
    project = project or get_project()
    uses: Dict[str, List[str]] = {}
    files = project.package_files()
    if os.path.exists(project.bench_file()):
        files = files + [project.bench_file()]
    for path in files:
        tree = project.ast_for(path)
        receivers = _config_receivers(tree, path)
        rel = os.path.relpath(path, project.root)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            op = _config_op(node, receivers)
            if op in ("", "register"):
                continue
            if (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                uses.setdefault(node.args[0].value, []).append(
                    f"{rel}:{node.lineno}")
    return uses


def documented_keys(project=None) -> Set[str]:
    """Keys with a `` | `key` | `` table row in docs/configuration.md."""
    project = project or get_project()
    try:
        with open(os.path.join(project.root, "docs", "configuration.md")) as fh:
            lines = fh.read().splitlines()
    except OSError:
        return set()
    out: Set[str] = set()
    for line in lines:
        m = _DOC_ROW_RE.match(line.strip())
        if m:
            out.add(m.group(1))
    return out


def findings(project=None) -> List[Finding]:
    project = project or get_project()
    regs, bad = registrations(project)
    uses = reads(project)
    docs = documented_keys(project)
    out: List[Finding] = []
    for path, line in bad:
        out.append(Finding(
            path, line, ConfigKeysPass.id,
            "config key registration must pass the key as one string "
            "literal", "register with a literal 'section.name' key"))

    def _loc(where: str) -> Tuple[str, int]:
        rel, _, line = where.rpartition(":")
        return os.path.join(project.root, rel), int(line)

    for key, places in sorted(regs.items()):
        path, line = _loc(places[0])
        if len(places) > 1:
            out.append(Finding(
                path, line, ConfigKeysPass.id,
                f"config key {key!r} registered at {len(places)} sites "
                f"({', '.join(places)}); one key, one owning registration",
                "keep a single registration per key"))
        if not _KEY_RE.match(key):
            out.append(Finding(
                path, line, ConfigKeysPass.id,
                f"config key {key!r} breaks the dotted 'section.name' "
                f"convention (lower_snake segments, at least one dot) — "
                f"env-var mapping needs it",
                "rename to section.name"))
        if key not in uses:
            out.append(Finding(
                path, line, ConfigKeysPass.id,
                f"config key {key!r} is registered but never read — dead "
                f"operator surface; setting it silently does nothing",
                "consume the key or drop the registration"))
        if key not in docs:
            out.append(Finding(
                path, line, ConfigKeysPass.id,
                f"config key {key!r} has no row in docs/configuration.md",
                "document every key an operator can set"))
    for key, places in sorted(uses.items()):
        if key in regs:
            continue
        path, line = _loc(places[0])
        out.append(Finding(
            path, line, ConfigKeysPass.id,
            f"config key {key!r} read at {places[0]} but never registered "
            f"— a typo'd key returns the miss default forever",
            "register the key in common/config.py"))
    doc_path = os.path.join(project.root, "docs", "configuration.md")
    for key in sorted(docs - set(regs)):
        out.append(Finding(
            doc_path, 1, ConfigKeysPass.id,
            f"docs/configuration.md documents {key!r} but no such key is "
            f"registered — stale row",
            "drop the row or restore the key"))
    return out


def check() -> List[str]:
    """Human-readable violations; empty = clean."""
    return [f.message for f in findings()]


@register_pass
class ConfigKeysPass(LintPass):
    id = "config-keys"
    title = "config-key registry literal/unique/consumed/documented ledger"
    rationale = (
        "the dotted-key registry is the operator API; unregistered reads, "
        "dead keys and undocumented rows all fail silently at runtime")

    def run(self, project: Project) -> List[Finding]:
        return findings(project)


def main() -> int:
    problems = check()
    if not problems:
        print(f"config-key lint: clean ({len(registrations()[0])} keys, "
              f"all literal, unique, consumed and documented)")
        return 0
    for p in problems:
        print(p, file=sys.stderr)
    return 1
