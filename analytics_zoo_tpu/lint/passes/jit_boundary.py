"""zoolint pass ``jit-host-sync``: jit-boundary host-sync escape analysis.

The hand-curated ``hot-path-sync`` table only protects functions someone
remembered to list — PRs 7, 8 and 11 each had to extend it by hand. This
pass *discovers* the traced surface automatically, so the next decode or
embedding PR is policed the day it lands:

* **traced roots** — every function decorated with or wrapped by a JAX
  tracing transform (``jax.jit``/``pjit``/``vmap``/``pmap``/``grad``/
  ``value_and_grad``/``remat``/``custom_vjp``/``custom_jvp``/
  ``shard_map``/``checkify.checkify``) or passed as a body to a structured
  control-flow primitive (``lax.scan``/``while_loop``/``fori_loop``/
  ``cond``/``switch``/``map``/``associative_scan``) or registered via
  ``.defvjp``/``.defjvp`` — including closures defined inside methods
  (``self._step_fn = jax.jit(_step)``);
* **the traced closure** — their transitive intra-package callees, resolved
  through an import-aware call graph: bare names through enclosing scopes
  and module/import tables, ``self.method`` through the class, and
  ``obj.method`` through a package-unique-method-name heuristic (skipped
  for ambiguous or generic names);
* **dispatch boundaries** — host functions that invoke a jit-wrapped
  callable (a ``self.X`` attribute assigned from ``jax.jit(...)`` or from
  a factory method returning one, a local jitted name, or a
  ``jax.device_put`` feed) — the loops that drive the device.

Inside the **traced closure** the pass bans host syncs (``float()``,
``.item()``, ``.tolist()``, ``np.asarray``, ``jax.device_get``,
``.block_until_ready()``), ``one_hot`` densification, host clock/RNG reads
(``time.*``, ``datetime.now``, stdlib/NumPy ``random``) — values that
constant-fold at trace time and silently freeze — and per-element Python
loops (``while``, iteration driven by array shapes, loops over
non-structure iterables), which unroll at trace time or re-serialize
vectorized work. Constant-trip *structure* loops (over ``self``
attributes, pytree containers, ``range(<constant>)``) are exempt.

Inside **dispatch boundaries** the pass bans host syncs in loop bodies
only — a sync per iteration re-serializes the async dispatch pipeline;
one drain after the loop is the supported pattern.

Host-side staging rules that no trace analysis can infer (``_gather``'s
zero-alloc ``np.take(out=)`` contract, ``masked_eval_batches``' cached
mask) remain table-driven in ``hot-path-sync``; this pass counts those
table rows as seeded roots so its coverage strictly dominates the legacy
hand-listed tables.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core import (Finding, LintPass, Project, REPO_ROOT, get_project,
                    register_pass)

PKG_NAME = "analytics_zoo_tpu"

#: fully-resolved callables that trace their function argument(s)
TRACE_WRAPPERS = {
    "jax.jit", "jax.pjit", "jax.vmap", "jax.pmap", "jax.grad",
    "jax.value_and_grad", "jax.checkpoint", "jax.remat",
    "jax.custom_vjp", "jax.custom_jvp",
    "jax.lax.scan", "jax.lax.while_loop", "jax.lax.fori_loop",
    "jax.lax.cond", "jax.lax.switch", "jax.lax.map",
    "jax.lax.associative_scan",
    "jax.experimental.pjit.pjit",
    "jax.experimental.shard_map.shard_map",
    "jax.experimental.checkify.checkify",
}

#: attribute registrations that trace their arguments
TRACE_METHODS = {"defvjp", "defjvp"}

#: method names never resolved via the unique-name heuristic (generic or
#: collection-protocol names that would wire unrelated code together)
_COMMON_METHODS = {
    "get", "set", "put", "pop", "add", "append", "extend", "update",
    "items", "keys", "values", "copy", "clear", "close", "open", "read",
    "write", "join", "split", "strip", "encode", "decode", "reshape",
    "astype", "sum", "mean", "max", "min", "item", "tolist", "result",
    "submit", "apply", "run", "start", "stop", "init", "reset", "next",
    "send", "save", "load", "name", "shape", "size", "fit", "predict",
    "evaluate", "transform", "register", "observe", "inc", "dec",
}

_SYNC_NAMES = {"float"}
_HOST_CLOCKS = {
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "time.monotonic_ns", "time.perf_counter_ns", "time.process_time",
}


@dataclass
class FuncInfo:
    node: ast.AST
    path: str
    modname: str
    name: str
    class_name: Optional[str] = None
    parent: Optional["FuncInfo"] = None
    nested: Dict[str, "FuncInfo"] = field(default_factory=dict)

    @property
    def qualname(self) -> str:
        parts = [self.name]
        p = self.parent
        while p is not None:
            parts.append(p.name)
            p = p.parent
        if self.class_name:
            parts.append(self.class_name)
        return ".".join(reversed(parts))


@dataclass
class ModuleInfo:
    path: str
    modname: str
    imports: Dict[str, str] = field(default_factory=dict)
    funcs: Dict[str, FuncInfo] = field(default_factory=dict)
    classes: Dict[str, Dict[str, FuncInfo]] = field(default_factory=dict)
    all_funcs: List[FuncInfo] = field(default_factory=list)
    #: (call node, enclosing function or None) for every Call in the module
    calls: List[Tuple[ast.Call, Optional[FuncInfo]]] = field(
        default_factory=list)


class PackageIndex:
    """Import-aware symbol/call index over the package's modules."""

    def __init__(self, project: Project) -> None:
        self.project = project
        self.modules: Dict[str, ModuleInfo] = {}
        for path in project.package_files():
            rel = os.path.relpath(path, project.root)
            modname = rel[:-3].replace(os.sep, ".")
            if modname.endswith(".__init__"):
                modname = modname[: -len(".__init__")]
            if modname.startswith(f"{PKG_NAME}.lint"):
                continue  # the analyzer itself has no device code
            self.modules[modname] = self._index_module(path, modname)
        # unique-method-name resolution table (ambiguous names dropped)
        counts: Dict[str, List[FuncInfo]] = {}
        for mod in self.modules.values():
            for methods in mod.classes.values():
                for name, fi in methods.items():
                    counts.setdefault(name, []).append(fi)
        self.unique_methods = {
            name: fis[0] for name, fis in counts.items()
            if len(fis) == 1 and name not in _COMMON_METHODS}

    # -- module indexing ------------------------------------------------------

    def _index_module(self, path: str, modname: str) -> ModuleInfo:
        tree = self.project.ast_for(path)
        mod = ModuleInfo(path, modname)
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    mod.imports[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    parts = modname.split(".")
                    # drop one for the module itself + (level-1) parents
                    parts = parts[: len(parts) - node.level]
                    base = ".".join(parts + ([node.module]
                                             if node.module else []))
                for a in node.names:
                    if a.name == "*":
                        continue
                    mod.imports[a.asname or a.name] = f"{base}.{a.name}"

        def collect(body, cls: Optional[str], parent: Optional[FuncInfo]):
            for node in body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fi = FuncInfo(node, path, modname, node.name, cls, parent)
                    mod.all_funcs.append(fi)
                    if parent is not None:
                        parent.nested[node.name] = fi
                    elif cls is not None:
                        mod.classes.setdefault(cls, {})[node.name] = fi
                    else:
                        mod.funcs[node.name] = fi
                    self._collect_calls(node, fi, mod)
                    collect(node.body, cls, fi)
                elif isinstance(node, ast.ClassDef):
                    collect(node.body, node.name, None)
                else:
                    collect(getattr(node, "body", []) or [], cls, parent)
                    collect(getattr(node, "orelse", []) or [], cls, parent)
                    collect(getattr(node, "finalbody", []) or [], cls,
                            parent)
                    for h in getattr(node, "handlers", []) or []:
                        collect(h.body, cls, parent)

        collect(tree.body, None, None)
        # module-level calls (outside any function)
        in_fn: Set[int] = set()
        for fi in mod.all_funcs:
            for sub in ast.walk(fi.node):
                in_fn.add(id(sub))
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and id(node) not in in_fn:
                mod.calls.append((node, None))
        return mod

    def _collect_calls(self, fn_node, fi: FuncInfo, mod: ModuleInfo) -> None:
        """Attribute each Call to its INNERMOST enclosing function."""
        direct: List[ast.AST] = list(ast.iter_child_nodes(fn_node))
        stack = direct
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested fn's calls attributed when it is indexed
            if isinstance(node, ast.Call):
                mod.calls.append((node, fi))
            stack.extend(ast.iter_child_nodes(node))

    # -- name resolution ------------------------------------------------------

    def dotted(self, expr, imports: Dict[str, str]) -> Optional[str]:
        parts: List[str] = []
        while isinstance(expr, ast.Attribute):
            parts.append(expr.attr)
            expr = expr.value
        if not isinstance(expr, ast.Name):
            return None
        root = imports.get(expr.id, expr.id)
        return ".".join([root] + list(reversed(parts)))

    def is_wrapper_call(self, call: ast.Call, imports: Dict[str, str]
                        ) -> bool:
        d = self.dotted(call.func, imports)
        if d in TRACE_WRAPPERS:
            return True
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr in TRACE_METHODS):
            return True
        return False

    def _unwrap_partial(self, expr, imports) -> Optional[str]:
        """Dotted path of a decorator, through ``partial(jax.jit, ...)``."""
        if isinstance(expr, ast.Call):
            d = self.dotted(expr.func, imports)
            if d in ("functools.partial", "partial"):
                return (self.dotted(expr.args[0], imports)
                        if expr.args else None)
            return d
        return self.dotted(expr, imports)

    def resolve(self, expr, mod: ModuleInfo, fi: Optional[FuncInfo]
                ) -> Optional[FuncInfo]:
        """Resolve a callee expression to a package FuncInfo, or None."""
        if isinstance(expr, ast.Name):
            scope = fi
            while scope is not None:
                if expr.id in scope.nested:
                    return scope.nested[expr.id]
                if scope.parent is not None and expr.id == scope.name:
                    pass
                # sibling closures live on the ENCLOSING function
                if (scope.parent is not None
                        and expr.id in scope.parent.nested):
                    return scope.parent.nested[expr.id]
                scope = scope.parent
            if expr.id in mod.funcs:
                return mod.funcs[expr.id]
            target = mod.imports.get(expr.id)
            if target and target.startswith(PKG_NAME + "."):
                owner, _, attr = target.rpartition(".")
                owned = self.modules.get(owner)
                if owned is not None:
                    return owned.funcs.get(attr)
            return None
        if isinstance(expr, ast.Attribute):
            base, attr = expr.value, expr.attr
            if isinstance(base, ast.Name):
                if base.id in ("self", "cls") and fi is not None:
                    cn = fi.class_name
                    if cn and attr in mod.classes.get(cn, {}):
                        return mod.classes[cn][attr]
                    return self.unique_methods.get(attr)
                target = mod.imports.get(base.id)
                if target:
                    if target.startswith(PKG_NAME):
                        owned = self.modules.get(target)
                        if owned is not None:
                            return owned.funcs.get(attr)
                    return None  # call into an external module
            return self.unique_methods.get(attr)
        return None


# -- discovery ----------------------------------------------------------------

@dataclass
class Discovery:
    traced: Dict[str, FuncInfo]          # qualpath -> info
    dispatch: Dict[str, FuncInfo]
    index: PackageIndex

    def traced_names(self) -> Set[str]:
        return {fi.name for fi in self.traced.values()}

    def dispatch_names(self) -> Set[str]:
        return {fi.name for fi in self.dispatch.values()}

    def discovered_names(self) -> Set[str]:
        """Automatically discovered function names plus the host-staging
        rows seeded from the hot-path table — the full policed surface."""
        from . import hot_path
        return (self.traced_names() | self.dispatch_names()
                | hot_path.policed_functions())


def _key(fi: FuncInfo) -> str:
    return f"{fi.modname}:{fi.qualname}"


def discover(project: Optional[Project] = None) -> Discovery:
    project = project or get_project()
    index = PackageIndex(project)

    roots: List[FuncInfo] = []
    # decorator roots
    for mod in index.modules.values():
        for fi in mod.all_funcs:
            for dec in getattr(fi.node, "decorator_list", []):
                d = index._unwrap_partial(dec, mod.imports)
                if d in TRACE_WRAPPERS:
                    roots.append(fi)
        # wrapper-call roots: every function-valued argument of a tracing
        # transform, resolved from the call's enclosing scope
        for call, enc in mod.calls:
            if not index.is_wrapper_call(call, mod.imports):
                continue
            args = list(call.args)
            d = index.dotted(call.func, mod.imports)
            if d in ("functools.partial", "partial") and args:
                args = args[1:]
            for arg in args:
                if isinstance(arg, ast.Call):
                    # shard_map(partial(_body, spec), ...) and friends
                    d2 = index.dotted(arg.func, mod.imports)
                    if d2 in ("functools.partial", "partial"):
                        for sub in arg.args:
                            target = index.resolve(sub, mod, enc)
                            if target is not None:
                                roots.append(target)
                    continue
                target = index.resolve(arg, mod, enc)
                if target is not None:
                    roots.append(target)

    # transitive closure over the intra-package call graph
    traced: Dict[str, FuncInfo] = {}
    stack = list(roots)
    while stack:
        fi = stack.pop()
        k = _key(fi)
        if k in traced:
            continue
        traced[k] = fi
        mod = index.modules[fi.modname]
        for call, enc in mod.calls:
            if enc is None:
                continue
            # calls made by fi itself or by closures nested inside it
            owner = enc
            while owner is not None and owner is not fi:
                owner = owner.parent
            if owner is None:
                continue
            target = index.resolve(call.func, mod, enc)
            if target is not None and _key(target) not in traced:
                stack.append(target)

    # dispatch boundaries: jit-valued attributes / locals / factories
    jit_like = {w for w in TRACE_WRAPPERS if not w.startswith("jax.lax.")}

    def _is_jit_call(expr, imports) -> bool:
        return (isinstance(expr, ast.Call)
                and index.dotted(expr.func, imports) in jit_like)

    factories: Set[str] = set()       # "mod:Class.method" returning a jit
    for mod in index.modules.values():
        for fi in mod.all_funcs:
            for sub in ast.walk(fi.node):
                if (isinstance(sub, ast.Return)
                        and _is_jit_call(sub.value, mod.imports)):
                    factories.add(_key(fi))

    jit_attrs: Dict[Tuple[str, str], Set[str]] = {}   # (mod, class) -> attrs
    for mod in index.modules.values():
        for fi in mod.all_funcs:
            if fi.class_name is None:
                continue
            for sub in ast.walk(fi.node):
                if not (isinstance(sub, ast.Assign)
                        and len(sub.targets) == 1):
                    continue
                t = sub.targets[0]
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                if _is_jit_call(sub.value, mod.imports):
                    jit_attrs.setdefault(
                        (mod.modname, fi.class_name), set()).add(t.attr)
                elif isinstance(sub.value, ast.Call):
                    f = index.resolve(sub.value.func, mod, fi)
                    if f is not None and _key(f) in factories:
                        jit_attrs.setdefault(
                            (mod.modname, fi.class_name), set()).add(t.attr)
    all_jit_attr_names: Dict[str, int] = {}
    for attrs in jit_attrs.values():
        for a in attrs:
            all_jit_attr_names[a] = all_jit_attr_names.get(a, 0) + 1

    dispatch: Dict[str, FuncInfo] = {}
    for mod in index.modules.values():
        local_jit: Dict[str, Set[str]] = {}
        for fi in mod.all_funcs:
            for sub in ast.walk(fi.node):
                if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Name)
                        and _is_jit_call(sub.value, mod.imports)):
                    local_jit.setdefault(_key(fi), set()).add(
                        sub.targets[0].id)
        for call, enc in mod.calls:
            if enc is None or _key(enc) in traced:
                continue
            f = call.func
            hit = False
            if isinstance(f, ast.Name):
                scope = enc
                while scope is not None and not hit:
                    hit = f.id in local_jit.get(_key(scope), set())
                    scope = scope.parent
            elif isinstance(f, ast.Attribute):
                if (isinstance(f.value, ast.Name) and f.value.id == "self"
                        and enc.class_name is not None):
                    hit = f.attr in jit_attrs.get(
                        (mod.modname, enc.class_name), set())
                if not hit and all_jit_attr_names.get(f.attr, 0) == 1:
                    hit = True  # unique jit attr accessed off another object
            if not hit:
                d = index.dotted(call.func, mod.imports)
                hit = d == "jax.device_put"
            if hit:
                # attribute to the nearest NAMED function (skip closures'
                # parents only when the closure itself is traced)
                dispatch.setdefault(_key(enc), enc)
    return Discovery(traced, dispatch, index)


# -- policing -----------------------------------------------------------------

def _sync_call(index: PackageIndex, call: ast.Call,
               imports: Dict[str, str]) -> str:
    f = call.func
    if isinstance(f, ast.Name) and f.id in _SYNC_NAMES:
        return f"{f.id}()"
    if isinstance(f, ast.Name) and f.id == "one_hot":
        return "one_hot()"
    if isinstance(f, ast.Attribute):
        if f.attr == "one_hot":
            return "one_hot()"
        if f.attr == "block_until_ready":
            return ".block_until_ready()"
        if f.attr in ("item", "tolist") and not call.args:
            return f".{f.attr}()"
        d = index.dotted(f, imports)
        if d == "numpy.asarray":
            return "np.asarray()"
        if d == "jax.device_get":
            return "jax.device_get()"
    return ""


def _host_effect(index: PackageIndex, call: ast.Call,
                 imports: Dict[str, str]) -> str:
    d = index.dotted(call.func, imports)
    if d is None:
        return ""
    if d in _HOST_CLOCKS:
        return f"host clock read {d}()"
    if d.startswith("datetime.") and d.split(".")[-1] in (
            "now", "utcnow", "today", "fromtimestamp"):
        return f"host clock read {d}()"
    if d.startswith("random.") or d.startswith("numpy.random."):
        return f"host RNG {d}()"
    return ""


def _structure_iter(it) -> bool:
    """Constant-trip structure iteration: pytree containers, ``self``
    attributes, ``range`` over non-shape values — trace-time unrolling
    over static structure, not per-element data work."""
    if isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute):
        # dict-pytree iteration: state.items() / params.keys() / .values()
        if (it.func.attr in ("items", "keys", "values") and not it.args
                and _structure_iter(it.func.value)):
            return True
        return False
    if isinstance(it, ast.Call) and isinstance(it.func, ast.Name):
        if it.func.id in ("enumerate", "zip", "reversed", "list", "tuple",
                          "sorted"):
            return all(_structure_iter(a) for a in it.args)
        if it.func.id == "len":
            return True
        if it.func.id == "range":
            for a in it.args:
                for sub in ast.walk(a):
                    if isinstance(sub, ast.Attribute) and sub.attr == "shape":
                        return False
            return True
        return False
    if isinstance(it, (ast.Name, ast.Attribute, ast.Subscript, ast.Tuple,
                       ast.List, ast.Constant)):
        return True
    return False


def police_traced(index: PackageIndex, fi: FuncInfo) -> List[Finding]:
    mod = index.modules[fi.modname]
    out: List[Finding] = []
    where = f"traced code ({fi.qualname}, {os.path.basename(fi.path)})"
    for sub in ast.walk(fi.node):
        if isinstance(sub, ast.Call):
            what = _sync_call(index, sub, mod.imports)
            if what:
                out.append(Finding(
                    fi.path, sub.lineno, JitBoundaryPass.id,
                    f"{what} inside {where} — host syncs break tracing or "
                    f"stall the dispatch pipeline",
                    "keep the computation on device; drain results after "
                    "the jit boundary"))
                continue
            eff = _host_effect(index, sub, mod.imports)
            if eff:
                out.append(Finding(
                    fi.path, sub.lineno, JitBoundaryPass.id,
                    f"{eff} inside {where} — the value constant-folds at "
                    f"trace time and silently freezes",
                    "pass clocks/seeds in as arguments (jax.random for "
                    "in-trace RNG)"))
        elif isinstance(sub, (ast.While,)):
            out.append(Finding(
                fi.path, sub.lineno, JitBoundaryPass.id,
                f"while loop inside {where} — Python control flow "
                f"re-traces or unrolls",
                "use lax.while_loop / lax.scan"))
        elif isinstance(sub, (ast.For, ast.AsyncFor)):
            if not _structure_iter(sub.iter):
                out.append(Finding(
                    fi.path, sub.lineno, JitBoundaryPass.id,
                    f"per-element Python loop inside {where} — unrolls at "
                    f"trace time / re-serializes vectorized work",
                    "vectorize, or use lax.scan over a fixed-shape axis"))
    return out


def _own_loops(fn_node) -> List[ast.AST]:
    """Loops in the function's own body — nested helper defs (e.g. a
    ``drain()`` closure called every N steps) police separately if they
    are themselves boundaries."""
    out: List[ast.AST] = []
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, (ast.For, ast.While, ast.AsyncFor)):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def police_dispatch(index: PackageIndex, fi: FuncInfo) -> List[Finding]:
    mod = index.modules[fi.modname]
    out: List[Finding] = []
    for loop in _own_loops(fi.node):
        for stmt in loop.body + loop.orelse:
            for sub in ast.walk(stmt):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                if isinstance(sub, ast.Call):
                    what = _sync_call(index, sub, mod.imports)
                    if what:
                        out.append(Finding(
                            fi.path, sub.lineno, JitBoundaryPass.id,
                            f"{what} inside the dispatch loop of "
                            f"{fi.qualname} — a per-iteration host sync "
                            f"re-serializes the async dispatch pipeline",
                            "accumulate on device / fetch behind the "
                            "dispatch frontier, drain once after the "
                            "loop"))
    return out


@register_pass
class JitBoundaryPass(LintPass):
    id = "jit-host-sync"
    title = "jit-boundary host-sync escape analysis (auto-discovered)"
    rationale = (
        "trace-boundary regressions — host syncs, frozen clocks/RNG, "
        "per-element loops inside traced code, per-iteration syncs in "
        "dispatch loops — break no functional test; discovery polices "
        "code nobody hand-listed")

    def run(self, project: Project) -> List[Finding]:
        disc = discover(project)
        seen: Set[Tuple[str, int, str]] = set()
        out: List[Finding] = []
        for fi in disc.traced.values():
            for f in police_traced(disc.index, fi):
                k = (f.file, f.line, f.message.split(" inside ")[0])
                if k not in seen:
                    seen.add(k)
                    out.append(f)
        for key, fi in disc.dispatch.items():
            if key in disc.traced:
                continue
            for f in police_dispatch(disc.index, fi):
                k = (f.file, f.line, f.message.split(" inside ")[0])
                if k not in seen:
                    seen.add(k)
                    out.append(f)
        return out
