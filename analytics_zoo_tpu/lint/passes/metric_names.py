"""zoolint pass ``metric-names``: registry names stay canonical.

Ported from ``scripts/check_metric_names.py`` (now a thin shim over this
module). The telemetry plane (``analytics_zoo_tpu/common/metrics.py``)
only stays queryable if names don't rot: a metric registered twice makes
dashboards ambiguous, an off-convention name breaks every ``subsystem.*``
query, and an undocumented metric is invisible to whoever writes the
alerts. Rules:

1. every registration call (``metrics.counter(...)`` / ``.gauge(...)`` /
   ``.histogram(...)`` on a metrics-module alias) passes a string LITERAL
   name (a computed name defeats both this lint and grep);
2. every metric name is registered exactly ONCE across the codebase — one
   name, one owning module (re-registration elsewhere would silently
   alias series);
3. names follow the ``subsystem.noun_unit`` convention
   (lower_snake, one dot), counters end in ``_total``, histograms in
   ``_seconds`` (all our histograms observe durations), and gauges carry
   a unit suffix (``_seconds``/``_bytes``/``_ratio``/``_depth``) unless
   allow-listed as genuinely unitless;
4. every registered metric is documented in ``docs/observability.md``
   (the metric table is the operator's scrape vocabulary).
"""
from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Tuple

from ..core import (Finding, LintPass, Project, REPO_ROOT, get_project,
                    register_pass)

_PKG = os.path.join(REPO_ROOT, "analytics_zoo_tpu")
_DOCS = os.path.join(REPO_ROOT, "docs", "observability.md")

#: common/metrics.py itself is excluded (its internal plumbing calls the
#: same method names on ``self``/fresh registries)
_EXCLUDE = (os.path.join("common", "metrics.py"),)

_KINDS = ("counter", "gauge", "histogram")
_NAME_RE = re.compile(r"^[a-z][a-z0-9]*\.[a-z][a-z0-9_]*$")
_UNIT_SUFFIX = {"counter": "_total", "histogram": "_seconds"}

#: gauges must say what they measure; any of these suffixes qualifies
_GAUGE_UNIT_SUFFIXES = ("_seconds", "_bytes", "_ratio", "_depth")
#: gauges that are genuinely unitless: live request/slot counts, the
#: info-style constant-1 build gauge (labels carry the payload), and the
#: enumerated state machines (brownout rung, breaker state)
_GAUGE_UNITLESS_OK = {"serving.in_flight", "serving.slots_occupied",
                      "serving.kv_pages_free", "build.info",
                      "fleet.instances_alive", "fleet.desired_instances",
                      "cluster.leases_alive", "serving.brownout_level",
                      "fleet.breaker_state"}


def _is_registration(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr in _KINDS
            and isinstance(f.value, ast.Name)
            and (f.value.id == "metrics" or f.value.id.endswith("_metrics")))


def registrations() -> Tuple[Dict[str, List[Tuple[str, str]]],
                             List[Tuple[str, int, str]]]:
    """``{name: [(file:line, kind), ...]}`` over all scanned files, plus
    violations for non-literal name arguments."""
    project = get_project()
    regs: Dict[str, List[Tuple[str, str]]] = {}
    bad: List[Tuple[str, int, str]] = []
    files = project.package_files()
    if os.path.exists(project.bench_file()):
        files = files + [project.bench_file()]
    for path in sorted(files):
        rel = os.path.relpath(path, REPO_ROOT)
        if any(rel.endswith(e) for e in _EXCLUDE):
            continue
        tree = project.ast_for(path)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and _is_registration(node)):
                continue
            where = f"{rel}:{node.lineno}"
            if (not node.args
                    or not isinstance(node.args[0], ast.Constant)
                    or not isinstance(node.args[0].value, str)):
                bad.append((path, node.lineno,
                            "metric name must be one string literal"))
                continue
            regs.setdefault(node.args[0].value, []).append(
                (where, node.func.attr))
    return regs, bad


def undocumented(names) -> List[str]:
    """Registered names with no `` `name` `` mention in the metric docs."""
    try:
        with open(_DOCS) as fh:
            text = fh.read()
    except OSError:
        return sorted(names)
    return sorted(n for n in names if f"`{n}`" not in text)


def _locate(regs: Dict[str, List[Tuple[str, str]]], name: str
            ) -> Tuple[str, int]:
    where = regs[name][0][0]
    rel, _, line = where.rpartition(":")
    return os.path.join(REPO_ROOT, rel), int(line)


def check() -> List[str]:
    """Human-readable violations; empty = clean."""
    return [f.message for f in findings()]


def findings() -> List[Finding]:
    regs, bad = registrations()
    out: List[Finding] = []
    for p, line, what in bad:
        out.append(Finding(p, line, MetricNamesPass.id,
                           f"{os.path.relpath(p, REPO_ROOT)}:{line}: {what}",
                           "pass the metric name as one string literal"))
    for name, places in sorted(regs.items()):
        path, line = _locate(regs, name)
        if len(places) > 1:
            out.append(Finding(
                path, line, MetricNamesPass.id,
                f"metric {name!r} registered at {len(places)} sites "
                f"({', '.join(w for w, _ in places)}); each name must be "
                f"registered exactly once",
                "keep one owning module per metric"))
        kind = places[0][1]
        if not _NAME_RE.match(name):
            out.append(Finding(
                path, line, MetricNamesPass.id,
                f"metric {name!r} ({places[0][0]}) breaks the "
                f"'subsystem.noun_unit' convention (lower_snake, one dot)",
                "rename to subsystem.noun_unit"))
        suffix = _UNIT_SUFFIX.get(kind)
        if suffix and not name.endswith(suffix):
            out.append(Finding(
                path, line, MetricNamesPass.id,
                f"{kind} {name!r} ({places[0][0]}) must end in "
                f"'{suffix}'", f"rename with the {suffix} suffix"))
        if (kind == "gauge" and name not in _GAUGE_UNITLESS_OK
                and not name.endswith(_GAUGE_UNIT_SUFFIXES)):
            out.append(Finding(
                path, line, MetricNamesPass.id,
                f"gauge {name!r} ({places[0][0]}) must end in one of "
                f"{'/'.join(_GAUGE_UNIT_SUFFIXES)} or be allow-listed in "
                f"_GAUGE_UNITLESS_OK",
                "add a unit suffix or allow-list a genuinely unitless "
                "gauge"))
    for name in undocumented(regs):
        path, line = _locate(regs, name)
        out.append(Finding(
            path, line, MetricNamesPass.id,
            f"metric {name!r} is registered but undocumented — add a row "
            f"to the metric table in docs/observability.md",
            "document every metric an operator can scrape"))
    return out


@register_pass
class MetricNamesPass(LintPass):
    id = "metric-names"
    title = "metrics registry naming/uniqueness/documentation contract"
    rationale = (
        "telemetry only stays queryable if names stay literal, unique, "
        "canonical and documented — drift is invisible to behavioral "
        "tests")

    def run(self, project: Project) -> List[Finding]:
        return findings()


def main() -> int:
    problems = check()
    if not problems:
        print(f"metric-name lint: clean ({len(registrations()[0])} metrics,"
              f" all literal, unique, canonical and documented)")
        return 0
    for p in problems:
        print(p, file=sys.stderr)
    return 1
