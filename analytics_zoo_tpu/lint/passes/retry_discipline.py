"""zoolint pass ``retry-discipline``: retries must be jittered and bounded.

A fleet under overload is a synchronized system: every client that saw
the same shed error retries on the same schedule, so a FIXED retry delay
turns one overload spike into a standing wave of them (the classic retry
storm), and an UNBOUNDED retry loop turns one dead backend into a caller
that never returns. The package-wide rules (docs/serving.md "Overload
survival" — ``ResilientClient`` and ``file_io._remote_op`` are the
reference implementations):

* **No fixed retry sleeps.** A ``time.sleep(<constant>)`` lexically
  inside an ``except`` handler that sits in a loop is a fixed, unjittered
  retry delay — compute the delay instead (exponential backoff, ideally
  with full jitter: ``rng.uniform(0, base * 2 ** attempt)``).
* **No unbounded retry loops.** A ``while True`` loop that catches
  exceptions but contains NO escape at all (no ``raise``, ``return`` or
  ``break`` anywhere in its body) retries forever with no budget or
  deadline — bound it by an attempt counter, a deadline, or a retry
  budget (:class:`~analytics_zoo_tpu.serving.client.RetryBudget`).

Scope is the package only (``tests/`` and ``bench.py`` drive chaos loops
on purpose). Waive a deliberate fixed delay with
``# zoolint: disable=retry-discipline — <why>`` and a justification.
"""
from __future__ import annotations

import ast
import sys
from typing import List

from ..core import Finding, LintPass, Project, get_project, register_pass
from .monotonic_clock import _dotted, _import_map

_LOOPS = (ast.For, ast.AsyncFor, ast.While)


def _is_constant_sleep(node: ast.AST, imports) -> bool:
    """``time.sleep(<literal>)`` (or an aliased import of it) — the
    argument must be a plain constant, not computed from an attempt
    counter or drawn from an rng."""
    if not (isinstance(node, ast.Call) and node.args):
        return False
    d = _dotted(node.func, imports)
    if d not in ("time.sleep", "sleep") and not d.endswith(".sleep"):
        return False
    return isinstance(node.args[0], ast.Constant)


def _walk_same_scope(body: List[ast.stmt]):
    """Walk statements without descending into nested function/class
    definitions (their control flow is not this loop's control flow)."""
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        for child in ast.iter_child_nodes(node):
            stack.append(child)


def _has_escape(loop: ast.While) -> bool:
    """Any ``raise``/``return``/``break`` in the loop's own scope (nested
    loops' breaks still bound *some* iteration, so they count — the rule
    targets loops with literally no exit path)."""
    for node in _walk_same_scope(loop.body):
        if isinstance(node, (ast.Raise, ast.Return, ast.Break)):
            return True
    return False


def _handlers_in(loop: ast.stmt) -> List[ast.ExceptHandler]:
    return [n for n in _walk_same_scope(loop.body)
            if isinstance(n, ast.ExceptHandler)]


def findings(project=None) -> List[Finding]:
    project = project or get_project()
    out: List[Finding] = []
    for path in project.package_files():
        tree = project.ast_for(path)
        imports = _import_map(tree)
        for node in ast.walk(tree):
            if not isinstance(node, _LOOPS):
                continue
            handlers = _handlers_in(node)
            if not handlers:
                continue
            for h in handlers:
                for sub in _walk_same_scope(h.body):
                    if _is_constant_sleep(sub, imports):
                        out.append(Finding(
                            path, sub.lineno, RetryDisciplinePass.id,
                            "fixed (unjittered) retry delay — every "
                            "caller that saw the same error retries in "
                            "lockstep, re-spiking the backend it is "
                            "retrying against",
                            "compute the delay: full-jitter exponential "
                            "backoff (rng.uniform(0, base * 2**attempt)) "
                            "as in serving.client.ResilientClient"))
            if (isinstance(node, ast.While)
                    and isinstance(node.test, ast.Constant)
                    and node.test.value is True
                    and not _has_escape(node)):
                out.append(Finding(
                    path, node.lineno, RetryDisciplinePass.id,
                    "unbounded `while True` retry loop — catches "
                    "exceptions but has no raise/return/break escape, "
                    "so a dead dependency is retried forever",
                    "bound it with an attempt counter, a deadline, or "
                    "a RetryBudget (serving.client)"))
    return out


def check() -> List[str]:
    """Human-readable violations; empty = clean."""
    return [f.message for f in findings()]


@register_pass
class RetryDisciplinePass(LintPass):
    id = "retry-discipline"
    title = "retries jittered and bounded (no storms, no forever-loops)"
    rationale = (
        "a fixed retry delay synchronizes every failed caller into a "
        "retry storm, and an unbounded retry loop hangs on a dead "
        "backend — jittered exponential backoff under an explicit "
        "budget/deadline is the only retry shape the package allows")

    def run(self, project: Project) -> List[Finding]:
        return findings(project)


def main() -> int:
    problems = check()
    if not problems:
        print("retry-discipline lint: clean")
        return 0
    for p in problems:
        print(p, file=sys.stderr)
    return 1
