"""zoolint pass ``hot-path-sync``: the hand-curated host hot-path policy.

Ported from ``scripts/check_hot_path_syncs.py`` (which is now a thin shim
over this module). The six policy families — estimator dispatch loops,
FeatureSet batch staging, DeviceFeed eval adaptation, sharded-embedding
exchange bodies, the slot decode engine, and the paged/speculative decode
bodies — keep their exact legacy semantics here, table-driven: each row
names the file, the functions, the extra banned ``np.*`` attrs, whether
Python loops are banned outright, and the scope (whole body vs loop
bodies only).

The table stays the right tool for HOST-side staging rules (``_gather``
must route copies through ``np.take(out=)``, ``masked_eval_batches`` must
not rebuild its arange mask — allocation policies no trace analysis can
infer). Device-side rows are additionally *rediscovered automatically* by
the ``jit-host-sync`` pass, which polices the whole traced closure, so the
next decode/embedding PR is covered before anyone edits this table.
"""
from __future__ import annotations

import ast
import os
import sys
from typing import List, Optional, Sequence, Tuple

from ..core import (Finding, LintPass, Project, REPO_ROOT, get_project,
                    register_pass)

ESTIMATOR_PY = os.path.join(REPO_ROOT, "analytics_zoo_tpu", "estimator",
                            "estimator.py")
FEATURESET_PY = os.path.join(REPO_ROOT, "analytics_zoo_tpu", "feature",
                             "featureset.py")
DEVICE_FEED_PY = os.path.join(REPO_ROOT, "analytics_zoo_tpu", "feature",
                              "device_feed.py")
EMBEDDING_PY = os.path.join(REPO_ROOT, "analytics_zoo_tpu", "parallel",
                            "embedding.py")
DECODE_PY = os.path.join(REPO_ROOT, "analytics_zoo_tpu", "ops", "decode.py")
LM_PY = os.path.join(REPO_ROOT, "analytics_zoo_tpu", "capture", "lm.py")
SERVER_PY = os.path.join(REPO_ROOT, "analytics_zoo_tpu", "serving",
                         "server.py")
FLEET_PY = os.path.join(REPO_ROOT, "analytics_zoo_tpu", "serving",
                        "fleet.py")
ENGINE_PY = os.path.join(REPO_ROOT, "analytics_zoo_tpu", "xshard",
                         "engine.py")
PIPELINE_PY = os.path.join(REPO_ROOT, "analytics_zoo_tpu", "parallel",
                           "pipeline.py")
RING_PY = os.path.join(REPO_ROOT, "analytics_zoo_tpu", "parallel",
                       "ring_attention.py")
MOE_PY = os.path.join(REPO_ROOT, "analytics_zoo_tpu", "parallel",
                      "moe.py")

#: model-parallel traced bodies. The pipeline scan bodies run once per
#: tick inside ``lax.scan`` under ``shard_map``, the ring bodies once per
#: ppermute hop, the MoE exchange once per step — all pure device code:
#: loop-free outright (scan/ppermute replace Python iteration), no host
#: syncs, no ``np.*`` staging, no one_hot densification.
PIPELINE_BODIES = ("pipeline_apply", "_pipe_fwd_body", "_pipe_1f1b_body")
# ulysses_attention is deliberately NOT a row: it is a per-shard body the
# CALLER wraps in shard_map, so the jit-boundary pass has no package-level
# trace site to auto-discover it from (the discovery-coverage invariant in
# tests/test_zoolint.py would break); the ring bodies below are reached
# through ring_self_attention/ring_context's own shard_map wrappers.
RING_BODIES = ("ring_attention", "ring_masked_context")
MOE_BODIES = ("_expert_exchange",)

EMBED_BODIES = ("_routing", "_lookup_body", "_lookup_bwd_body",
                "_update_body")

EMBED_KERNELS_PY = os.path.join(REPO_ROOT, "analytics_zoo_tpu", "ops",
                                "embedding_kernels.py")

#: fused embedding kernels (ops/embedding_kernels.py). The KERNEL_BODIES
#: are the per-row hot cores — the pallas kernel bodies and the fused
#: lookup/pool/backward primitives the engine and layers trace per step:
#: loop-free outright (fori_loop is a traced call, not a Python loop), no
#: one_hot densification, no host syncs. The WRAPPERS (multi-table
#: dispatch, table quantization) may loop over the static table count but
#: still must not sync or densify.
EMBED_KERNEL_BODIES = ("gather_rows", "gather_rows_clip", "segment_grads",
                       "scatter_rows", "gather_pool", "gather_pool_int8",
                       "_gather_pool_ref", "_gather_kernel",
                       "_gather_int8_kernel", "_gather_pool_kernel",
                       "_scatter_add_kernel")
EMBED_KERNEL_WRAPPERS = ("multi_table_lookup", "quantize_table",
                         "fused_enabled")

SLOT_OPS = ("init_slot_cache", "slot_join", "slot_evict", "slot_insert",
            "slot_attention")

PAGED_OPS = ("init_paged_pool", "page_table_set", "page_table_clear",
             "page_copy", "_page_positions", "_paged_write", "paged_gather",
             "paged_insert", "paged_attention", "paged_verify_attention",
             "spec_accept_greedy", "_spec_accept_sampled")

HOT_FUNCS = ("evaluate", "_evaluate_direct", "_evaluate_direct_exact",
             "predict")

#: XShard ETL engine bodies. The KERNELS are the per-row-scale vector
#: cores (hash mixing, bucket reorder, join match, handoff scatter):
#: loop-free outright. The TASKS are the exchange/partition/gather/
#: combine bodies: loops there are column/source-count sized and legal,
#: but host syncs and full-frame ``pd.concat`` are not.
ETL_KERNELS = ("_mix64", "_bucket_order", "_join_match", "_stack_into",
               "_exchange_task")
ETL_TASKS = ("_gather_dest", "_filter_task", "_groupby_task", "_join_task",
             "_handoff_task", "_take_cols_into")

#: policy rows: (path, class name or None for module level, function names,
#: extra banned np.<attr> calls, ban per-record loops?, scope)
#: scope "loops" = only loop bodies inside the function are policed;
#: scope "body"  = the whole function body is policed (innermost hot funcs)
_CHECKS: List[Tuple[str, Optional[str], Sequence[str], Sequence[str],
                    bool, str]] = [
    (ESTIMATOR_PY, "Estimator", HOT_FUNCS, (), False, "loops"),
    (FEATURESET_PY, "FeatureSet", ("_gather",), ("asarray",), True, "body"),
    (FEATURESET_PY, "LazyTransformFeatureSet",
     ("train_iterator", "eval_iterator", "_transformed_batches",
      "_cached_batches"), (), False, "loops"),
    (DEVICE_FEED_PY, None, ("masked_eval_batches",), ("arange",), False,
     "loops"),
    (DEVICE_FEED_PY, None, ("_produce",), (), False, "loops"),
    (EMBEDDING_PY, None, EMBED_BODIES, (), True, "body"),
    (EMBED_KERNELS_PY, None, EMBED_KERNEL_BODIES, (), True, "body"),
    (EMBED_KERNELS_PY, None, EMBED_KERNEL_WRAPPERS, (), False, "body"),
    (DECODE_PY, None, SLOT_OPS, (), True, "body"),
    (DECODE_PY, None, PAGED_OPS, (), True, "body"),
    (LM_PY, "TransformerLM",
     ("slot_step", "prefill_kv", "paged_slot_step", "verify_step",
      "prefill_kv_suffix"), (), False, "body"),
    (SERVER_PY, "GenerativeServing",
     ("_dispatch_step", "_insert_request_device", "_insert_request_paged",
      "_insert_request_spec", "_insert_suffix_paged", "_copy_page_device",
      "_evict_slots"), (), True, "body"),
    # the fleet router's placement scoring runs once per routed request:
    # it must stay a single vectorized pass over the instance-gauge
    # arrays — no host syncs, no per-request Python loop over instances
    (FLEET_PY, None, ("_score_instances",), (), True, "body"),
    (ENGINE_PY, None, ETL_KERNELS, (), True, "body"),
    (ENGINE_PY, None, ETL_TASKS, (), False, "body"),
    (PIPELINE_PY, None, PIPELINE_BODIES, (), True, "body"),
    (RING_PY, None, RING_BODIES, (), True, "body"),
    (MOE_PY, None, MOE_BODIES, (), True, "body"),
]


def _banned_call(node: ast.Call, np_attrs: Sequence[str] = ("asarray",)
                 ) -> str:
    f = node.func
    if isinstance(f, ast.Name) and f.id == "float":
        return "float()"
    if isinstance(f, ast.Name) and f.id == "one_hot":
        return "one_hot()"
    if isinstance(f, ast.Attribute):
        if f.attr == "one_hot":
            return "one_hot()"
        base = f.value
        if (f.attr in np_attrs and isinstance(base, ast.Name)
                and base.id in ("np", "numpy")):
            return f"{base.id}.{f.attr}()"
        if (f.attr == "concat" and isinstance(base, ast.Name)
                and base.id in ("pd", "pandas")):
            # a full-frame concat in a policed body is the seed-era
            # gather-everything antipattern the ETL engine exists to kill
            return f"{base.id}.concat()"
        if (f.attr == "device_get" and isinstance(base, ast.Name)
                and base.id == "jax"):
            return "jax.device_get()"
        if f.attr == "block_until_ready":
            return ".block_until_ready()"
    return ""


def _iter_functions(tree: ast.Module, cls: Optional[str],
                    names: Sequence[str]):
    if cls is None:
        for node in tree.body:
            if isinstance(node, ast.FunctionDef) and node.name in names:
                yield node
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls:
            for fn in node.body:
                if isinstance(fn, ast.FunctionDef) and fn.name in names:
                    yield fn


def _scan_stmts(stmts, np_attrs, out, fn_name):
    for stmt in stmts:
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call):
                what = _banned_call(sub, np_attrs)
                if what:
                    out.append((fn_name, sub.lineno, what))


def _check_file(path: str, cls: Optional[str], names: Sequence[str],
                extra_np: Sequence[str], ban_loops: bool, scope: str
                ) -> List[Tuple[str, int, str]]:
    tree = get_project().ast_for(path)
    np_attrs = ("asarray",) + tuple(extra_np)
    violations: List[Tuple[str, int, str]] = []
    for fn in _iter_functions(tree, cls, names):
        if scope == "body":
            _scan_stmts(fn.body, np_attrs, violations, fn.name)
            if ban_loops:
                for sub in ast.walk(fn):
                    if isinstance(sub, (ast.For, ast.While, ast.AsyncFor,
                                        ast.ListComp, ast.SetComp,
                                        ast.DictComp, ast.GeneratorExp)):
                        violations.append(
                            (fn.name, sub.lineno, "per-record Python loop"))
            continue
        for loop in ast.walk(fn):
            if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
                continue
            _scan_stmts(loop.body + loop.orelse, np_attrs, violations,
                        fn.name)
    return violations


def policed_functions() -> set:
    """All function names the policy table polices (the legacy hand-listed
    coverage the ``jit-host-sync`` discovery must dominate)."""
    return {fn for row in _CHECKS for fn in row[2]}


def check(path: Optional[str] = None
          ) -> List[Tuple[str, str, int, str]]:
    """Return ``(file, function, line, what)`` violations; empty = clean.
    With an explicit ``path`` only the Estimator dispatch-loop policy runs
    against that file (self-test hook)."""
    if path is not None:
        return [(path, fn, line, what) for fn, line, what in
                _check_file(path, "Estimator", HOT_FUNCS, (), False,
                            "loops")]
    out: List[Tuple[str, str, int, str]] = []
    for (p, cls, names, extra_np, ban_loops, scope) in _CHECKS:
        out.extend((p, fn, line, what) for fn, line, what in
                   _check_file(p, cls, names, extra_np, ban_loops, scope))
    return out


@register_pass
class HotPathPass(LintPass):
    id = "hot-path-sync"
    title = "hand-curated hot-path sync/loop/allocation policy"
    rationale = (
        "the data-plane, eval/predict, embedding-exchange and decode hot "
        "paths must stay free of per-batch host syncs, per-record Python "
        "and per-batch allocation — regressions are invisible to "
        "functional tests and only a healthy BENCH round would notice")

    def run(self, project: Project) -> List[Finding]:
        return [
            Finding(path, line, self.id,
                    f"{what} inside the hot path of {fn}",
                    "route syncs behind the dispatch frontier / drain "
                    "after the loop; keep per-batch staging vectorized")
            for path, fn, line, what in check()
        ]


def main() -> int:
    violations = check()
    if not violations:
        print("hot-path sync lint: clean")
        return 0
    for path, fn, line, what in violations:
        print(f"{path}:{line}: {what} inside the hot path of {fn} — "
              f"route syncs behind the dispatch frontier / drain after "
              f"the loop, and keep per-batch staging vectorized",
              file=sys.stderr)
    return 1
