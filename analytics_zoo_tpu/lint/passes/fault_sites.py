"""zoolint pass ``fault-sites``: injection registry <-> call-site bijection.

Ported from ``scripts/check_fault_sites.py`` (now a thin shim over this
module). Chaos coverage rots silently: an injection site that no test arms
is dead code wearing a safety vest, and a registry row whose call site was
refactored away advertises protection that no longer exists. Rules:

1. every ``faults.inject(...)`` call passes a string LITERAL (a computed
   site name defeats both this lint and grep);
2. every injected site name is registered in
   ``analytics_zoo_tpu/common/faults.py``'s ``REGISTRY``;
3. site names are UNIQUE across call sites — one site, one place (a name
   shared by two call sites makes budgets/schedules ambiguous);
4. every REGISTRY row has a live call site (no stale advertising);
5. every site name appears in at least one file under ``tests/`` — i.e.
   some test arms or asserts on it;
6. every registered site is documented in ``docs/faults.md`` (the site
   table is the operator's chaos-plan vocabulary).
"""
from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Set, Tuple

from ..core import (Finding, LintPass, Project, REPO_ROOT, get_project,
                    register_pass)

_PKG = os.path.join(REPO_ROOT, "analytics_zoo_tpu")
_FAULTS_PY = os.path.join(_PKG, "common", "faults.py")
_TESTS_DIR = os.path.join(REPO_ROOT, "tests")
_DOCS_FAULTS = os.path.join(REPO_ROOT, "docs", "faults.md")


def registry_sites(path: str = _FAULTS_PY) -> Set[str]:
    """Site names from the REGISTRY dict literal (AST parse — import-free,
    shared with the cached project index)."""
    tree = get_project().ast_for(path)
    for node in tree.body:
        target = None
        if isinstance(node, ast.AnnAssign):
            target, value = node.target, node.value
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        if (isinstance(target, ast.Name) and target.id == "REGISTRY"
                and isinstance(value, ast.Dict)):
            for k in value.keys:
                if not (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    raise AssertionError(
                        f"{path}: REGISTRY keys must be string literals")
            return {k.value for k in value.keys}
    raise AssertionError(f"{path}: no REGISTRY dict literal found")


def _is_inject_call(node: ast.Call) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr == "inject"
            and isinstance(f.value, ast.Name) and f.value.id == "faults")


def inject_sites() -> Tuple[Dict[str, List[str]], List[Tuple[str, int, str]]]:
    """``{site: [file:line, ...]}`` over all scanned files, plus
    violations for non-literal site arguments."""
    project = get_project()
    calls: Dict[str, List[str]] = {}
    bad: List[Tuple[str, int, str]] = []
    files = project.package_files()
    if os.path.exists(project.bench_file()):
        files = files + [project.bench_file()]
    for path in sorted(files):
        tree = project.ast_for(path)
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and _is_inject_call(node)):
                continue
            where = f"{os.path.relpath(path, REPO_ROOT)}:{node.lineno}"
            if (len(node.args) != 1
                    or not isinstance(node.args[0], ast.Constant)
                    or not isinstance(node.args[0].value, str)):
                bad.append((path, node.lineno,
                            "faults.inject() site must be one string "
                            "literal"))
                continue
            calls.setdefault(node.args[0].value, []).append(where)
    return calls, bad


def tests_mentioning(site: str) -> List[str]:
    out = []
    for path in get_project().test_files():
        if site in get_project().source(path).text:
            out.append(os.path.basename(path))
    return out


def undocumented_sites(registered: Set[str]) -> List[str]:
    """Registered sites with no `` `site` `` mention in docs/faults.md."""
    try:
        with open(_DOCS_FAULTS) as fh:
            text = fh.read()
    except OSError:
        return sorted(registered)
    return sorted(s for s in registered if f"`{s}`" not in text)


def findings() -> List[Finding]:
    registered = registry_sites()
    calls, bad = inject_sites()
    out: List[Finding] = []
    for p, line, what in bad:
        out.append(Finding(p, line, FaultSitesPass.id,
                           f"{os.path.relpath(p, REPO_ROOT)}:{line}: {what}",
                           "pass the site name as one string literal"))

    def _site_loc(places: List[str]) -> Tuple[str, int]:
        rel, _, line = places[0].rpartition(":")
        return os.path.join(REPO_ROOT, rel), int(line)

    for site, places in sorted(calls.items()):
        path, line = _site_loc(places)
        if site not in registered:
            out.append(Finding(
                path, line, FaultSitesPass.id,
                f"site {site!r} injected at {places[0]} but not registered "
                f"in common/faults.py REGISTRY",
                "add a REGISTRY row (kind, description)"))
        if len(places) > 1:
            out.append(Finding(
                path, line, FaultSitesPass.id,
                f"site {site!r} injected from {len(places)} call sites "
                f"({', '.join(places)}); site names must be unique",
                "split into per-call-site names"))
        if not tests_mentioning(site):
            out.append(Finding(
                path, line, FaultSitesPass.id,
                f"site {site!r} is not exercised by any test under tests/ "
                f"(arm it in a chaos test or drop the site)",
                "arm the site in a chaos test"))
    for site in sorted(registered - set(calls)):
        out.append(Finding(
            _FAULTS_PY, 1, FaultSitesPass.id,
            f"REGISTRY advertises site {site!r} but no faults.inject("
            f"{site!r}) call exists in the codebase",
            "drop the stale row or restore the call site"))
    for site in undocumented_sites(registered):
        out.append(Finding(
            _FAULTS_PY, 1, FaultSitesPass.id,
            f"site {site!r} is registered but undocumented — add a row to "
            f"the site table in docs/faults.md",
            "document every chaos site an operator can arm"))
    return out


def check() -> List[str]:
    """Human-readable violations; empty = clean."""
    return [f.message for f in findings()]


@register_pass
class FaultSitesPass(LintPass):
    id = "fault-sites"
    title = "fault-injection registry/call-site/test/doc bijection"
    rationale = (
        "an injection site no test arms is dead code wearing a safety "
        "vest; a registry row without a call site advertises protection "
        "that no longer exists")

    def run(self, project: Project) -> List[Finding]:
        return findings()


def main() -> int:
    problems = check()
    if not problems:
        print(f"fault-site lint: clean "
              f"({len(registry_sites())} sites, all registered, unique, "
              f"test-exercised and documented)")
        return 0
    for p in problems:
        print(p, file=sys.stderr)
    return 1
