"""zoolint core: shared parse pass, findings, suppressions, pass registry.

Every checker in this package is a *pass* over one shared :class:`Project`
index — each file under the package, ``bench.py``, and ``tests/`` is read
and AST-parsed exactly once per process (cached by mtime/size), no matter
how many passes run or how many entry points (pytest collection guards,
the ``python -m analytics_zoo_tpu.lint`` CLI, the legacy ``scripts/
check_*.py`` shims) invoke them.

Findings can be waived per line with a suppression comment::

    x = time.time()  # zoolint: disable=monotonic-clock — cross-process stamp

or, on its own line, applying to the next source line::

    # zoolint: disable=jit-host-sync — constant-trip per-BLOCK tracing loop
    for li, blk in enumerate(params["blocks"]):

A file-level waiver (``# zoolint: disable-file=<pass>``) anywhere in a file
waives the whole file for that pass. Every suppression MUST carry a
justification after the pass list, and the built-in ``unused-suppression``
check fails when a waiver no longer matches any finding — waivers cannot
rot into silent blanket exemptions.
"""
from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: repo root: analytics_zoo_tpu/lint/core.py -> repo
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PACKAGE_DIR = os.path.join(REPO_ROOT, "analytics_zoo_tpu")

UNUSED_SUPPRESSION_ID = "unused-suppression"

_SUPP_RE = re.compile(
    r"zoolint:\s*(disable|disable-file)=([A-Za-z0-9_,\-]+)\s*(.*)")


@dataclass(frozen=True)
class Finding:
    """One lint result. ``file`` is absolute; ``rel()`` is repo-relative."""
    file: str
    line: int
    pass_id: str
    message: str
    fix_hint: str = ""

    def rel(self) -> str:
        try:
            return os.path.relpath(self.file, REPO_ROOT)
        except ValueError:
            return self.file

    def text(self) -> str:
        hint = f"  [fix: {self.fix_hint}]" if self.fix_hint else ""
        return f"{self.rel()}:{self.line}: [{self.pass_id}] {self.message}{hint}"

    def github(self) -> str:
        msg = self.message.replace("%", "%25").replace("\n", "%0A")
        return (f"::error file={self.rel()},line={self.line},"
                f"title=zoolint/{self.pass_id}::{msg}")


@dataclass
class Suppression:
    line: int                 # comment's own line number
    pass_ids: Tuple[str, ...]
    justification: str
    file_level: bool
    used: bool = False


@dataclass
class SourceFile:
    path: str
    text: str
    _tree: Optional[ast.Module] = field(default=None, repr=False)
    _suppressions: Optional[List[Suppression]] = field(default=None,
                                                       repr=False)

    @property
    def tree(self) -> ast.Module:
        """AST, parsed on first access — passes that only need raw text
        (e.g. test-mention scans) never pay for a parse of the file."""
        if self._tree is None:
            self._tree = ast.parse(self.text, filename=self.path)
        return self._tree

    @property
    def suppressions(self) -> List[Suppression]:
        """Waiver comments, tokenized on first access (tokenize is
        pure-Python; text-only scans shouldn't pay for it)."""
        if self._suppressions is None:
            self._suppressions = _parse_suppressions(self.path, self.text)
        return self._suppressions

    def _match(self, pass_id: str, line: int) -> Optional[Suppression]:
        for s in self.suppressions:
            if pass_id not in s.pass_ids:
                continue
            if s.file_level or s.line in (line, line - 1):
                return s
        return None

    def suppresses(self, finding: Finding) -> bool:
        """True (and marks the waiver used) when a suppression covers the
        finding: same line, the standalone comment line directly above, or
        a file-level waiver."""
        s = self._match(finding.pass_id, finding.line)
        if s is not None:
            s.used = True
            return True
        return False


def _parse_suppressions(path: str, text: str) -> List[Suppression]:
    """Comment-token scan (``tokenize``), so a ``# zoolint:`` sequence
    inside a string literal — e.g. a test fixture seeding a bad file — is
    never mistaken for a live waiver."""
    out: List[Suppression] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(text).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPP_RE.search(tok.string)
            if not m:
                continue
            ids = tuple(p.strip() for p in m.group(2).split(",") if p.strip())
            just = m.group(3).strip().lstrip("—–:- (").rstrip(")").strip()
            out.append(Suppression(tok.start[0], ids, just,
                                   m.group(1) == "disable-file"))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass
    return out


class Project:
    """Cached AST + source index over the repo's analyzable files."""

    def __init__(self, root: str = REPO_ROOT) -> None:
        self.root = os.path.abspath(root)
        self._cache: Dict[str, Tuple[Tuple[float, int], SourceFile]] = {}

    # -- file walks -----------------------------------------------------------

    def _walk(self, base: str) -> List[str]:
        if os.path.isfile(base):
            return [base]
        files: List[str] = []
        for dirpath, dirs, names in os.walk(base):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            files.extend(os.path.join(dirpath, n) for n in names
                         if n.endswith(".py"))
        return sorted(files)

    def package_files(self) -> List[str]:
        return self._walk(os.path.join(self.root, "analytics_zoo_tpu"))

    def test_files(self) -> List[str]:
        return self._walk(os.path.join(self.root, "tests"))

    def bench_file(self) -> str:
        return os.path.join(self.root, "bench.py")

    def all_files(self) -> List[str]:
        files = self.package_files() + self.test_files()
        bench = self.bench_file()
        if os.path.exists(bench):
            files.append(bench)
        return files

    # -- cached parse ---------------------------------------------------------

    def source(self, path: str) -> SourceFile:
        """Parse-once accessor; works for any path (tests hand it tmp
        files), keyed by (mtime, size) so edits invalidate."""
        path = os.path.abspath(path)
        st = os.stat(path)
        key = (st.st_mtime, st.st_size)
        hit = self._cache.get(path)
        if hit is not None and hit[0] == key:
            return hit[1]
        with open(path) as fh:
            text = fh.read()
        src = SourceFile(path, text)
        self._cache[path] = (key, src)
        return src

    def ast_for(self, path: str) -> ast.Module:
        return self.source(path).tree


_project: Optional[Project] = None


def get_project() -> Project:
    """The process-global shared index — every entry point funnels here, so
    the repo is read and parsed once per process."""
    global _project
    if _project is None:
        _project = Project()
    return _project


# -- pass registry ------------------------------------------------------------

class LintPass:
    """One analysis plugin. Subclasses set ``id``/``title``/``rationale``
    and implement ``run(project) -> list[Finding]`` (raw findings —
    suppression filtering happens in the runner)."""

    id: str = ""
    title: str = ""
    rationale: str = ""

    def run(self, project: Project) -> List[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, LintPass] = {}


def register_pass(cls):
    """Class decorator: instantiate and register a pass by its ``id``.
    Re-registration with the same id replaces (supports module reload)."""
    inst = cls()
    if not inst.id:
        raise ValueError(f"{cls.__name__} has no pass id")
    _REGISTRY[inst.id] = inst
    return cls


def all_passes() -> Dict[str, LintPass]:
    from . import passes  # noqa: F401 — importing registers the plugins
    return dict(_REGISTRY)


@dataclass
class RunResult:
    findings: List[Finding]          # active (unsuppressed) findings
    suppressed: List[Finding]        # findings waived by a live suppression
    pass_ids: List[str]

    @property
    def clean(self) -> bool:
        return not self.findings


def run_passes(project: Optional[Project] = None,
               ids: Optional[Sequence[str]] = None) -> RunResult:
    """Run the selected passes (default: all), apply suppressions, then
    append ``unused-suppression`` findings for stale or justification-less
    waivers of the selected passes."""
    project = project or get_project()
    registry = all_passes()
    if ids is None:
        selected = [p for p in registry.values()]
    else:
        unknown = [i for i in ids if i not in registry]
        if unknown:
            raise KeyError(f"unknown pass id(s): {', '.join(unknown)}; "
                           f"known: {', '.join(sorted(registry))}")
        selected = [registry[i] for i in ids]
    active: List[Finding] = []
    suppressed: List[Finding] = []
    seen: Set[Tuple[str, int, str, str]] = set()
    for p in selected:
        for f in p.run(project):
            key = (f.file, f.line, f.pass_id, f.message)
            if key in seen:
                continue
            seen.add(key)
            try:
                src = project.source(f.file)
            except OSError:
                src = None
            if src is not None and src.suppresses(f):
                suppressed.append(f)
            else:
                active.append(f)
    selected_ids = {p.id for p in selected}
    active.extend(_suppression_hygiene(project, selected_ids))
    active.sort(key=lambda f: (f.rel(), f.line, f.pass_id))
    return RunResult(active, suppressed, [p.id for p in selected])


def _suppression_hygiene(project: Project, selected_ids: Set[str]
                         ) -> List[Finding]:
    """The waiver ledger must stay honest: every suppression names known
    passes, carries a justification, and still matches a real finding."""
    known = set(all_passes()) | {UNUSED_SUPPRESSION_ID}
    out: List[Finding] = []
    for path in project.all_files():
        src = project.source(path)
        for s in src.suppressions:
            bogus = [i for i in s.pass_ids if i not in known]
            if bogus:
                out.append(Finding(
                    path, s.line, UNUSED_SUPPRESSION_ID,
                    f"suppression names unknown pass(es) "
                    f"{', '.join(bogus)}",
                    "use ids from `python -m analytics_zoo_tpu.lint "
                    "--list`"))
                continue
            if not s.justification:
                out.append(Finding(
                    path, s.line, UNUSED_SUPPRESSION_ID,
                    f"suppression for {', '.join(s.pass_ids)} has no "
                    f"justification",
                    "append ' — <why this waiver is sound>'"))
                continue
            if not s.used and set(s.pass_ids) <= selected_ids:
                out.append(Finding(
                    path, s.line, UNUSED_SUPPRESSION_ID,
                    f"unused suppression for {', '.join(s.pass_ids)} — no "
                    f"finding matches this waiver anymore",
                    "delete the stale comment"))
    return out
