"""zoolint: the repo's unified static-analysis framework.

One shared parse pass (:class:`Project`), a plugin registry of
:class:`LintPass` checkers, per-line ``# zoolint: disable=<pass>``
suppressions with an unused-waiver check, and text/GitHub-annotation
output. Run it with ``python -m analytics_zoo_tpu.lint`` (or the
``zoolint`` console script); see ``docs/linting.md``.
"""
from .core import (Finding, LintPass, Project, RunResult,  # noqa: F401
                   UNUSED_SUPPRESSION_ID, all_passes, get_project,
                   register_pass, run_passes)

__all__ = ["Finding", "LintPass", "Project", "RunResult",
           "UNUSED_SUPPRESSION_ID", "all_passes", "get_project",
           "register_pass", "run_passes"]
