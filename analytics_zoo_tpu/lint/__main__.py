from .runner import main

raise SystemExit(main())
