"""zoolint CLI: ``python -m analytics_zoo_tpu.lint`` / ``zoolint``.

Exit status is 0 when every selected pass is clean (including the
built-in unused-suppression hygiene check), 1 when there are findings,
2 on usage errors. ``--format github`` emits ``::error`` workflow
annotations so CI surfaces findings on the touched lines.
"""
from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from .core import all_passes, get_project, run_passes


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="zoolint",
        description="unified static analysis for analytics_zoo_tpu")
    p.add_argument("--format", choices=("text", "github"), default="text",
                   help="finding output style (default: text)")
    p.add_argument("--pass", dest="passes", action="append", metavar="ID",
                   help="run only this pass (repeatable; default: all)")
    p.add_argument("--list", action="store_true",
                   help="list registered passes and exit")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="suppress the summary line")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    registry = all_passes()
    if args.list:
        width = max(len(i) for i in registry)
        for pid in sorted(registry):
            print(f"{pid:<{width}}  {registry[pid].title}")
        return 0
    try:
        result = run_passes(get_project(), ids=args.passes)
    except KeyError as e:
        print(f"zoolint: {e.args[0]}", file=sys.stderr)
        return 2
    for f in result.findings:
        print(f.github() if args.format == "github" else f.text())
    if not args.quiet:
        n = len(result.findings)
        sup = len(result.suppressed)
        ran = ", ".join(result.pass_ids)
        status = "clean" if n == 0 else f"{n} finding(s)"
        print(f"zoolint: {status} [{ran}]"
              + (f" ({sup} suppressed)" if sup else ""),
              file=sys.stderr)
    return 0 if result.clean else 1


if __name__ == "__main__":
    raise SystemExit(main())
