"""Streaming ingest: a bounded-buffer FeatureSet over a queue backend.

``QueueFeatureSet`` turns a :class:`~analytics_zoo_tpu.serving.queues.
QueueBackend` (FileQueue / RedisQueue) into a dataset the Estimator can
train on forever.  The design separates two concerns:

* **Ingest** — a daemon thread claims records from the queue into a
  small in-memory pending list and *releases* them to an append-only
  JSONL journal when the watermark passes (``wall_clock() - record_ts
  >= watermark_s``) or the bounded buffer fills.  The thread stops
  claiming while the buffer (journaled-but-unconsumed + pending) is at
  ``ingest.buffer_records``, so backpressure is visible to producers as
  queue depth.

* **Consumption** — batches are a pure function of journal order.  The
  dataset keeps a single committed cursor ``(records, bytes, crc)``
  into the journal; ``train_iterator`` reads forward from it and
  commits it once per epoch window, just before handing out the
  window's last batch.  ``data_state()`` serializes the cursor —
  queue offset plus a rolling CRC-32 buffer digest — and
  ``set_data_state`` verifies the digest against the journal before
  rewinding, so a killed consumer resumes bit-reproducibly: the
  journal replays in the identical order the first run saw.

The journal is the durability and reproducibility boundary.  A crash
between queue claim and journal append can drop (FileQueue) or
redeliver (RedisQueue pending-entry reclaim) the claimed-but-unreleased
records — the same window any consumer with a local pre-commit buffer
has — but everything past the journal replays exactly.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from collections import deque
from typing import Any, Callable, Dict, Iterator, Optional, Sequence, Tuple

import numpy as np

from ..common import file_io
from ..common import metrics as zoo_metrics
from ..common.config import global_config
from ..common.utils import wall_clock
from ..feature.featureset import HostDataset
from ..serving.queues import QueueBackend, make_queue

_M_RECORDS = zoo_metrics.counter(
    "ingest.records_total",
    "Records released from the queue into the streaming journal "
    "(past the watermark or on buffer-full force release).")
_M_DEPTH = zoo_metrics.gauge(
    "ingest.buffer_depth",
    "Fill level of the bounded ingest buffer: journaled-but-unconsumed "
    "plus claimed-but-unreleased records.")
_M_LAG = zoo_metrics.gauge(
    "ingest.watermark_lag_seconds",
    "Ingest-time age of the newest record released past the watermark "
    "(how far behind event time the journal is running).")


def _default_record_fn(rec: Dict[str, Any]) -> Tuple[Any, Any]:
    """Queue payload → ``(x, y)`` training record.  JSON numbers decode
    as float64/int64; narrow to the f32/i32 the accelerators use so a
    journal replay is dtype-identical to live ingest."""
    def narrow(v):
        a = np.asarray(v)
        if a.dtype == np.float64:
            return a.astype(np.float32)
        if a.dtype == np.int64:
            return a.astype(np.int32)
        return a
    x = rec["x"]
    x = tuple(narrow(v) for v in x) if isinstance(x, (list, tuple)) and \
        x and isinstance(x[0], (list, tuple)) else narrow(x)
    y = narrow(rec["y"]) if "y" in rec else None
    return x, y


class QueueFeatureSet(HostDataset):
    """Bounded-buffer streaming dataset over a queue backend.

    ``epoch_records`` defines the *epoch window*: the Estimator sees a
    dataset of that size and runs its normal epoch loop; each "epoch"
    consumes the next ``epoch_records`` records off the journal.  The
    committed cursor only ever advances at window boundaries, so the
    Estimator's epoch-start ``data_state()`` capture and mid-epoch
    ``skip_batches`` replay compose with it unchanged — and throwaway
    iterators (the sample draw the Estimator uses for model init) never
    lose records, because an uncommitted read position dies with its
    iterator.
    """

    def __init__(self, backend, journal_dir: str, epoch_records: int,
                 buffer_records: Optional[int] = None,
                 watermark_s: Optional[float] = None,
                 poll_interval_s: Optional[float] = None,
                 record_fn: Optional[Callable[[Dict[str, Any]],
                                              Tuple[Any, Any]]] = None,
                 claim_chunk: int = 64):
        cfg = global_config()
        if isinstance(backend, str):
            backend = make_queue(backend)
        if not isinstance(backend, QueueBackend):
            raise TypeError("backend must be a QueueBackend or src string, "
                            "got %r" % (backend,))
        if epoch_records < 1:
            raise ValueError("epoch_records must be >= 1")
        self.backend = backend
        self.journal_dir = journal_dir
        self.journal_path = os.path.join(journal_dir, "journal.jsonl")
        self.epoch_records = int(epoch_records)
        self.buffer_records = int(
            buffer_records if buffer_records is not None
            else cfg.get("ingest.buffer_records"))
        self.watermark_s = float(
            watermark_s if watermark_s is not None
            else cfg.get("ingest.watermark_s"))
        self.poll_interval_s = float(
            poll_interval_s if poll_interval_s is not None
            else cfg.get("ingest.poll_interval_s"))
        self.record_fn = record_fn or _default_record_fn
        self.claim_chunk = max(1, int(claim_chunk))

        # FeatureSet contract surface.
        self.size = self.epoch_records
        self.num_slices = 1
        self.shuffle = False  # order is journal order, by construction

        file_io.makedirs(journal_dir)
        # Resume-aware append position: scan whatever journal already
        # exists so a restarted ingest thread appends, never truncates.
        self._append_lock = threading.Lock()
        self._journal_records = 0
        self._journal_bytes = 0
        if os.path.exists(self.journal_path):
            with open(self.journal_path, "rb") as f:
                data = f.read()
            # Ignore a torn trailing line (crash mid-append): appends
            # resume at the last newline so the journal stays parseable.
            keep = data.rfind(b"\n") + 1
            if keep < len(data):
                with open(self.journal_path, "r+b") as f:
                    f.truncate(keep)
                data = data[:keep]
            self._journal_records = data.count(b"\n")
            self._journal_bytes = len(data)

        # Committed consumption cursor (the resume point).
        self._cursor = {"records": 0, "bytes": 0, "crc": 0}
        # High-water mark of records actually DELIVERED to a consumer —
        # distinct from the cursor, which only advances at epoch
        # boundaries: buffer accounting off the cursor would wedge
        # (ingest stops claiming mid-epoch while the consumer starves).
        self._consumed_hwm = 0

        self._closed = False
        self._ingest_thread: Optional[threading.Thread] = None
        self._ingest_error: Optional[BaseException] = None

    # -- contract -------------------------------------------------------------

    def num_batches(self, batch_size: int, drop_remainder: bool = True) -> int:
        if drop_remainder:
            return self.size // batch_size
        return (self.size + batch_size - 1) // batch_size

    def slice_boundaries(self, batch_size: int) -> Sequence[int]:
        return [self.num_batches(batch_size)]

    # -- data_state: queue offset + buffer digest -----------------------------

    def data_state(self) -> str:
        """Committed cursor as JSON: record/byte offsets into the journal
        plus the CRC-32 of every consumed byte (the buffer digest)."""
        return json.dumps(dict(self._cursor))

    def set_data_state(self, state: str) -> None:
        """Rewind to a saved cursor, verifying the journal prefix still
        hashes to the saved digest — a resume against a journal that
        diverged (wrong dir, lost records) fails loudly, not silently."""
        pos = json.loads(state)
        cur = {"records": int(pos["records"]), "bytes": int(pos["bytes"]),
               "crc": int(pos["crc"])}
        if cur["bytes"]:
            try:
                with open(self.journal_path, "rb") as f:
                    prefix = f.read(cur["bytes"])
            except FileNotFoundError:
                prefix = b""
            if len(prefix) < cur["bytes"]:
                raise ValueError(
                    "journal %s is shorter (%d bytes) than the saved "
                    "cursor (%d bytes): cannot resume" %
                    (self.journal_path, len(prefix), cur["bytes"]))
            crc = zlib.crc32(prefix)
            if crc != cur["crc"]:
                raise ValueError(
                    "journal digest mismatch at byte %d: saved crc=%d, "
                    "journal crc=%d — the journal is not the one this "
                    "data_state was taken against" %
                    (cur["bytes"], cur["crc"], crc))
        self._cursor = cur
        if cur["records"] > self._consumed_hwm:
            self._consumed_hwm = cur["records"]

    # -- ingest side ----------------------------------------------------------

    def _ensure_ingest(self) -> None:
        if self._closed:
            raise RuntimeError("QueueFeatureSet is closed")
        if self._ingest_thread is None or not self._ingest_thread.is_alive():
            self._ingest_thread = threading.Thread(
                target=self._ingest_loop, daemon=True, name="queue-ingest")
            self._ingest_thread.start()

    def _backlog(self) -> int:
        return self._journal_records - max(self._consumed_hwm,
                                           self._cursor["records"])

    def _append_journal(self, recs) -> None:
        payload = b"".join(
            json.dumps(r, sort_keys=True).encode() + b"\n" for r in recs)
        with self._append_lock:
            with open(self.journal_path, "ab") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            self._journal_records += len(recs)
            self._journal_bytes += len(payload)
        _M_RECORDS.inc(len(recs))

    def _ingest_loop(self) -> None:
        pending: "deque" = deque()  # (event_ts, record)
        try:
            while not self._closed:
                free = self.buffer_records - self._backlog() - len(pending)
                claimed = []
                if free > 0:
                    claimed = self.backend.claim_batch(
                        min(free, self.claim_chunk))
                    now = wall_clock()
                    for _uri, rec in claimed:
                        pending.append((float(rec.get("ts", now)), rec))
                # Release: watermark passed, or buffer full forces the
                # oldest out so ingest never deadlocks on a quiet stream.
                now = wall_clock()
                full = (self._backlog() + len(pending)) \
                    >= self.buffer_records
                released = []
                while pending and (full or
                                   now - pending[0][0] >= self.watermark_s):
                    released.append(pending.popleft()[1])
                if released:
                    self._append_journal(released)
                    _M_LAG.set(max(0.0, now - float(
                        released[-1].get("ts", now))))
                _M_DEPTH.set(self._backlog() + len(pending))
                if not claimed and not released:
                    time.sleep(self.poll_interval_s)
        except BaseException as e:  # surfaced by the consumer side
            self._ingest_error = e

    # -- consumption side -----------------------------------------------------

    def _read_records(self, pos: Dict[str, int], n: int):
        """Read ``n`` journal records starting at ``pos``, blocking on
        journal growth.  Advances ``pos`` in place (records/bytes/crc)."""
        out = []
        f = None
        try:
            while len(out) < n:
                if self._ingest_error is not None:
                    raise self._ingest_error
                if self._closed:
                    raise RuntimeError("QueueFeatureSet closed mid-read")
                if f is None:
                    if not os.path.exists(self.journal_path):
                        time.sleep(self.poll_interval_s)
                        continue
                    f = open(self.journal_path, "rb")
                    f.seek(pos["bytes"])
                line = f.readline()
                if not line.endswith(b"\n"):
                    # Torn tail or end of journal: rewind and wait for
                    # the ingest thread to finish the line.
                    f.seek(pos["bytes"])
                    time.sleep(self.poll_interval_s)
                    continue
                pos["bytes"] += len(line)
                pos["crc"] = zlib.crc32(line, pos["crc"])
                pos["records"] += 1
                if pos["records"] > self._consumed_hwm:
                    self._consumed_hwm = pos["records"]
                out.append(json.loads(line))
                _M_DEPTH.set(max(0, self._backlog()))
        finally:
            if f is not None:
                f.close()
        return out

    def _assemble(self, recs) -> Tuple[Any, Any]:
        from ..feature.preprocessing import stack_records
        pairs = [self.record_fn(r) for r in recs]
        xs = stack_records([p[0] for p in pairs])
        ys = None
        if pairs[0][1] is not None:
            ys = stack_records([p[1] for p in pairs])
        return xs, ys

    def train_iterator(self, batch_size: int, skip_batches: int = 0
                       ) -> Iterator[Tuple[Any, Any]]:
        """One epoch window per call: yields ``epoch_records //
        batch_size`` batches read forward from the committed cursor,
        then stops.  The cursor commits just before the last batch is
        handed out, so by the time the train loop observes the epoch
        end, ``data_state()`` is the post-epoch resume point — and a
        finite iterator means an eager prefetcher can never read past
        the window into records the next epoch's iterator must see."""
        self._ensure_ingest()
        per_epoch = self.num_batches(batch_size)
        if per_epoch < 2:
            raise ValueError(
                "epoch_records (%d) must cover at least 2 batches of %d: "
                "the Estimator draws one throwaway batch for model init "
                "and a 1-batch window would commit the cursor on it" %
                (self.epoch_records, batch_size))
        pos = dict(self._cursor)
        for i in range(per_epoch):
            batch = self._assemble(self._read_records(pos, batch_size))
            if i == per_epoch - 1:
                self._cursor = dict(pos)
            if i >= skip_batches:
                yield batch

    def eval_iterator(self, batch_size: int, pad_remainder: bool = False
                      ) -> Iterator[Tuple[Any, Any, int]]:
        """Evaluates on the most recent full window *behind* the cursor
        (the records just trained on) without moving it — online eval is
        a rearview mirror, not a second consumer of the stream."""
        start_rec = max(0, self._cursor["records"] - self.epoch_records)
        pos = {"records": 0, "bytes": 0, "crc": 0}
        if start_rec:
            self._read_records(pos, start_rec)  # cheap scan to the window
        avail = min(self.epoch_records,
                    self._cursor["records"] - start_rec)
        done = 0
        while done + batch_size <= avail:
            recs = self._read_records(pos, batch_size)
            x, y = self._assemble(recs)
            yield x, y, batch_size
            done += batch_size
        rem = avail - done
        if rem:
            recs = self._read_records(pos, rem)
            if pad_remainder:
                recs = recs + [recs[-1]] * (batch_size - rem)
            x, y = self._assemble(recs)
            yield x, y, rem

    def close(self) -> None:
        self._closed = True
        t = self._ingest_thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)
