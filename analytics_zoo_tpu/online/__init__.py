"""Online learning loop: streaming ingest → continual training →
trainer→server promotion.  See docs/online.md."""

from .promote import (PromotionError, Promoter, RollbackError,
                      export_servable)
from .stream import QueueFeatureSet

__all__ = ["Promoter", "PromotionError", "QueueFeatureSet",
           "RollbackError", "export_servable"]
